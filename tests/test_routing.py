"""ReplicaRouter unit tests (ISSUE 17): pool-aware (fair-share-aware)
scoring, quarantine-driven failover, scrape-failure degradation, and
live membership — all HTTP-free via the scrape-absorb seam."""

import time

import pytest

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query.routing import ReplicaRouter, RoutedYtClient


def _payload(pools, hold=0.05, rung=0):
    return {"gateways": [{"admission": {
        "hold_ewma": hold,
        "brownout": {"rung": rung},
        "pools": {name: {"waiting": w, "in_flight": f, "fair_slots": s}
                  for name, (w, f, s) in pools.items()}}}]}


def _router(n=2):
    router = ReplicaRouter(
        [(f"r{i}", f"r{i}", f"m{i}") for i in range(n)],
        scrape_period=999.0, penalty_seconds=0.05)
    return router, router.replicas()


def test_pool_aware_pick_ignores_other_pools_backlog():
    """A greedy tenant's 500-deep backlog on one replica must not blind
    the router for OTHER pools: prod routes by prod's own queue."""
    router, (r0, r1) = _router()
    router._absorb(r0, _payload({"prod": (0, 0, 1.0),
                                 "batch": (500, 1, 1.0)}))
    router._absorb(r1, _payload({"prod": (2, 1, 1.0),
                                 "batch": (0, 0, 1.0)}))
    # prod: r0 has an empty prod queue behind the batch storm; r1 has
    # two prod waiters on one fair slot.
    assert router.pick(pool="prod").name == "r0"
    assert router.pick(pool="batch").name == "r1"
    # Pool-less picks fall back to the global queue: r1 looks emptier.
    assert router.pick().name == "r1"


def test_pool_latency_ewma_is_isolated_per_pool():
    """Batch's multi-second queue waits must not poison the latency
    estimate the router uses for prod."""
    router, (r0, r1) = _router()
    for r in (r0, r1):
        router._absorb(r, _payload({"prod": (0, 0, 1.0)}))
    # Same replica serves batch terribly and prod quickly.
    router.report(r0, latency=5.0, pool="batch")
    router.report(r0, latency=0.01, pool="prod")
    router.report(r1, latency=0.5, pool="prod")
    assert router.pick(pool="prod").name == "r0"
    assert r0.pool_latency["batch"] > r0.pool_latency["prod"]


def test_report_error_quarantines_then_recovers():
    router, (r0, r1) = _router()
    for r in (r0, r1):
        router._absorb(r, _payload({"prod": (0, 0, 1.0)}))
    router.report(r0, error=True)
    assert router.failovers_n == 1
    for _ in range(4):                   # quarantined: never picked
        assert router.pick(pool="prod").name == "r1"
    time.sleep(0.06)                     # penalty_seconds elapsed
    names = {router.pick(pool="prod").name for _ in range(4)}
    assert "r0" in names


def test_scrape_failure_degrades_to_unknown_penalty():
    router, (r0, r1) = _router()
    router._absorb(r0, _payload({"prod": (50, 1, 1.0)}))
    # r1 was never scraped: UNKNOWN outweighs even a 50-deep queue.
    assert not r1.scrape_ok
    assert router.pick(pool="prod").name == "r0"


def test_brownout_rung_penalizes_replica():
    router, (r0, r1) = _router()
    router._absorb(r0, _payload({"prod": (0, 0, 1.0)}, rung=2))
    router._absorb(r1, _payload({"prod": (3, 1, 1.0)}))
    # A shedding replica is routed around while any alternative exists.
    assert router.pick(pool="prod").name == "r1"


def test_pick_with_no_replicas_raises_peer_unavailable():
    router = ReplicaRouter([], scrape_period=999.0)
    with pytest.raises(YtError) as err:
        router.pick()
    assert err.value.code == EErrorCode.PeerUnavailable


class _FakeClient:
    def __init__(self, dead=False):
        self.dead = dead
        self.calls = 0

    def select_rows(self, query, **kwargs):
        self.calls += 1
        if self.dead:
            raise YtError("replica down",
                          code=EErrorCode.TransportError)
        return ["rows"]


def test_routed_client_fails_over_once_and_quarantines():
    router, (r0, r1) = _router()
    # r0 is strictly more attractive — and dead.
    router._absorb(r0, _payload({"prod": (0, 0, 1.0)}))
    router._absorb(r1, _payload({"prod": (5, 1, 1.0)}))
    dead, alive = _FakeClient(dead=True), _FakeClient()
    routed = RoutedYtClient(router, {"r0": dead, "r1": alive})
    assert routed.select_rows("q", pool="prod") == ["rows"]
    assert dead.calls == 1 and alive.calls == 1
    assert router.failovers_n == 1
    # The corpse is quarantined: the next call goes straight to r1.
    assert routed.select_rows("q", pool="prod") == ["rows"]
    assert dead.calls == 1 and alive.calls == 2


def test_routed_client_application_errors_pass_through():
    """Only transport-class failures fail over; an application error
    (bad query) must surface, not burn a second replica."""
    router, (r0, r1) = _router()
    router._absorb(r0, _payload({"prod": (0, 0, 1.0)}))

    class _BadQuery(_FakeClient):
        def select_rows(self, query, **kwargs):
            self.calls += 1
            raise YtError("syntax error",
                          code=EErrorCode.QueryParseError)

    bad, other = _BadQuery(), _FakeClient()
    routed = RoutedYtClient(router, {"r0": bad, "r1": other})
    with pytest.raises(YtError) as err:
        routed.select_rows("q", pool="prod")
    assert err.value.code == EErrorCode.QueryParseError
    assert bad.calls + other.calls == 1
    assert router.failovers_n == 0


def test_add_replica_joins_live():
    router, (r0,) = _router(n=1)
    router._absorb(r0, _payload({"prod": (9, 1, 1.0)}))
    clients = {"r0": _FakeClient()}
    routed = RoutedYtClient(router, clients)
    joiner = _FakeClient()
    routed.add_replica(("r9", "r9", "m9"), joiner)
    names = {r.name for r in router.replicas()}
    assert names == {"r0", "r9"}
    # The joiner starts un-scraped (UNKNOWN penalty) — picks stay on
    # the known replica until a scrape reports the newcomer's load.
    assert router.pick(pool="prod").name == "r0"
