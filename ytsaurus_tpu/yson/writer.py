"""YSON writer: text and binary formats.

Ref: yt/yt/core/yson/writer.h.  Binary markers: 0x01 string (varint byte
length), 0x02 int64 (zigzag varint), 0x03 double (8 LE bytes), 0x04 false,
0x05 true, 0x06 uint64 (varint).
"""

from __future__ import annotations

import math
import struct

from ytsaurus_tpu.yson.types import (
    YsonBoolean,
    YsonEntity,
    YsonUint64,
    get_attributes,
)

_STRING_MARKER = b"\x01"
_INT64_MARKER = b"\x02"
_DOUBLE_MARKER = b"\x03"
_FALSE_MARKER = b"\x04"
_TRUE_MARKER = b"\x05"
_UINT64_MARKER = b"\x06"

_BARE_OK = set(
    b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-%./")


from ytsaurus_tpu.utils.varint import write_varint_u as _write_varint  # noqa: E402


def zigzag_encode(value: int) -> int:
    return (value << 1) if value >= 0 else ((-value) << 1) - 1


def zigzag_decode(value: int) -> int:
    return (value >> 1) if (value & 1) == 0 else -((value + 1) >> 1)


class _Writer:
    def __init__(self, binary: bool, indent: int | None = None):
        self.binary = binary
        self.out = bytearray()
        self.indent = indent

    # -- scalars ---------------------------------------------------------------

    def write(self, value):
        attrs = get_attributes(value)
        if attrs:
            self.out += b"<"
            self._write_map_body(attrs)
            self.out += b">"
        if value is None or isinstance(value, YsonEntity):
            self.out += b"#"
        elif isinstance(value, (bool, YsonBoolean)):
            if self.binary:
                self.out += _TRUE_MARKER if value else _FALSE_MARKER
            else:
                self.out += b"%true" if value else b"%false"
        elif isinstance(value, YsonUint64):
            if self.binary:
                self.out += _UINT64_MARKER
                _write_varint(self.out, int(value))
            else:
                self.out += str(int(value)).encode() + b"u"
        elif isinstance(value, int):
            if not (-(2**63) <= value < 2**64):
                raise ValueError(f"Integer out of YSON range: {value}")
            if value >= 2**63:
                self.write(YsonUint64(value))
            elif self.binary:
                self.out += _INT64_MARKER
                _write_varint(self.out, zigzag_encode(value))
            else:
                self.out += str(value).encode()
        elif isinstance(value, float):
            if self.binary:
                self.out += _DOUBLE_MARKER + struct.pack("<d", value)
            elif math.isnan(value):
                self.out += b"%nan"
            elif math.isinf(value):
                self.out += b"%inf" if value > 0 else b"%-inf"
            else:
                text = repr(value).encode()
                if b"." not in text and b"e" not in text and b"E" not in text \
                        and b"n" not in text:
                    text += b"."
                self.out += text
        elif isinstance(value, (bytes, str)):
            self._write_string(value)
        elif isinstance(value, dict):
            self.out += b"{"
            self._write_map_body(value)
            self.out += b"}"
        elif isinstance(value, (list, tuple)):
            self.out += b"["
            for i, item in enumerate(value):
                if i:
                    self.out += b";"
                self.write(item)
            self.out += b"]"
        else:
            raise TypeError(f"Cannot serialize {type(value).__name__} to YSON")

    def _write_string(self, value) -> None:
        raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        if self.binary:
            self.out += _STRING_MARKER
            _write_varint(self.out, len(raw))
            self.out += raw
        elif raw and all(b in _BARE_OK for b in raw) and \
                not raw[0:1].isdigit() and raw not in (b"%true", b"%false") \
                and not raw.startswith(b"%") and not raw.startswith(b"-"):
            self.out += raw
        else:
            self.out += b'"'
            for b in raw:
                c = bytes([b])
                if c == b'"':
                    self.out += b'\\"'
                elif c == b"\\":
                    self.out += b"\\\\"
                elif 32 <= b < 127:
                    self.out += c
                elif c == b"\n":
                    self.out += b"\\n"
                elif c == b"\t":
                    self.out += b"\\t"
                elif c == b"\r":
                    self.out += b"\\r"
                else:
                    self.out += b"\\x%02x" % b
            self.out += b'"'

    def _write_map_body(self, mapping: dict) -> None:
        first = True
        for key, item in mapping.items():
            if not first:
                self.out += b";"
            first = False
            self._write_string(key)
            self.out += b"="
            self.write(item)


def dumps(value, binary: bool = False) -> bytes:
    writer = _Writer(binary=binary)
    writer.write(value)
    return bytes(writer.out)
