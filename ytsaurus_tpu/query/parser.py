"""QL parser: token stream → QueryAst.

Hand-written Pratt parser over the same grammar surface as the reference
(library/query/base/parser.ypp): optional SELECT list, FROM source, LEFT/inner
JOIN ... USING/ON, WHERE, GROUP BY [WITH TOTALS], HAVING, ORDER BY ASC/DESC,
OFFSET, LIMIT; the full expression language incl. IN / BETWEEN / TRANSFORM /
CASE / LIKE and tuple forms.
"""

from __future__ import annotations

from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ast
from ytsaurus_tpu.query.lexer import Token, TokenKind, tokenize

# Binary operator precedence (higher binds tighter).
_PRECEDENCE = {
    "or": 1,
    "and": 2,
    # NOT handled as prefix at level 3
    "=": 4, "!=": 4, "<>": 4, "<": 4, "<=": 4, ">": 4, ">=": 4,
    "|": 5,
    "^": 6,
    "&": 7,
    "<<": 8, ">>": 8,
    "+": 9, "-": 9,
    "*": 10, "/": 10, "%": 10,
}

_COMPARISON_LEVEL = 4

# NEAREST(col, q, k [, metric]) desugars to ORDER BY <fn>(col, q) LIMIT k.
# Similarity metrics (dot) sort descending; distances ascending.
_NEAREST_METRICS = {
    "l2": ("l2_distance", False),
    "euclidean": ("l2_distance", False),
    "cosine": ("cosine_distance", False),
    "dot": ("dot_product", True),
    "inner": ("dot_product", True),
}


class _Parser:
    def __init__(self, source: str):
        self.source = source
        self.tokens = tokenize(source)
        self.pos = 0
        self._placeholders = 0

    # --- token helpers --------------------------------------------------------

    @property
    def cur(self) -> Token:
        return self.tokens[self.pos]

    def advance(self) -> Token:
        tok = self.cur
        self.pos += 1
        return tok

    def error(self, message: str) -> YtError:
        tok = self.cur
        return YtError(f"{message} (near position {tok.pos} in {self.source!r})",
                       code=EErrorCode.QueryParseError)

    def expect_op(self, op: str) -> None:
        if not self.cur.is_op(op):
            raise self.error(f"Expected {op!r}")
        self.advance()

    def expect_keyword(self, kw: str) -> None:
        if not self.cur.is_keyword(kw):
            raise self.error(f"Expected {kw.upper()}")
        self.advance()

    def accept_op(self, op: str) -> bool:
        if self.cur.is_op(op):
            self.advance()
            return True
        return False

    def accept_keyword(self, *kws: str) -> Optional[str]:
        if self.cur.is_keyword(*kws):
            return self.advance().value
        return None

    # OVER / PARTITION / ROWS / UNBOUNDED / PRECEDING / FOLLOWING / CURRENT /
    # ROW are contextual words (matched case-insensitively where the window
    # grammar expects them) rather than reserved keywords, so existing
    # queries may keep using them as column names.

    def _at_word(self, *names: str) -> bool:
        tok = self.cur
        return tok.kind in (TokenKind.IDENT, TokenKind.KEYWORD) and \
            isinstance(tok.value, str) and tok.value.lower() in names

    def accept_word(self, *names: str) -> Optional[str]:
        if self._at_word(*names):
            return self.advance().value.lower()
        return None

    def expect_word(self, name: str) -> None:
        if self.accept_word(name) is None:
            raise self.error(f"Expected {name.upper()}")

    # --- expressions ----------------------------------------------------------

    def parse_expression(self, min_prec: int = 0) -> ast.Expr:
        lhs = self.parse_prefix(min_prec)
        while True:
            tok = self.cur
            op = None
            if tok.kind is TokenKind.OP and tok.value in _PRECEDENCE:
                op = tok.value
            elif tok.is_keyword("and", "or"):
                op = tok.value
            elif tok.is_keyword("in", "between", "like", "ilike", "rlike",
                                "regexp", "not"):
                if _COMPARISON_LEVEL < min_prec:
                    break
                lhs = self.parse_predicate_suffix(lhs)
                continue   # let the main loop handle trailing AND/OR etc.
            if op is None:
                break
            prec = _PRECEDENCE[op]
            if prec < min_prec:
                break
            self.advance()
            if op in ("=", "!=", "<>", "<", "<=", ">", ">="):
                rhs = self.parse_expression(prec + 1)
                lhs = ast.BinaryOp("!=" if op == "<>" else op, lhs, rhs)
            else:
                rhs = self.parse_expression(prec + 1)
                lhs = ast.BinaryOp(op, lhs, rhs)
        return lhs

    def parse_predicate_suffix(self, lhs: ast.Expr) -> ast.Expr:
        negated = self.accept_keyword("not") is not None
        if self.accept_keyword("in"):
            values = self.parse_literal_tuple_list()
            operands = lhs.operands if isinstance(lhs, _TupleExpr) else (lhs,)
            expr: ast.Expr = ast.InExpr(operands=operands, values=values)
            if negated:
                expr = ast.UnaryOp("not", expr)
            return expr
        if self.accept_keyword("between"):
            operands = lhs.operands if isinstance(lhs, _TupleExpr) else (lhs,)
            if self.cur.is_op("(") and len(operands) > 1:
                # Tuple form: (a,b) BETWEEN ((l...) AND (u...), ...)
                ranges = self.parse_between_range_list()
            else:
                lower = self.parse_literal_tuple(single_ok=True)
                self.expect_keyword("and")
                upper = self.parse_literal_tuple(single_ok=True)
                ranges = ((lower, upper),)
            return ast.BetweenExpr(operands=operands, ranges=ranges,
                                   negated=negated)
        if self.cur.is_keyword("like", "ilike", "rlike", "regexp"):
            kind = self.advance().value
            pattern = self.parse_expression(_COMPARISON_LEVEL + 1)
            escape = None
            if self.accept_keyword("escape"):
                escape = self.parse_expression(_COMPARISON_LEVEL + 1)
            expr = ast.LikeExpr(text=lhs, pattern=pattern, negated=negated,
                                case_insensitive=(kind == "ilike"),
                                escape=escape)
            if kind in ("rlike", "regexp"):
                expr = ast.FunctionCall(
                    "regex_full_match", (pattern, lhs))
                if negated:
                    expr = ast.UnaryOp("not", expr)
            return expr
        raise self.error("Expected IN, BETWEEN or LIKE after NOT")

    def parse_prefix(self, min_prec: int = 0) -> ast.Expr:
        tok = self.cur
        if tok.is_op("-"):
            self.advance()
            operand = self.parse_expression(11)
            if isinstance(operand, ast.Literal) and isinstance(operand.value, (int, float)) \
                    and not isinstance(operand.value, bool):
                return ast.Literal(-operand.value, is_uint=False)
            return ast.UnaryOp("-", operand)
        if tok.is_op("+"):
            self.advance()
            return self.parse_expression(11)
        if tok.is_op("~"):
            self.advance()
            return ast.UnaryOp("~", self.parse_expression(11))
        if tok.is_keyword("not"):
            self.advance()
            return ast.UnaryOp("not", self.parse_expression(3))
        if tok.is_op("("):
            self.advance()
            exprs = [self.parse_expression()]
            while self.accept_op(","):
                exprs.append(self.parse_expression())
            self.expect_op(")")
            if len(exprs) == 1:
                return exprs[0]
            return _TupleExpr(tuple(exprs))
        if tok.kind is TokenKind.INT:
            self.advance()
            return ast.Literal(tok.value)
        if tok.kind is TokenKind.UINT:
            self.advance()
            return ast.Literal(tok.value, is_uint=True)
        if tok.kind is TokenKind.DOUBLE:
            self.advance()
            return ast.Literal(float(tok.value))
        if tok.kind is TokenKind.STRING:
            self.advance()
            return ast.Literal(tok.value)
        if tok.is_keyword("true"):
            self.advance()
            return ast.Literal(True)
        if tok.is_keyword("false"):
            self.advance()
            return ast.Literal(False)
        if tok.is_keyword("null"):
            self.advance()
            return ast.Literal(None)
        if tok.is_op("#"):
            self.advance()
            return ast.Literal(None)
        if tok.is_op("?"):
            self.advance()
            index = self._placeholders
            self._placeholders += 1
            return ast.Placeholder(index)
        if tok.is_keyword("case"):
            return self.parse_case()
        if tok.is_keyword("transform"):
            return self.parse_transform()
        if tok.is_keyword("if"):
            self.advance()
            self.expect_op("(")
            args = [self.parse_expression()]
            while self.accept_op(","):
                args.append(self.parse_expression())
            self.expect_op(")")
            return ast.FunctionCall("if", tuple(args))
        if tok.kind is TokenKind.IDENT:
            self.advance()
            name = tok.value
            # Function call.
            if self.cur.is_op("("):
                self.advance()
                args: list[ast.Expr] = []
                if not self.cur.is_op(")"):
                    # count(*) style
                    if self.cur.is_op("*"):
                        self.advance()
                        args.append(ast.Literal(1))
                    else:
                        args.append(self.parse_expression())
                        while self.accept_op(","):
                            args.append(self.parse_expression())
                self.expect_op(")")
                call = ast.FunctionCall(name.lower(), tuple(args))
                if self._at_word("over") and \
                        self.tokens[self.pos + 1].is_op("("):
                    return self.parse_over(call)
                return call
            # Qualified reference t.col.
            if self.cur.is_op("."):
                self.advance()
                col = self.advance()
                if col.kind is not TokenKind.IDENT:
                    raise self.error("Expected column name after '.'")
                return ast.Reference(name=col.value, table=name)
            return ast.Reference(name=name)
        raise self.error(f"Unexpected token {tok.value!r}")

    def parse_over(self, call: ast.FunctionCall) -> ast.Expr:
        """fn(args) OVER (PARTITION BY e, ... ORDER BY e [ASC|DESC], ...
        [ROWS BETWEEN bound AND bound])."""
        self.expect_word("over")
        self.expect_op("(")
        partition: list[ast.Expr] = []
        if self.accept_word("partition"):
            self.expect_keyword("by")
            partition.append(self.parse_expression())
            while self.accept_op(","):
                partition.append(self.parse_expression())
        order_items: list[ast.OrderItem] = []
        if self.accept_keyword("order"):
            self.expect_keyword("by")
            while True:
                expr = self.parse_expression()
                desc = False
                if self.accept_keyword("desc"):
                    desc = True
                elif self.accept_keyword("asc"):
                    pass
                order_items.append(ast.OrderItem(expr=expr, descending=desc))
                if not self.accept_op(","):
                    break
        frame = None
        if self.accept_word("rows"):
            self.expect_keyword("between")
            lower = self.parse_frame_bound()
            self.expect_keyword("and")
            upper = self.parse_frame_bound()
            frame = (lower, upper)
        self.expect_op(")")
        return ast.WindowExpr(
            function=call.name, args=call.args,
            spec=ast.WindowSpec(partition_by=tuple(partition),
                                order_by=tuple(order_items), frame=frame))

    def parse_frame_bound(self) -> ast.FrameBound:
        if self.accept_word("unbounded"):
            which = self.accept_word("preceding", "following")
            if which is None:
                raise self.error("Expected PRECEDING or FOLLOWING")
            return ast.FrameBound(kind=f"unbounded_{which}")
        if self.accept_word("current"):
            if self.accept_word("row") is None:
                raise self.error("Expected ROW after CURRENT")
            return ast.FrameBound(kind="current_row")
        tok = self.cur
        if tok.kind in (TokenKind.INT, TokenKind.UINT):
            self.advance()
            which = self.accept_word("preceding", "following")
            if which is None:
                raise self.error("Expected PRECEDING or FOLLOWING")
            return ast.FrameBound(kind=which, offset=int(tok.value))
        raise self.error("Expected ROWS frame bound")

    def parse_case(self) -> ast.Expr:
        self.expect_keyword("case")
        operand = None
        if not self.cur.is_keyword("when"):
            operand = self.parse_expression()
        when_then: list[tuple[ast.Expr, ast.Expr]] = []
        while self.accept_keyword("when"):
            cond = self.parse_expression()
            self.expect_keyword("then")
            result = self.parse_expression()
            when_then.append((cond, result))
        default = None
        if self.accept_keyword("else"):
            default = self.parse_expression()
        self.expect_keyword("end")
        if not when_then:
            raise self.error("CASE requires at least one WHEN")
        return ast.CaseExpr(operand=operand, when_then=tuple(when_then),
                            default=default)

    def parse_transform(self) -> ast.Expr:
        self.expect_keyword("transform")
        self.expect_op("(")
        first = self.parse_expression()
        operands = first.operands if isinstance(first, _TupleExpr) else (first,)
        self.expect_op(",")
        from_values = self.parse_literal_tuple_list()
        self.expect_op(",")
        to_list = self.parse_literal_list()
        default = None
        if self.accept_op(","):
            default = self.parse_expression()
        self.expect_op(")")
        return ast.TransformExpr(operands=operands, from_values=from_values,
                                 to_values=to_list, default=default)

    # --- literal tuples for IN/BETWEEN/TRANSFORM ------------------------------

    def parse_literal(self):
        expr = self.parse_expression(_COMPARISON_LEVEL + 1)
        if not isinstance(expr, ast.Literal):
            raise self.error("Expected literal value")
        return expr.value

    def parse_literal_tuple(self, single_ok: bool = False) -> tuple:
        if self.cur.is_op("("):
            self.advance()
            values = [self.parse_literal()]
            while self.accept_op(","):
                values.append(self.parse_literal())
            self.expect_op(")")
            return tuple(values)
        if single_ok:
            return (self.parse_literal(),)
        raise self.error("Expected tuple literal")

    def parse_literal_tuple_list(self) -> tuple[tuple, ...]:
        self.expect_op("(")
        tuples: list[tuple] = []
        first = True
        while not self.cur.is_op(")"):
            if not first:
                self.expect_op(",")
            if self.cur.is_op("("):
                tuples.append(self.parse_literal_tuple())
            else:
                tuples.append((self.parse_literal(),))
            first = False
        self.expect_op(")")
        return tuple(tuples)

    def parse_literal_list(self) -> tuple:
        self.expect_op("(")
        values = []
        first = True
        while not self.cur.is_op(")"):
            if not first:
                self.expect_op(",")
            values.append(self.parse_literal())
            first = False
        self.expect_op(")")
        return tuple(values)

    def parse_between_range_list(self) -> tuple[tuple, ...]:
        self.expect_op("(")
        ranges = []
        first = True
        while not self.cur.is_op(")"):
            if not first:
                self.expect_op(",")
            lower = self.parse_literal_tuple(single_ok=True)
            self.expect_keyword("and")
            upper = self.parse_literal_tuple(single_ok=True)
            ranges.append((lower, upper))
            first = False
        self.expect_op(")")
        return tuple(ranges)

    # --- query ----------------------------------------------------------------

    def parse_query(self) -> ast.QueryAst:
        self.accept_keyword("select")
        # Select list (or *).
        select: Optional[tuple[ast.SelectItem, ...]]
        if self.accept_op("*"):
            select = None
        else:
            items = [self.parse_select_item()]
            while self.accept_op(","):
                items.append(self.parse_select_item())
            select = tuple(items)
        source = None
        source_alias = None
        joins: list[ast.Join] = []
        if self.accept_keyword("from"):
            source = self.parse_table_ref()
            if self.accept_keyword("as"):
                source_alias = self.parse_ident()
        while self.cur.is_keyword("left", "join"):
            is_left = self.accept_keyword("left") is not None
            self.expect_keyword("join")
            table = self.parse_table_ref()
            alias = None
            if self.accept_keyword("as"):
                alias = self.parse_ident()
            elif self.cur.kind is TokenKind.IDENT:
                alias = self.parse_ident()
            using: tuple[str, ...] = ()
            on: tuple[tuple[ast.Expr, ast.Expr], ...] = ()
            if self.accept_keyword("using"):
                names = [self.parse_ident()]
                while self.accept_op(","):
                    names.append(self.parse_ident())
                using = tuple(names)
            elif self.accept_keyword("on"):
                on = self.parse_on_equations()
            joins.append(ast.Join(table=table, alias=alias, is_left=is_left,
                                  using=using, on=on))
        where = None
        if self.accept_keyword("where"):
            where = self.parse_expression()
        # NEAREST(col, q, k [, metric]) — contextual word (not a reserved
        # keyword) so `nearest` stays usable as a column name.  Pure
        # sugar over ORDER BY <metric_fn>(col, q) LIMIT k.
        nearest = None
        if self._at_word("nearest") and \
                self.tokens[self.pos + 1].is_op("("):
            self.advance()
            self.expect_op("(")
            near_col = self.parse_expression()
            self.expect_op(",")
            near_q = self.parse_expression()
            self.expect_op(",")
            ktok = self.advance()
            if ktok.kind not in (TokenKind.INT, TokenKind.UINT):
                raise self.error("NEAREST expects an integer literal k")
            near_k = int(ktok.value)
            metric = "l2"
            if self.accept_op(","):
                mtok = self.advance()
                if mtok.kind not in (TokenKind.IDENT, TokenKind.STRING):
                    raise self.error(
                        "NEAREST metric must be an identifier or string")
                metric = str(mtok.value).lower()
            self.expect_op(")")
            if metric not in _NEAREST_METRICS:
                raise self.error(
                    f"Unknown NEAREST metric {metric!r}; expected one of "
                    f"{sorted(set(_NEAREST_METRICS))}")
            if near_k <= 0:
                raise self.error("NEAREST expects k >= 1")
            nearest = (near_col, near_q, near_k, metric)
        group_by: tuple[ast.SelectItem, ...] = ()
        with_totals = False
        if self.accept_keyword("group"):
            self.expect_keyword("by")
            items = [self.parse_select_item()]
            while self.accept_op(","):
                items.append(self.parse_select_item())
            group_by = tuple(items)
            if self.accept_keyword("with"):
                self.expect_keyword("totals")
                with_totals = True
        having = None
        if self.accept_keyword("having"):
            having = self.parse_expression()
        order_by: list[ast.OrderItem] = []
        if self.accept_keyword("order"):
            self.expect_keyword("by")
            while True:
                expr = self.parse_expression()
                desc = False
                if self.accept_keyword("desc"):
                    desc = True
                elif self.accept_keyword("asc"):
                    pass
                order_by.append(ast.OrderItem(expr=expr, descending=desc))
                if not self.accept_op(","):
                    break
        offset = None
        if self.accept_keyword("offset"):
            tok = self.advance()
            if tok.kind not in (TokenKind.INT, TokenKind.UINT):
                raise self.error("OFFSET expects an integer literal")
            offset = int(tok.value)
        limit = None
        if self.accept_keyword("limit"):
            tok = self.advance()
            if tok.kind not in (TokenKind.INT, TokenKind.UINT):
                raise self.error("LIMIT expects an integer literal")
            limit = int(tok.value)
        if self.cur.kind is not TokenKind.EOF:
            raise self.error(f"Unexpected trailing token {self.cur.value!r}")
        if nearest is not None:
            if order_by or limit is not None or offset is not None:
                raise self.error(
                    "NEAREST cannot be combined with ORDER BY/OFFSET/LIMIT "
                    "(it IS an ORDER BY ... LIMIT)")
            near_col, near_q, near_k, metric = nearest
            fn, desc = _NEAREST_METRICS[metric]
            order_by = [ast.OrderItem(
                expr=ast.FunctionCall(fn, (near_col, near_q)),
                descending=desc)]
            limit = near_k
            # NULL vectors have no distance: NEAREST returns only rows
            # with a stored vector, so the sugar fuses the exclusion
            # into WHERE (where the predicate pass runs BEFORE the
            # distance matmul) rather than leaving NULL order keys to
            # the sort's NULLS-first convention.
            notnull = ast.UnaryOp(
                "not", ast.FunctionCall("is_null", (near_col,)))
            where = notnull if where is None \
                else ast.BinaryOp("and", where, notnull)
        return ast.QueryAst(
            select=select, source=source, source_alias=source_alias,
            joins=tuple(joins), where=where, group_by=group_by,
            with_totals=with_totals, having=having, order_by=tuple(order_by),
            offset=offset, limit=limit)

    def parse_select_item(self) -> ast.SelectItem:
        expr = self.parse_expression()
        alias = None
        if self.accept_keyword("as"):
            alias = self.parse_ident()
        return ast.SelectItem(expr=expr, alias=alias)

    def parse_table_ref(self) -> str:
        tok = self.advance()
        if tok.kind is not TokenKind.IDENT:
            raise self.error("Expected table reference")
        return tok.value

    def parse_ident(self) -> str:
        tok = self.advance()
        if tok.kind is not TokenKind.IDENT:
            raise self.error("Expected identifier")
        return tok.value

    def parse_on_equations(self) -> tuple[tuple[ast.Expr, ast.Expr], ...]:
        equations = []
        while True:
            lhs = self.parse_expression(_PRECEDENCE["and"] + 1)
            if not (isinstance(lhs, ast.BinaryOp) and lhs.op == "="):
                raise self.error("JOIN ON expects conjunctions of equalities")
            equations.append((lhs.lhs, lhs.rhs))
            if not self.accept_keyword("and"):
                break
        return tuple(equations)


class _TupleExpr(ast.Expr):
    """Internal: parenthesized tuple, only valid before IN/BETWEEN/TRANSFORM."""

    def __init__(self, operands: tuple[ast.Expr, ...]):
        self.operands = operands


def parse_query(source: str) -> ast.QueryAst:
    """Parse a full QL query string."""
    return _Parser(source).parse_query()


def parse_expression(source: str) -> ast.Expr:
    """Parse a standalone expression (used for computed columns etc.)."""
    parser = _Parser(source)
    expr = parser.parse_expression()
    if parser.cur.kind is not TokenKind.EOF:
        raise parser.error("Unexpected trailing token")
    return expr
