"""Operations: scheduler + controllers for map / merge / sort / erase /
reduce / map_reduce.

Ref mapping:
  TScheduler + StartOperation RPC      → OperationScheduler.start_operation
    (server/scheduler/scheduler.cpp)
  TOperationControllerBase lifecycle   → _Controller.prepare/execute/commit
    (controller_agent/operation_controller_detail.cpp: SafePrepare /
     SafeMaterialize / commit)
  operation records in Cypress         → //sys/operations/<id> attributes
  chunk pools / job slicing            → operations/chunk_pools.py
  fair share over pools + preemption   → operations/fair_share.py
  user-process jobs + speculation      → operations/jobs.py

Sort/merge stay whole-device programs (their inner parallelism is the
device mesh); map fans out over sliced stripes on the shared JobManager —
user code runs either as Python callables or as shell commands in job-
proxy subprocesses with wire-format pipes.
"""

from __future__ import annotations

import threading
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.tracing import (
    child_span,
    current_trace,
    start_query_span,
)

# Crash-once at `scheduler.publish` simulates a controller dying between
# the last snapshot record and the output publish — the revival window
# the snapshot subsystem exists for (tests/test_revival.py).
_FP_SNAPSHOT_RECORD = failpoints.register_site("scheduler.snapshot_record")
_FP_PUBLISH = failpoints.register_site("scheduler.publish")


@dataclass
class Operation:
    id: str
    type: str                      # map | merge | sort | erase
    spec: dict
    state: str = "pending"         # pending|running|completed|failed|aborted
    error: Optional[dict] = None
    result: dict = field(default_factory=dict)
    progress: dict = field(default_factory=dict)   # jobs total/completed


class OperationScheduler:
    def __init__(self, client, slots: int = 4):
        from ytsaurus_tpu.operations.jobs import JobManager
        self.client = client
        self._operations: dict[str, Operation] = {}
        self._lock = threading.Lock()
        self._pool_cache: dict[str, tuple[float, dict]] = {}
        self.job_manager = JobManager(slots=slots,
                                      pool_config=self._pool_config)

    _POOL_CONFIG_TTL = 5.0

    def _pool_config(self, name: str) -> dict:
        """Pool definitions from Cypress (//sys/pools/<name>/@...), the
        reference's pool-tree objects (scheduler_pool_server).  Cached
        with a short TTL: this runs per scheduling decision under the
        JobManager lock, and against a remote cluster each lookup is an
        RPC."""
        import time as _time
        cached = self._pool_cache.get(name)
        now = _time.monotonic()
        if cached is not None and now - cached[0] < self._POOL_CONFIG_TTL:
            return cached[1]
        path = f"//sys/pools/{name}"
        out: dict = {}
        try:
            if self.client.exists(path):
                for key in ("weight", "min_share_ratio",
                            "max_running_jobs"):
                    if self.client.exists(f"{path}/@{key}"):
                        out[key] = self.client.get(f"{path}/@{key}")
        except Exception:     # noqa: BLE001 — config lookup must not fail jobs
            pass
        self._pool_cache[name] = (now, out)
        return out

    # -- public API ------------------------------------------------------------

    def start_operation(self, op_type: str, spec: dict,
                        sync: bool = True) -> Operation:
        op = Operation(id=uuid.uuid4().hex, type=op_type, spec=dict(spec))
        with self._lock:
            self._operations[op.id] = op
        self._record(op)
        if sync:
            self._run(op)
        else:
            thread = threading.Thread(target=self._run, args=(op,),
                                      daemon=True)
            thread.start()
        return op

    def get_operation(self, op_id: str) -> Operation:
        op = self._operations.get(op_id)
        if op is None:
            raise YtError(f"No such operation {op_id}",
                          code=EErrorCode.NoSuchOperation)
        return op

    def list_operations(self) -> list[Operation]:
        return list(self._operations.values())

    def abort_operation(self, op_id: str) -> Operation:
        """Abort a running operation: kill its jobs, mark it aborted (ref
        scheduler.cpp AbortOperation).  The aborted state is terminal —
        the controller thread must not overwrite it with completed."""
        op = self.get_operation(op_id)
        with self._lock:
            if op.state in ("completed", "failed", "aborted"):
                return op
            op.state = "aborted"
            op.error = YtError("operation aborted",
                               code=EErrorCode.Canceled).to_dict()
        self.job_manager.abort_operation(op_id)
        self._record(op)
        return op

    def revive_operations(self) -> list[Operation]:
        """Re-run operations a dead controller left pending/running (ref
        revival from snapshots, snapshot_downloader.cpp).  Command-job map
        operations resume from their @snapshot (completed stripes skipped);
        other types re-run deterministically from the recorded spec.
        Python-callable mappers cannot revive — the callable is not
        serializable — and fail with the normal spec error."""
        revived = []
        if not self.client.exists("//sys/operations"):
            return revived
        for op_id in self.client.list("//sys/operations"):
            doc = f"//sys/operations/{op_id}"
            with self._lock:
                if op_id in self._operations:
                    continue     # live in THIS controller; not orphaned
            try:
                if self.client.get(doc + "/@state") not in (
                        "pending", "running"):
                    continue
                op = Operation(
                    id=op_id,
                    type=self.client.get(doc + "/@operation_type"),
                    spec=dict(self.client.get(doc + "/@spec")))
            except YtError:
                continue
            with self._lock:
                self._operations[op.id] = op
            try:
                self._run(op)
            except YtError:
                pass        # state recorded on the op; caller inspects
            revived.append(op)
        return revived

    # -- lifecycle -------------------------------------------------------------

    def _run(self, op: Operation) -> None:
        import time as _time

        # State transitions race with abort_operation (async ops): every
        # transition takes the lock, and aborted is terminal.
        with self._lock:
            if op.state == "aborted":
                return                      # aborted before the thread ran
            op.state = "running"
        self._record(op)
        t0 = _time.monotonic()
        try:
            controller = _CONTROLLERS.get(op.type)
            if controller is None:
                raise YtError(f"Unknown operation type {op.type!r}",
                              code=EErrorCode.OperationFailed)
            # Operation ROOT span (the operations-plane trace entry):
            # controller phases and per-job spans nest under it.
            with start_query_span("operation.run", type=op.type,
                                  operation_id=op.id):
                result = controller(self.client, op.spec, op=op,
                                    job_manager=self.job_manager)
            with self._lock:
                if op.state != "aborted":
                    op.result = result or {}
                    op.state = "completed"
        except YtError as e:
            with self._lock:
                if op.state != "aborted":
                    op.state = "failed"
                    op.error = e.to_dict()
        except Exception as e:                      # noqa: BLE001
            with self._lock:
                if op.state != "aborted":
                    op.state = "failed"
                    op.error = YtError(
                        f"Operation crashed: {e}",
                        code=EErrorCode.OperationFailed,
                        attributes={
                            "traceback":
                                traceback.format_exc()}).to_dict()
        # Per-tenant accounting (ISSUE 6): a terminal operation folds
        # its wall seconds + completed-job count under its spec pool —
        # the operations plane shares the usage ledger with the query
        # plane, so `yt top --by pool` sees both.  Failed/aborted runs
        # fold too: the slots they held were consumed either way.
        try:
            from ytsaurus_tpu.query.accounting import get_accountant
            get_accountant().observe_operation(
                op.spec.get("pool", "default"), op.spec.get("user"),
                wall_seconds=_time.monotonic() - t0,
                jobs=int(op.progress.get("completed", 0) or 0))
        except Exception:   # noqa: BLE001 — accounting must never fail
            pass            # an operation's state transition
        self._record(op)
        if op.state == "failed" and op.spec.get("raise_on_failure", True):
            raise YtError.from_dict(op.error)

    def _record(self, op: Operation) -> None:
        # Each client.set is one fsync'd WAL mutation; write immutable fields
        # once at registration and only the state transition afterwards.
        path = f"//sys/operations/{op.id}"
        client = self.client
        if not client.exists(path):
            client.create("document", path, recursive=True,
                          ignore_existing=True)
            client.set(path + "/@operation_type", op.type)
            client.set(path + "/@spec", _clean_spec(op.spec))
        client.set(path + "/@state", op.state)
        if op.error is not None:
            client.set(path + "/@error", op.error)


def _clean_spec(spec: dict) -> dict:
    """Strip Python callables (any nesting depth) before persisting the
    spec to Cypress — vanilla specs nest them under tasks.<name>."""
    out = {}
    for k, v in spec.items():
        if callable(v):
            continue
        out[k] = _clean_spec(v) if isinstance(v, dict) else v
    return out


class _Snapshot:
    """Operation progress snapshot (ref controller snapshots via
    fork+Phoenix, controller_agent/snapshot_builder.cpp:177 — redesigned:
    no fork; per-stripe outputs persist as ordinary chunks and the
    completed-set lives under //sys/operations/<id>/@snapshot, so revival
    is a plan-match + skip, not a process-image restore)."""

    def __init__(self, client, op_id: str, plan: dict):
        self.client = client
        self.doc = f"//sys/operations/{op_id}"
        self.path = self.doc + "/@snapshot"
        self.plan = plan
        self._lock = threading.Lock()

    def load(self) -> "dict[int, str]":
        """Completed stripe index → output chunk id, iff the recorded plan
        matches the deterministic re-plan (inputs unchanged)."""
        if not self.client.exists(self.doc) or \
                not self.client.exists(self.path):
            return {}
        snap = self.client.get(self.path)
        if snap.get("plan") != self.plan:
            return {}
        return {int(k): v for k, v in (snap.get("completed") or {}).items()}

    def record(self, index: int, rows: list) -> None:
        from ytsaurus_tpu.chunks.columnar import ColumnarChunk
        from ytsaurus_tpu.client import infer_schema
        _FP_SNAPSHOT_RECORD.hit()
        chunk_id = ""
        if rows:
            chunk = ColumnarChunk.from_rows(infer_schema(rows), rows)
            chunk_id = self.client.cluster.chunk_store.write_chunk(chunk)
        with self._lock:
            snap = self.client.get(self.path) \
                if self.client.exists(self.path) else {}
            if snap.get("plan") != self.plan:
                snap = {"plan": self.plan, "completed": {}}
            snap.setdefault("completed", {})[str(index)] = chunk_id
            self.client.set(self.path, snap)

    def read_output(self, chunk_id: str) -> list:
        if not chunk_id:
            return []
        return self.client.cluster.chunk_store.read_chunk(chunk_id).to_rows()

    def clear(self) -> None:
        """Drop the snapshot + its chunks once the output is published.
        Snapshot state is system-owned (like the records themselves)."""
        from ytsaurus_tpu.cypress.security import (
            ROOT_USER,
            authenticated_user,
        )
        # Serialize against record(): a straggler job thread (speculative
        # or respawned after an injected worker death) can still be
        # folding its stripe into the live snapshot dict while the
        # controller publishes — iterating it unlocked crashed the
        # operation with "dictionary changed size during iteration".
        with self._lock:
            if not self.client.exists(self.path):
                return
            snap = self.client.get(self.path)
            chunk_ids = list((snap.get("completed") or {}).values())
        for chunk_id in chunk_ids:
            if chunk_id:
                try:
                    self.client.cluster.chunk_store.remove_chunk(chunk_id)
                except YtError:
                    pass
        with authenticated_user(ROOT_USER):
            self.client.remove(self.path, force=True)


# -- controllers ---------------------------------------------------------------


def _sort_controller(client, spec: dict, op=None, job_manager=None) -> dict:
    """Ref: sort_controller.cpp — read input chunks, device sort (or mesh
    shuffle when a mesh is attached), write output.  Inputs whose device
    footprint exceeds the HBM budget go through the external sort
    (ops/bigsort: range partition + host spill + per-range sorts — the
    partition-tree analog of sort_controller.cpp:459), producing one
    sorted output chunk per range instead of one giant resident table."""
    import os as _os

    from ytsaurus_tpu.operations.chunk_pools import chunk_data_weight
    from ytsaurus_tpu.operations.sort_op import sort_chunks

    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    sort_by = spec["sort_by"]
    if isinstance(sort_by, str):
        sort_by = [sort_by]
    descending = spec.get("descending", False)
    chunks = client._read_table_chunks(input_path)
    if not chunks:
        client._write_table_chunks(output_path, [], sorted_by=sort_by)
        return {"rows": 0}
    budget = int(spec.get("hbm_budget") or
                 _os.environ.get("YT_TPU_HBM_BUDGET", 8 << 30))
    total_weight = sum(chunk_data_weight(c) for c in chunks)
    numeric_only = all(
        col.dictionary is None and col.host_values is None
        for c in chunks for col in c.columns.values())
    if total_weight * 2 > budget and numeric_only:
        from ytsaurus_tpu.ops.bigsort import SpillStats, external_sort
        stats = SpillStats()
        with child_span("sort.external", chunks=len(chunks),
                        bytes=total_weight):
            outs = list(external_sort(chunks, sort_by,
                                      budget_bytes=budget,
                                      descending=descending, stats=stats))
        client._write_table_chunks(
            output_path, outs, sorted_by=sort_by,
            schema=outs[0].schema if outs else None)
        return {"rows": sum(c.row_count for c in outs),
                "spill_ranges": stats.ranges,
                "resplits": stats.resplits}
    with child_span("sort.device_sort", chunks=len(chunks),
                    bytes=total_weight):
        out = sort_chunks(chunks, sort_by, descending=descending)
    client._write_table_chunks(output_path, [out], sorted_by=sort_by,
                               schema=out.schema)
    return {"rows": out.row_count}


def _merge_controller(client, spec: dict, op=None, job_manager=None) -> dict:
    """Ref: ordered/sorted merge (ordered_controller.cpp,
    sorted_controller.cpp)."""
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.operations.sort_op import sort_chunks

    input_paths = spec["input_table_paths"]
    output_path = _one(spec, "output_table_path")
    mode = spec.get("mode", "unordered")
    chunks = []
    for path in input_paths:
        chunks.extend(client._read_table_chunks(path))
    if not chunks:
        client._write_table_chunks(output_path, [])
        return {"rows": 0}
    chunks = _align_schemas(chunks)
    if mode == "sorted":
        key_names = spec.get("merge_by") or \
            chunks[0].schema.key_column_names
        if not key_names:
            raise YtError("sorted merge requires merge_by or sorted input")
        out = sort_chunks(chunks, key_names)
        client._write_table_chunks(output_path, [out], sorted_by=key_names,
                                   schema=out.schema)
    else:
        out = concat_chunks(chunks) if len(chunks) > 1 else chunks[0]
        client._write_table_chunks(output_path, [out], schema=out.schema)
    return {"rows": out.row_count}


def _map_controller(client, spec: dict, op=None, job_manager=None) -> dict:
    """Ref: unordered_controller.cpp + the user-process map job
    (job_proxy/user_job.cpp).

    Two user-code shapes:
      spec["mapper"]  — a Python callable rows→rows, run in-slot;
      spec["command"] — a shell command; rows stream through a job-proxy
                        subprocess on stdin/stdout in spec["format"]
                        (default json lines), stderr tail kept on errors.
    Input slices into stripes via the chunk pool, jobs run concurrently
    on the shared JobManager under spec["pool"] fair share; stragglers
    get speculative twins (command jobs)."""
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    from ytsaurus_tpu.operations.chunk_pools import build_stripes, split_stripe
    from ytsaurus_tpu.operations.jobs import (
        Job,
        run_command_job,
        run_remote_command_job,
    )

    mapper: Optional[Callable] = spec.get("mapper")
    command: Optional[str] = spec.get("command")
    if (mapper is None) == (command is None):
        raise YtError("map spec requires exactly one of mapper/command")
    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    fmt = spec.get("format", "json")
    pool = spec.get("pool", "default")
    chunks = client._read_table_chunks(input_path)

    def attr(name, default):
        try:
            return client.get(f"{input_path}/@{name}")
        except YtError:
            return default

    input_chunk_ids = list(attr("chunk_ids", []))
    # Snapshots are plan-keyed by the input chunk list; dynamic tables
    # have no stable chunk list, so their operations restart from scratch
    # on revival rather than risk stale per-stripe outputs.  Remote thin
    # clients have no direct chunk store either — local controllers only.
    snapshot_ok = not attr("dynamic", False) and hasattr(client, "cluster")
    rows_per_job = spec.get("rows_per_job")
    if rows_per_job is None and spec.get("job_count"):
        total = sum(c.row_count for c in chunks)
        rows_per_job = max(-(-total // max(int(spec["job_count"]), 1)), 1)
    stripes = build_stripes(
        chunks, ordered=bool(spec.get("ordered", False)),
        rows_per_job=rows_per_job or 4_000_000,
        max_job_count=spec.get("max_job_count"))
    if not stripes:
        client.write_table(output_path, [],
                           schema=spec.get("output_schema"))
        return {"rows": 0, "jobs": 0}

    op_id = op.id if op is not None else uuid.uuid4().hex

    # Distributed exec plane (ref server/node/exec_node/): command jobs
    # dispatch to job slots on data-node daemons whenever the cluster
    # has any, reading their input chunks LOCAL-FIRST on the node; the
    # in-process path remains for pure local mode and Python mappers.
    exec_nodes: dict = {}
    if command is not None and spec.get("remote_jobs", True):
        try:
            exec_nodes = dict(client.exec_node_addresses())
        except Exception:   # noqa: BLE001 — directory is advisory
            exec_nodes = {}
    chunk_to_id: dict[int, str] = {}
    if exec_nodes and len(input_chunk_ids) == len(chunks):
        chunk_to_id = {id(c): cid for c, cid in
                       zip(chunks, input_chunk_ids)}

    def make_run(stripe):
        if mapper is not None:
            def run_py(job):
                return list(mapper(stripe.materialize().to_rows()))
            return run_py, False

        if exec_nodes:
            def run_remote(job):
                from ytsaurus_tpu.server.remote_store import placement_rank
                addrs = list(dict(exec_nodes).values())
                by_id = all(id(c) in chunk_to_id
                            for c, _, _ in stripe.slices)
                from ytsaurus_tpu.operations.job_environment import (
                    limits_from_spec,
                )
                body = {"command": command, "format": fmt,
                        "op_id": op_id, "job_id": job.id,
                        "time_limit": spec.get("job_time_limit"),
                        "limits": limits_from_spec(spec),
                        "env": spec.get("environment") or {}}
                blob = None
                if by_id:
                    # Node-side materialization: rank by the first
                    # slice's chunk placement so a replica holder runs
                    # the job (local read); rotate within the replica
                    # set by index for spread, and past it on retries
                    # (node-death revival).
                    first = chunk_to_id[id(stripe.slices[0][0])]
                    ranked = placement_rank(first, addrs)
                    body["slices"] = [
                        {"chunk_id": chunk_to_id[id(c)],
                         "start": s, "end": e}
                        for c, s, e in stripe.slices]
                    body["peers"] = addrs
                    spread = min(2, len(ranked))
                    offset = (job.index + job.attempt) % spread \
                        if job.attempt == 0 else \
                        (job.index + job.attempt) % len(ranked)
                    order = ranked[offset:] + ranked[:offset]
                else:
                    # No stable chunk ids (e.g. dynamic input): ship the
                    # formatted rows with the spec.
                    blob = dumps_rows(stripe.materialize().to_rows(),
                                      fmt)
                    offset = (job.index + job.attempt) % len(addrs)
                    order = addrs[offset:] + addrs[:offset]
                time_limit = spec.get("job_time_limit")
                poll_timeout = time_limit + 60 if time_limit else None
                last: "YtError | None" = None
                for addr in order:
                    try:
                        out = run_remote_command_job(
                            job, addr, dict(body), input_blob=blob,
                            timeout=poll_timeout)
                        return loads_rows(out, fmt)
                    except YtError as err:
                        if err.code in (EErrorCode.TransportError,
                                        EErrorCode.PeerUnavailable,
                                        EErrorCode.RpcTimeout,
                                        EErrorCode.NoSuchOperation):
                            # Node died or restarted mid-job: revive the
                            # job on the next node.
                            last = err
                            continue
                        raise
                raise last or YtError("no exec node accepted the job",
                                      code=EErrorCode.PeerUnavailable)
            return run_remote, True

        def run_cmd(job):
            from ytsaurus_tpu.operations.job_environment import (
                limits_from_spec,
            )
            blob = dumps_rows(stripe.materialize().to_rows(), fmt)
            out = run_command_job(job, command, blob,
                                  timeout=spec.get("job_time_limit"),
                                  limits=limits_from_spec(spec))
            return loads_rows(out, fmt)
        return run_cmd, True

    def make_splitter(stripe):
        """Straggler split (ref job_splitter.h): halve the stripe, same
        command, children settle the parent (command jobs only)."""
        def split(parent):
            halves = split_stripe(stripe)
            if len(halves) < 2:
                return []
            children = []
            for h, half in enumerate(halves):
                run, _ = make_run(half)
                children.append(Job(
                    op_id=op_id, index=parent.index, run=run, pool=pool,
                    preemptible=True, splitter=make_splitter(half)))
            return children
        return split

    # Controller snapshot (ref fork+Phoenix operation snapshots,
    # snapshot_builder.cpp): per-stripe outputs persist as chunks under
    # @snapshot so a revived operation skips completed work.  Valid only
    # while the deterministic stripe plan matches (input chunks + split).
    outputs, revived = _run_user_jobs(
        client, op, job_manager, spec, stripes, make_run,
        plan={"input_chunk_ids": input_chunk_ids,
              "stripe_count": len(stripes)},
        is_command=command is not None and snapshot_ok,
        make_splitter=make_splitter if command is not None else None,
        publish=lambda outs: client.write_table(
            output_path, [row for part in outs for row in part],
            schema=spec.get("output_schema")))
    return {"rows": sum(len(part) for part in outputs),
            "jobs": len(stripes) - revived, "revived_jobs": revived}


def _erase_controller(client, spec: dict, op=None, job_manager=None) -> dict:
    path = _one(spec, "table_path")
    client._write_table_chunks(path, [])
    return {"rows": 0}


def _spec_keys(spec: dict, name: str, default=None) -> list[str]:
    value = spec.get(name)
    if value is None:           # absent OR explicitly None → default
        value = default
    if value is None:
        raise YtError(f"Operation spec requires {name!r}")
    return [value] if isinstance(value, str) else list(value)


def _reduce_keys(spec: dict) -> "tuple[list[str], list[str]]":
    """(reduce_by, sort_by) with sort_by defaulting to reduce_by and
    required to extend it (ref reduce sort_by semantics)."""
    reduce_by = _spec_keys(spec, "reduce_by")
    sort_by = _spec_keys(spec, "sort_by", default=reduce_by)
    if sort_by[: len(reduce_by)] != reduce_by:
        raise YtError(f"sort_by {sort_by} must start with reduce_by "
                      f"{reduce_by}", code=EErrorCode.QueryTypeError)
    return reduce_by, sort_by


def _run_user_jobs(client, op, job_manager, spec, work_items, make_runner,
                   plan: dict, is_command: bool,
                   make_splitter=None,
                   publish=None) -> "tuple[list, int]":
    """Shared fan-out for the map/reduce/map_reduce user-job phases:
    one job per work item on the JobManager, with command-job snapshot
    revival (_Snapshot, plan-keyed) and optional straggler splitting.

    make_runner(item) -> (run, preemptible);
    make_splitter(item) -> Job.splitter (command jobs only);
    publish(outputs) runs BEFORE snapshot cleanup so a crash between
    output write and snapshot removal stays revivable.
    Returns (per-item outputs in item order, revived_count)."""
    _raise_if_aborted(op)      # an abort during an earlier phase stops here
    op_id = op.id if op is not None else uuid.uuid4().hex
    from ytsaurus_tpu.operations.jobs import Job

    snapshot_ok = is_command and hasattr(client, "cluster")
    snap = _Snapshot(client, op_id, plan=plan) if snapshot_ok else None
    completed = snap.load() if snap is not None else {}
    pool = spec.get("pool", "default")
    total = len(work_items)
    if op is not None:
        op.progress = {"total": total, "completed": len(completed)}

    def on_done(job) -> None:
        if job.state != "completed":
            return
        if op is not None:
            op.progress["completed"] = op.progress.get("completed", 0) + 1
        if snap is not None:
            snap.record(job.index, job.result or [])

    # Per-job failure budget (ref max_failed_job_count): transient
    # failures requeue the job until the budget runs out.
    max_failures = max(int(spec.get("max_failed_job_count", 1)), 1)
    # Job runners execute on JobManager worker threads: under a sampled
    # trace each gets an EXPLICIT contextvars capture so its span links
    # operation → phase → job; untraced operations skip the wrap.
    trace = current_trace()
    traced = trace is not None and trace.sampled
    jobs = []
    phase_span = child_span("operation.phase", jobs=total,
                            revived=len(completed))
    with phase_span:
        for i, item in enumerate(work_items):
            if i in completed:
                continue
            run, preemptible = make_runner(item)
            if traced:
                run = _traced_job_run(run, i)
            jobs.append(Job(op_id=op_id, index=i, run=run, pool=pool,
                            preemptible=preemptible, on_done=on_done,
                            max_failures=max_failures,
                            splitter=make_splitter(item)
                            if make_splitter is not None else None))
        job_manager.submit(jobs)
        try:
            job_manager.wait(jobs)
        except YtError:
            job_manager.abort_operation(op_id)
            raise
        finally:
            job_manager.finish_operation(op_id)
    # An abort landing during the wait settles its jobs as 'aborted'
    # (empty results) without raising; publishing would then overwrite
    # the destination with partial rows and snap.clear() would destroy
    # the revival snapshot.  Stop BEFORE either.
    if any(job.state == "aborted" for job in jobs):
        raise YtError("operation aborted", code=EErrorCode.Canceled)
    _raise_if_aborted(op)
    by_index = {job.index: (job.result or []) for job in jobs}
    outputs = []
    for i in range(total):
        if i in by_index:
            outputs.append(by_index[i])
        else:
            outputs.append(snap.read_output(completed[i]))
    # crash-once HERE = controller death after every stripe recorded but
    # before the output exists: revival must replay purely from the
    # snapshot.  (The site sits before publish on purpose — after
    # publish the operation is observably complete.)
    _FP_PUBLISH.hit()
    if publish is not None:
        publish(outputs)
    if snap is not None:
        snap.clear()
    return outputs, len(completed)


def _traced_job_run(run, index: int):
    """Per-job span wrapper: captures the submitting thread's trace
    context EXPLICITLY (worker threads have empty contextvars) and
    re-parents each invocation under it.  A fresh child per call keeps
    speculative/requeued copies of one job distinguishable — and avoids
    contextvars.Context.run's no-concurrent-reentry restriction."""
    parent = current_trace()

    def wrapped(job):
        span = parent.create_child("operation.job")
        span.add_tag("index", index)
        with span:
            return run(job)

    return wrapped


def _raise_if_aborted(op) -> None:
    """Abort barrier between controller phases: a multi-phase controller
    (map_reduce) must not start its next phase — or publish — after the
    operation was aborted."""
    if op is not None and op.state == "aborted":
        raise YtError("operation aborted", code=EErrorCode.Canceled)


def _make_reduce_runner(reducer, command, reduce_by, fmt, spec):
    """Runner factory over a LAZY key-sorted row source (rows_fn runs on
    the job slot, not the controller thread).  Python reducers get
    yt.wrapper-style (key_dict, group_rows) per group; command reducers
    stream the sorted rows through job-proxy pipes (contiguous key groups
    on stdin — the classic streaming-reduce contract)."""
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    from ytsaurus_tpu.operations.jobs import run_command_job
    from ytsaurus_tpu.operations.reduce_op import iter_groups

    def make(rows_fn):
        if reducer is not None:
            def run_py(job):
                out: list[dict] = []
                for key, group in iter_groups(rows_fn(), reduce_by):
                    out.extend(reducer(key, group))
                return out
            return run_py, False

        def run_cmd(job):
            from ytsaurus_tpu.operations.job_environment import (
                limits_from_spec,
            )
            blob = dumps_rows(rows_fn(), fmt)
            out = run_command_job(job, command, blob,
                                  timeout=spec.get("job_time_limit"),
                                  limits=limits_from_spec(spec))
            return loads_rows(out, fmt)
        return run_cmd, True
    return make


def _sort_rows_for_reduce(rows: list, sort_by: list) -> list:
    """Sort intermediate rows by the reduce sort key.  Device lexsort when
    the rows are schema-uniform (the partition_sort_job analog); host
    fallback for ragged user-job output the columnar planes reject —
    type-ranked so mixed-type columns still admit a total order."""
    if not rows:
        return rows
    try:
        from ytsaurus_tpu.chunks.columnar import ColumnarChunk
        from ytsaurus_tpu.client import infer_schema
        from ytsaurus_tpu.operations.sort_op import sort_chunk
        chunk = ColumnarChunk.from_rows(infer_schema(rows), rows)
        return sort_chunk(chunk, sort_by).to_rows()
    except Exception:       # noqa: BLE001 — ragged rows: host stable sort
        def key(row):
            out = []
            for k in sort_by:
                v = row.get(k)
                if v is None:
                    out.append((0, 0))
                elif isinstance(v, (bool, int, float)):
                    out.append((1, v))
                elif isinstance(v, bytes):
                    out.append((2, v))
                elif isinstance(v, str):
                    out.append((3, v))
                else:
                    out.append((4, repr(v)))
            return tuple(out)
        return sorted(rows, key=key)


def _reduce_controller(client, spec: dict, op=None, job_manager=None) -> dict:
    """Sorted Reduce (ref sorted_controller.cpp:1451
    CreateReduceController).

    The reference merges sorted chunk readers and slices jobs at key
    boundaries (the key guarantee).  Here the merge of already-sorted
    inputs is one device lexsort over the concatenated columnar planes,
    and stripes cut only where the reduce key changes — so every key
    group lands in exactly one job."""
    from ytsaurus_tpu.operations.reduce_op import (
        decode_keys,
        key_aligned_ranges,
        validate_sorted_input,
    )
    from ytsaurus_tpu.operations.sort_op import sort_chunks

    reducer = spec.get("reducer")
    command = spec.get("command")
    if (reducer is None) == (command is None):
        raise YtError("reduce spec requires exactly one of reducer/command")
    reduce_by, sort_by = _reduce_keys(spec)
    input_paths = spec.get("input_table_paths") or \
        [_one(spec, "input_table_path")]
    output_path = _one(spec, "output_table_path")
    fmt = spec.get("format", "json")

    chunks = []
    input_chunk_ids: list[str] = []
    plan_stable = True          # chunk ids readable → snapshot plan keyed
    for path in input_paths:
        validate_sorted_input(client, path, reduce_by)
        chunks.extend(client._read_table_chunks(path))
        try:
            input_chunk_ids.extend(client.get(path + "/@chunk_ids") or [])
        except YtError:
            plan_stable = False
    chunks = [c for c in chunks if c.row_count > 0]
    if not chunks:
        client.write_table(output_path, [],
                           schema=spec.get("output_schema"))
        return {"rows": 0, "jobs": 0}
    merged = sort_chunks(_align_schemas(chunks), sort_by)
    keys = decode_keys(merged, reduce_by)
    rows_per_job = spec.get("rows_per_job") or 4_000_000
    if spec.get("job_count"):
        rows_per_job = max(-(-len(keys) // max(int(spec["job_count"]), 1)),
                           1)
    ranges = key_aligned_ranges(keys, rows_per_job)

    base = _make_reduce_runner(reducer, command, reduce_by, fmt, spec)

    def make(rng):
        start, end = rng
        # Slice the merged columnar chunk lazily: rows decode on the job
        # slot (the stripe.materialize() analog), not the controller.
        return base(lambda: merged.slice_rows(start, end).to_rows())

    outputs, revived = _run_user_jobs(
        client, op, job_manager, spec, ranges, make,
        plan={"kind": "reduce", "input_chunk_ids": input_chunk_ids,
              "ranges": [list(r) for r in ranges], "command": command},
        is_command=command is not None and plan_stable,
        publish=lambda outs: client.write_table(
            output_path, [row for part in outs for row in part],
            schema=spec.get("output_schema")))
    return {"rows": sum(len(part) for part in outputs),
            "jobs": len(ranges) - revived, "revived_jobs": revived}


def _map_reduce_controller(client, spec: dict, op=None,
                           job_manager=None) -> dict:
    """MapReduce (ref sort_controller.cpp:5029 CreateMapReduceController):
    map+partition jobs → hash shuffle by reduce key → per-partition
    sort + reduce jobs (partition_sort_job.cpp:43 semantics).

    Redesign: the reference streams partition chunks through a partition
    tree; here map jobs hash-route their output rows in-job (stable CRC,
    revival-safe) and each reduce job device-sorts its partition before
    grouping — the shuffle itself is row movement between job results,
    not a cluster data plane, because operation intermediates are
    operation-lifetime state."""
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    from ytsaurus_tpu.operations.chunk_pools import build_stripes
    from ytsaurus_tpu.operations.jobs import run_command_job
    from ytsaurus_tpu.operations.reduce_op import partition_rows

    mapper = spec.get("mapper")
    map_command = spec.get("map_command")
    reducer = spec.get("reducer")
    reduce_command = spec.get("reduce_command")
    if (reducer is None) == (reduce_command is None):
        raise YtError(
            "map_reduce spec requires exactly one of reducer/reduce_command")
    if mapper is not None and map_command is not None:
        raise YtError("map_reduce spec allows at most one of "
                      "mapper/map_command")
    reduce_by, sort_by = _reduce_keys(spec)
    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    fmt = spec.get("format", "json")
    chunks = client._read_table_chunks(input_path)
    chunks = [c for c in chunks if c.row_count > 0]
    if not chunks:
        client.write_table(output_path, [],
                           schema=spec.get("output_schema"))
        return {"rows": 0, "jobs": 0}
    total_rows = sum(c.row_count for c in chunks)
    rows_per_job = spec.get("rows_per_job") or 4_000_000
    partition_count = int(spec.get("partition_count") or
                          max(min(-(-total_rows // rows_per_job), 64), 1))
    stripes = build_stripes(chunks, rows_per_job=rows_per_job,
                            max_job_count=spec.get("max_job_count"))
    # Snapshot revival is valid only when the whole pipeline is free of
    # Python callables (commands re-run deterministically; closures don't
    # survive a controller restart).  Dynamic tables have no stable chunk
    # list (rows change while @chunk_ids stays fixed), so their snapshot
    # plans would silently go stale — no revival for them, as in map.
    def _attr(name, default):
        try:
            return client.get(f"{input_path}/@{name}")
        except YtError:
            return default

    input_chunk_ids = list(_attr("chunk_ids", []) or [])
    plan_stable = bool(input_chunk_ids) and not _attr("dynamic", False)
    is_command = mapper is None and reducer is None and plan_stable

    # -- phase 1: map + partition (each job hash-routes its own output) --------
    def make_map(stripe):
        def run_map(job):
            rows = stripe.materialize().to_rows()
            if mapper is not None:
                rows = list(mapper(rows))
            elif map_command is not None:
                from ytsaurus_tpu.operations.job_environment import (
                    limits_from_spec,
                )
                blob = dumps_rows(rows, fmt)
                out = run_command_job(job, map_command, blob,
                                      timeout=spec.get("job_time_limit"),
                                      limits=limits_from_spec(spec))
                rows = loads_rows(out, fmt)
            return partition_rows(rows, reduce_by, partition_count)
        return run_map, map_command is not None

    plan = {"kind": "map_reduce", "input_chunk_ids": input_chunk_ids,
            "partition_count": partition_count,
            "map_command": map_command, "reduce_command": reduce_command}

    # Revival fast path: when every reduce partition is already recorded
    # in the snapshot, skip the (deterministic) map phase entirely.
    op_id = op.id if op is not None else uuid.uuid4().hex
    snap_ok = is_command and hasattr(client, "cluster")
    probe = _Snapshot(client, op_id, plan=plan) if snap_ok else None
    pre_completed = probe.load() if probe is not None else {}
    map_jobs_run = 0
    if len(pre_completed) == partition_count:
        partitions: "list[list[dict]]" = [[] for _ in range(partition_count)]
    else:
        buckets, _ = _run_user_jobs(
            client, op, job_manager, spec, stripes, make_map,
            plan={}, is_command=False)   # map phase re-runs on revival
        map_jobs_run = len(stripes)
        partitions = [[] for _ in range(partition_count)]
        for job_buckets in buckets:
            for p, rows in enumerate(job_buckets):
                partitions[p].extend(rows)

    # An abort that landed during the map phase must stop the reduce
    # phase from running (and publishing) at all.
    _raise_if_aborted(op)

    # -- phase 2: per-partition device sort + reduce ---------------------------
    make_reduce_base = _make_reduce_runner(
        reducer, reduce_command, reduce_by, fmt, spec)

    def make_reduce(rows):
        # Sort runs INSIDE the job via the lazy rows_fn (the
        # partition_sort_job analog): device lexsort on a job slot, not
        # the controller thread.
        return make_reduce_base(
            lambda: _sort_rows_for_reduce(rows, sort_by))

    outputs, revived = _run_user_jobs(
        client, op, job_manager, spec, partitions, make_reduce,
        plan=plan, is_command=is_command,
        publish=lambda outs: client.write_table(
            output_path, [row for part in outs for row in part],
            schema=spec.get("output_schema")))
    return {"rows": sum(len(part) for part in outputs),
            "jobs": map_jobs_run + partition_count - revived,
            "partitions": partition_count, "revived_jobs": revived}


def _vanilla_controller(client, spec: dict, op=None,
                        job_manager=None) -> dict:
    """Vanilla (gang) operations (ref vanilla_controller.cpp:130): named
    tasks × job_count jobs with NO input tables — the hosting primitive
    for CHYT cliques and everything strawberry-shaped.

    Gang semantics: the whole gang must fit the slot pool (all-or-nothing
    acquisition — a partial gang would deadlock the cluster), and ANY job
    failure restarts the ENTIRE gang (ref vanilla_controller.cpp gang
    rank restart), up to max_gang_restarts.  Long-lived commands (servers)
    run until the operation is aborted."""
    from ytsaurus_tpu.formats import loads_rows
    from ytsaurus_tpu.operations.jobs import Job, run_command_job

    tasks = spec.get("tasks")
    if not tasks or not isinstance(tasks, dict):
        raise YtError("vanilla spec requires tasks: {name: {...}}")
    gang = bool(spec.get("gang", True))
    max_restarts = int(spec.get("max_gang_restarts", 2))
    fmt = spec.get("format", "json")
    pool = spec.get("pool", "default")
    op_id = op.id if op is not None else uuid.uuid4().hex

    plans = []                       # (task_name, job_count, runner spec)
    total = 0
    for name in sorted(tasks):
        task = tasks[name]
        job_count = int(task.get("job_count", 1))
        if job_count < 1:
            raise YtError(f"vanilla task {name!r}: job_count must be >= 1")
        command = task.get("command")
        fn = task.get("callable")
        if (command is None) == (fn is None):
            raise YtError(f"vanilla task {name!r} requires exactly one "
                          "of command/callable")
        plans.append((name, job_count, command, fn, task))
        total += job_count
    if gang and total > job_manager.slots:
        raise YtError(
            f"vanilla gang of {total} jobs cannot acquire "
            f"{job_manager.slots} slots (all-or-nothing scheduling)",
            code=EErrorCode.OperationFailed)

    attempt = 0
    while True:
        jobs: list = []
        index = 0
        for name, job_count, command, fn, task in plans:
            for rank in range(job_count):
                if command is not None:
                    def run_cmd(job, _cmd=command, _name=name,
                                _rank=rank, _task=task):
                        from ytsaurus_tpu.operations.job_environment \
                            import limits_from_spec
                        out = run_command_job(
                            job, _cmd, b"",
                            timeout=_task.get("job_time_limit") or
                            spec.get("job_time_limit"),
                            env={"YT_TASK_NAME": _name,
                                 "YT_JOB_COOKIE": str(_rank),
                                 **(_task.get("environment") or {})},
                            # Per-KEY merge: a task overriding one limit
                            # must not drop the operation-wide others.
                            limits={**(limits_from_spec(spec) or {}),
                                    **(limits_from_spec(_task) or {})}
                            or None)
                        return loads_rows(out, fmt) if out.strip() else []
                    run, preemptible = run_cmd, True
                else:
                    def run_py(job, _fn=fn, _name=name, _rank=rank):
                        return list(_fn(_name, _rank) or [])
                    run, preemptible = run_py, False
                jobs.append(Job(op_id=op_id, index=index, run=run,
                                pool=pool, preemptible=preemptible))
                index += 1
        if op is not None:
            op.progress = {"total": total, "completed": 0,
                           "gang_attempt": attempt}
        # Gang wait with FIRST-casualty short-circuit: a failing sibling
        # must condemn still-running (possibly long-lived) rank mates
        # immediately, not after they exit on their own.
        wake = threading.Event()
        for job in jobs:
            job.on_done = lambda _job: wake.set()
        job_manager.submit(jobs)
        try:
            while True:
                states = [j.state for j in jobs]
                if all(s == "completed" for s in states):
                    break
                if any(s in ("failed", "aborted") for s in states):
                    break
                if op is not None and op.state == "aborted":
                    break
                wake.wait(0.2)
                wake.clear()
        finally:
            job_manager.finish_operation(op_id)
        if all(j.state == "completed" for j in jobs):
            break
        # Gang discipline: one casualty condemns the whole rank set.
        job_manager.abort_operation(op_id)
        if op is not None and op.state == "aborted":
            raise YtError("operation aborted", code=EErrorCode.Canceled)
        attempt += 1
        first_error = next((j.error for j in jobs if j.error is not None),
                           None)
        if attempt > max_restarts:
            raise first_error or YtError(
                "vanilla gang failed", code=EErrorCode.OperationFailed)

    # Optional per-task output tables (ref vanilla output table specs).
    outputs: dict = {}
    cursor = 0
    for name, job_count, _command, _fn, task in plans:
        rows = [row for job in jobs[cursor: cursor + job_count]
                for row in (job.result or [])]
        cursor += job_count
        outputs[name] = len(rows)
        out_path = task.get("output_table_path")
        if out_path:
            client.write_table(out_path, rows,
                               schema=task.get("output_schema"))
    return {"jobs": total, "gang_restarts": attempt,
            "task_output_rows": outputs}


def _remote_copy_controller(client, spec: dict, op=None,
                            job_manager=None) -> dict:
    """Remote copy (ref controllers/remote_copy_controller.cpp): pull a
    table from ANOTHER cluster into this one through the remote thin
    client — chunk-shaped reads on the source, ordinary chunk publishes
    on the destination, schema + sort order preserved."""
    from ytsaurus_tpu.remote_client import connect_remote

    cluster_address = spec.get("cluster_address") or \
        spec.get("cluster_connection")
    if not cluster_address:
        raise YtError("remote_copy spec requires cluster_address")
    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    src = connect_remote(cluster_address)
    try:
        chunks = src._read_table_chunks(input_path)
        schema = None
        sorted_by = None
        try:
            schema_dict = src.get(input_path + "/@schema")
            if schema_dict:
                from ytsaurus_tpu.schema import TableSchema
                schema = TableSchema.from_dict(schema_dict)
        except YtError:
            pass
        try:
            sorted_by = src.get(input_path + "/@sorted_by")
        except YtError:
            sorted_by = None
        chunks = [c for c in chunks if c.row_count > 0]
        client._write_table_chunks(output_path, chunks,
                                   sorted_by=sorted_by, schema=schema)
        # User attributes ride along (ref remote copy attribute keys).
        # They were requested EXPLICITLY: a missing one is an error, not
        # a silent drop.
        missing = []
        for key in spec.get("attribute_keys") or []:
            try:
                client.set(f"{output_path}/@{key}",
                           src.get(f"{input_path}/@{key}"))
            except YtError:
                missing.append(key)
        if missing:
            raise YtError(
                f"remote_copy: requested attribute_keys {missing} absent "
                f"on {input_path!r}", code=EErrorCode.ResolveError)
        return {"rows": sum(c.row_count for c in chunks),
                "chunks": len(chunks)}
    finally:
        src.close()


def _align_schemas(chunks):
    """Inputs from different tables may agree on columns but differ in order
    or sort annotations; align them onto one unsorted schema for merging."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema

    base = {c.name: c.type for c in chunks[0].schema}
    for chunk in chunks[1:]:
        other = {c.name: c.type for c in chunk.schema}
        if other != base:
            raise YtError(
                f"Merge inputs have incompatible schemas: {sorted(base)} vs "
                f"{sorted(other)}", code=EErrorCode.QueryTypeError)
    target = TableSchema.make(
        [(c.name, c.type.value) for c in chunks[0].schema])
    return [
        ColumnarChunk(schema=target, row_count=chunk.row_count,
                      columns={name: chunk.columns[name]
                               for name in target.column_names})
        for chunk in chunks
    ]


def _one(spec: dict, key: str) -> str:
    value = spec.get(key)
    if not value or not isinstance(value, str):
        raise YtError(f"Operation spec requires {key!r}")
    return value


_CONTROLLERS = {
    "sort": _sort_controller,
    "merge": _merge_controller,
    "map": _map_controller,
    "erase": _erase_controller,
    "reduce": _reduce_controller,
    "map_reduce": _map_reduce_controller,
    "vanilla": _vanilla_controller,
    "remote_copy": _remote_copy_controller,
}
