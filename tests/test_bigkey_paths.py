"""Randomized oracle tests for the >dense-cap GROUP BY (hash-major) and
packed-key ORDER BY paths — the regimes where full lexsorts collapsed on
TPU (round-1 finding; VERDICT item 4)."""

import numpy as np
import pytest

from tests.harness import evaluate
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.schema import TableSchema

T = "//t"


def test_groupby_cardinality_beyond_dense_cap():
    # 200k distinct keys > 65536 dense-slot cap → hash-major general path.
    rng = np.random.default_rng(7)
    n = 400_000
    g = rng.integers(0, 200_000, n)
    v = rng.integers(0, 100, n)
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": np.arange(n), "g": g, "v": v})
    rows = evaluate(f"g, sum(v) AS s, count(*) AS c FROM [{T}] GROUP BY g",
                    {T: chunk})
    # numpy oracle
    import collections
    want_s = collections.Counter()
    want_c = collections.Counter()
    for gi, vi in zip(g.tolist(), v.tolist()):
        want_s[gi] += vi
        want_c[gi] += 1
    assert len(rows) == len(want_s)
    got = {r["g"]: (r["s"], r["c"]) for r in rows}
    assert len(got) == len(rows), "duplicate group keys in output"
    for gi in want_s:
        assert got[gi] == (want_s[gi], want_c[gi])


def test_groupby_multikey_with_nulls_hash_path():
    rng = np.random.default_rng(3)
    n = 50_000
    rows_in = []
    for i in range(n):
        a = int(rng.integers(0, 300)) if rng.random() > 0.1 else None
        b = int(rng.integers(0, 300)) * 7 - 1000 if rng.random() > 0.1 \
            else None
        rows_in.append((i, a, b, int(rng.integers(0, 10))))
    tables = {T: ([("k", "int64", "ascending"), ("a", "int64"),
                   ("b", "int64"), ("v", "int64")], rows_in)}
    rows = evaluate(f"a, b, sum(v) AS s FROM [{T}] GROUP BY a, b", tables)
    import collections
    want = collections.Counter()
    for _, a, b, v in rows_in:
        want[(a, b)] += v
    assert len(rows) == len(want)
    got = {(r["a"], r["b"]): r["s"] for r in rows}
    assert got == dict(want)


def test_orderby_two_keys_mixed_direction_with_nulls():
    rng = np.random.default_rng(5)
    n = 20_000
    rows_in = []
    for i in range(n):
        a = int(rng.integers(0, 50)) if rng.random() > 0.05 else None
        d = float(rng.normal()) if rng.random() > 0.05 else None
        rows_in.append((i, a, d))
    tables = {T: ([("k", "int64", "ascending"), ("a", "int64"),
                   ("d", "double")], rows_in)}
    rows = evaluate(
        f"k, a, d FROM [{T}] ORDER BY a ASC, d DESC LIMIT 500",
        {T: ([("k", "int64", "ascending"), ("a", "int64"),
              ("d", "double")], rows_in)})
    # Oracle: null-first asc on a; within, desc d with nulls LAST.
    def key(r):
        i, a, d = r
        return (0 if a is None else 1, a if a is not None else 0,
                1 if d is None else 0, -(d if d is not None else 0.0))
    want = sorted(rows_in, key=key)[:500]
    got = [(r["k"], r["a"], r["d"]) for r in rows]
    for (gk, ga, gd), (wk, wa, wd) in zip(got, want):
        assert (ga, gd is None) == (wa, wd is None)
        if gd is not None:
            assert abs(gd - wd) < 1e-12


def test_orderby_float_negative_zero_and_inf():
    vals = [0.0, -0.0, float("inf"), float("-inf"), 2.5, -2.5, None]
    tables = {T: ([("k", "int64", "ascending"), ("d", "double")],
                  [(i, v) for i, v in enumerate(vals)])}
    rows = evaluate(f"k FROM [{T}] ORDER BY d ASC LIMIT 7", tables)
    order = [r["k"] for r in rows]
    # null first, then -inf, -2.5, (-0.0 / 0.0 in either order), 2.5, inf
    assert order[0] == 6 and order[1] == 3 and order[2] == 5
    assert set(order[3:5]) == {0, 1}
    assert order[5] == 4 and order[6] == 2


def test_sort_chunk_descending_with_nulls_and_strings():
    from ytsaurus_tpu.operations.sort_op import sort_chunk
    rng = np.random.default_rng(11)
    n = 5000
    words = [b"w%04d" % i for i in range(200)]
    s = [words[int(rng.integers(0, 200))] if rng.random() > 0.1 else None
         for _ in range(n)]
    schema = TableSchema.make([("s", "string"), ("v", "int64")])
    chunk = ColumnarChunk.from_rows(
        schema, [(si, i) for i, si in enumerate(s)])
    out = sort_chunk(chunk, ["s"], descending=True)
    got = [r["s"] for r in out.to_rows()]
    want = sorted(s, key=lambda x: (x is None, () if x is None else
                                    tuple(-b for b in x)))
    assert got == want


def test_lsd_radix_argsort_matches_single_pass():
    """The large-N LSD path (one stable single-word sort per key word)
    must produce EXACTLY the single-pass variadic network's permutation —
    including stability across duplicate composite keys."""
    import jax.numpy as jnp

    from ytsaurus_tpu.ops.segments import stable_argsort_u32

    rng = np.random.default_rng(7)
    n = 5000
    words = [
        jnp.asarray(rng.integers(0, 50, n, dtype=np.uint32)),   # many dups
        jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32)),
        jnp.asarray(rng.integers(0, 3, n, dtype=np.uint32)),    # heavy dups
    ]
    single = np.asarray(stable_argsort_u32(words, lsd=False))
    radix = np.asarray(stable_argsort_u32(words, lsd=True))
    np.testing.assert_array_equal(single, radix)


def test_lsd_threshold_env_controls_default(monkeypatch):
    from ytsaurus_tpu.ops import segments

    monkeypatch.setattr(segments, "LSD_SORT_THRESHOLD", 10)
    import jax.numpy as jnp
    words = [jnp.asarray(np.arange(100, dtype=np.uint32)[::-1].copy()),
             jnp.asarray(np.zeros(100, dtype=np.uint32))]
    order = np.asarray(segments.stable_argsort_u32(words))
    np.testing.assert_array_equal(order, np.arange(100)[::-1])
