"""Query statistics (ref: client/query_client/query_statistics.h
TQueryStatistics — rows read/written, execute time, codegen time, incomplete
flags; aggregated across subqueries by the coordinator)."""

from __future__ import annotations

from dataclasses import dataclass, field, fields


@dataclass
class QueryStatistics:
    rows_read: int = 0
    rows_written: int = 0
    bytes_read: int = 0              # resident bytes of scanned planes
    execute_time: float = 0.0        # seconds, wall, incl. device sync
    compile_time: float = 0.0        # seconds building device programs
    compile_count: int = 0           # programs compiled (cache misses)
    cache_hits: int = 0
    # Compile-miss cause split (ISSUE 8): compile_count partitions into
    # never-seen plan shapes, known shapes meeting a new capacity/
    # binding shape (shape-spectrum growth), and LRU re-misses — so a
    # slow-query log entry answers "why did this recompile" directly.
    compile_new_fingerprint: int = 0
    compile_new_shape: int = 0
    compile_evicted: int = 0
    # Memory misses served by the persistent artifact tier (ISSUE 10):
    # deserialized ready executables, no fresh compile burn.  Fresh
    # compiles for a query = compile_count - compile_disk_hit.
    compile_disk_hit: int = 0
    shards_total: int = 0
    shards_pruned: int = 0
    shards_skipped: int = 0          # LIMIT early-exit left these unread
    shards_staged: int = 0           # shards actually fetched/decoded
    retries: int = 0                 # transient per-shard retry attempts
    joins_executed: int = 0
    # Whole-plan SPMD execution (ISSUE 12): 1 when the query was served
    # by the fused one-program rung (parallel/whole_plan.py); retries
    # count exchange-quota overflow re-runs (each a fresh pow2 rung of
    # the compile-once ladder, not a host sync).
    whole_plan: int = 0
    whole_plan_retries: int = 0
    # The pow2 capacity buckets this query's programs ran against
    # (ISSUE 8 satellite): per-query bucket churn is a shape-spectrum
    # leak EXPLAIN ANALYZE must surface.  A set, serialized sorted.
    capacity_buckets: set = field(default_factory=set)
    # Cost-based join plan (ISSUE 14): one entry per join stage in
    # EXECUTION order — chosen side strategy plus estimated-vs-actual
    # cardinality, so a bad plan is diagnosable from the slow log
    # without re-running.  Actuals/estimates ACCUMULATE across shard
    # programs (the host-coordinated cascade runs the stage per shard).
    join_plan: list = field(default_factory=list)
    # Brown-out ladder (ISSUE 17): non-zero when this response was
    # served DEGRADED — rung 1 reads the tablet snapshot cache within
    # the pool's staleness bound; degraded_staleness is the max
    # staleness (seconds) actually served.  Every degraded response is
    # tagged here, in the root span, and in the per-pool counters.
    degraded_rung: int = 0
    degraded_staleness: float = 0.0
    # Memory misses served by the CLUSTER artifact store (fetch-on-miss
    # from the chunk-backed tier): a replica joining mid-storm serves
    # its first queries with these instead of fresh compiles.
    compile_cluster_hit: int = 0
    # Which execution tier served the (last) dispatch of this query
    # (ISSUE 18): "compiled", "interpreted" (the no-compile numpy
    # tier), or "promoted-midstream" (first compiled serve after a
    # background promotion swapped the program in mid-traffic).  A
    # string — the serving counters skip it (only numerics fold).
    execution_tier: str = "compiled"
    # Which kernel-execution mode the string predicates ran in
    # (ISSUE 19): "encoded" (dict-code compares, the shipping default)
    # or "decoded" (at least one predicate fell back to the merged-
    # vocab remap-table path).  Same string/fold discipline as
    # execution_tier.
    execution_encoding: str = "encoded"
    # Mesh execution telemetry (ISSUE 20): the versioned per-program
    # blocks the fused SPMD path returns stacked with its result (and
    # the stitched rungs assemble from host values they already read).
    # The list holds full blocks (EXPLAIN ANALYZE renders them); the
    # numeric roll-ups below auto-fold into /serving/query_stats.
    mesh_blocks: list = field(default_factory=list)
    mesh_skew_max: float = 0.0
    mesh_exchange_bytes: int = 0
    mesh_quota_headroom: float = 0.0
    mesh_memory_watermark_bytes: int = 0

    def note_mesh_block(self, block: dict) -> None:
        """Fold one mesh telemetry block (whole_plan._mesh_block shape)
        into this query's statistics."""
        self.mesh_blocks.append(block)
        self.mesh_skew_max = max(self.mesh_skew_max,
                                 float(block.get("skew", 0.0)))
        self.mesh_exchange_bytes += int(block.get("exchange_bytes", 0))
        self.mesh_quota_headroom = max(
            self.mesh_quota_headroom,
            max([float(e.get("headroom", 0.0))
                 for e in block.get("exchanges", ())] or [0.0]))
        watermark = int(block.get("memory_watermark_bytes") or 0)
        self.mesh_memory_watermark_bytes = max(
            self.mesh_memory_watermark_bytes, watermark)

    def note_join_stage(self, position: int, table: str, strategy: str,
                        est_rows: int = 0, actual_rows=None) -> None:
        while len(self.join_plan) <= position:
            self.join_plan.append(None)
        entry = self.join_plan[position]
        if entry is None:
            entry = {"table": table, "strategy": strategy,
                     "est_rows": 0, "actual_rows": 0}
            self.join_plan[position] = entry
        entry["est_rows"] += int(est_rows)
        if actual_rows is not None:
            entry["actual_rows"] += int(actual_rows)

    def to_dict(self) -> dict:
        out = {}
        for f in fields(self):
            value = getattr(self, f.name)
            out[f.name] = sorted(value) if isinstance(value, set) \
                else value
        return out
