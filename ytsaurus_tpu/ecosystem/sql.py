"""ANSI/ClickHouse-flavored SQL over YT tables — the CHYT analog.

Ref mapping (yt/chyt):
  CHYT accepts ClickHouse SQL over YT tables     → translate_sql rewrites
  (`SELECT ... FROM "//path"`), converting          the dialect onto the
  schemas/blocks into the CH engine                 native QL engine (the
  (chyt/server/conversion.h)                        columnar XLA backend
                                                    IS the vectorized
                                                    engine here, so no
                                                    second execution
                                                    engine is embedded)
  query dispatch via Query Tracker engines       → registered as engine
  (server/query_tracker/chyt_engine.cpp)           "chyt" / alias "sql"

Dialect deltas handled:
  SELECT * / SELECT cols FROM "//path" | `//path` | [//path]
  SELECT ... FROM (SELECT ...)   — subqueries: the inner SELECT runs
      first and the outer query evaluates over its materialized rowset
      (CHYT's subquery pushdown collapses to two engine passes here)
  SELECT DISTINCT a, b FROM t    → GROUP BY a, b
  ANSI double-quoted / backticked identifiers → bare identifiers
  <> / ==             → != / =
  CH aggregate names  → native (uniq/uniqExact → cardinality, any →
      first, countIf/sumIf/avgIf/minIf/maxIf → agg(CASE WHEN c THEN x
      END) — aggregates skip nulls, matching the -If combinators)
  CH casts            → native (toInt64 → int64, toUInt64 → uint64,
      toFloat64 → double, toString is rejected [no string casts])
  LIMIT n OFFSET m / LIMIT m, n  → OFFSET m LIMIT n (QL clause order)
Strings must use single quotes (ANSI); double quotes always mean
identifiers, exactly like ClickHouse's default dialect.
"""

from __future__ import annotations

import re

from ytsaurus_tpu.errors import EErrorCode, YtError

_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<string>'(?:\\.|[^'\\])*')
  | (?P<dquote>"(?:[^"\\]|\\.)*")
  | (?P<btick>`[^`]*`)
  | (?P<bracket>\[[^\]]*\])
  | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?u?)
  | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<op><>|<=|>=|!=|==|\|\||[-+*/%(),=<>.])
""", re.VERBOSE)

_AGG_RENAMES = {
    "uniq": "cardinality",
    "uniqexact": "cardinality",
    "any": "first",
}

_CAST_RENAMES = {
    "toint64": "int64",
    "touint64": "uint64",
    "tofloat64": "double",
}

# aggIf(x, cond) → agg(CASE WHEN cond THEN x END); countIf(cond) →
# sum(CASE WHEN cond THEN 1 END).  Null-skipping aggregation gives the
# -If combinator semantics exactly.
_IF_COMBINATORS = {
    "countif": "sum",
    "sumif": "sum",
    "avgif": "avg",
    "minif": "min",
    "maxif": "max",
}

_TABLE_KEYWORDS = {"from", "join"}


def _tokens(text: str):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise YtError(f"SQL: cannot tokenize at {text[pos:pos + 20]!r}",
                          code=EErrorCode.QueryParseError)
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        yield kind, m.group()


def _rewrite_if_combinators(toks: "list[tuple[str, str]]"
                            ) -> "list[tuple[str, str]]":
    """aggIf(x, cond) → agg(CASE WHEN cond THEN x END); countIf(cond)
    → sum(CASE WHEN cond THEN 1 END).  Recursive: arguments may nest
    further combinators."""
    out: list = []
    i = 0
    while i < len(toks):
        kind, tok = toks[i]
        low = tok.lower()
        if kind == "word" and low in _IF_COMBINATORS and \
                i + 1 < len(toks) and toks[i + 1][1] == "(":
            depth = 0
            j = i + 1
            args: list = [[]]
            while j < len(toks):
                k2, t2 = toks[j]
                if t2 == "(":
                    depth += 1
                    if depth > 1:
                        args[-1].append((k2, t2))
                elif t2 == ")":
                    depth -= 1
                    if depth == 0:
                        break
                    args[-1].append((k2, t2))
                elif t2 == "," and depth == 1:
                    args.append([])
                else:
                    args[-1].append((k2, t2))
                j += 1
            if depth != 0:
                raise YtError(f"SQL: unbalanced parens in {tok}(...)",
                              code=EErrorCode.QueryParseError)
            args = [_rewrite_if_combinators(a) for a in args]
            if low == "countif":
                if len(args) != 1:
                    raise YtError("SQL: countIf takes one argument",
                                  code=EErrorCode.QueryParseError)
                cond, value = args[0], [("num", "1")]
            else:
                if len(args) != 2:
                    raise YtError(f"SQL: {tok} takes (value, condition)",
                                  code=EErrorCode.QueryParseError)
                value, cond = args
            # CH -If combinators return the aggregate's DEFAULT on an
            # empty match set (0 for count/sum); our aggregates return
            # NULL over empty sets, so those two wrap in if_null.
            zero_default = low in ("countif", "sumif")
            if zero_default:
                out.append(("word", "if_null"))
                out.append(("op", "("))
            out.append(("word", _IF_COMBINATORS[low]))
            out.append(("op", "("))
            out.append(("word", "CASE"))
            out.append(("word", "WHEN"))
            out.extend(cond)
            out.append(("word", "THEN"))
            out.extend(value)
            out.append(("word", "END"))
            out.append(("op", ")"))
            if zero_default:
                out.append(("op", ","))
                out.append(("num", "0"))
                out.append(("op", ")"))
            i = j + 1
            continue
        out.append((kind, tok))
        i += 1
    return out


_JOIN_MODIFIERS = {"inner", "any", "all", "outer", "left"}
_UNSUPPORTED_JOINS = {"cross", "right", "full", "semi", "anti", "asof"}
_ALIAS_RESERVED = {"on", "using", "where", "group", "order", "limit",
                   "having", "offset", "join", "left", "inner", "any",
                   "all", "cross", "as", "asc", "desc", "with", "union",
                   "settings"}


def _normalize_joins(toks):
    """CH join modifiers → QL's two forms.  INNER/ALL are QL's plain
    JOIN; ANY is accepted and behaves identically when the right side
    is key-unique (the dimension-join case — CH's own ALL default
    matches QL exactly); CROSS/RIGHT/FULL/ASOF have no QL counterpart
    and fail loudly."""
    out = []
    i, n = 0, len(toks)
    while i < n:
        kind, tok = toks[i]
        low = tok.lower()
        if kind == "word" and (low in _JOIN_MODIFIERS or
                               low in _UNSUPPORTED_JOINS):
            j = i
            mods = []
            while j < n and toks[j][0] == "word" and \
                    toks[j][1].lower() in (_JOIN_MODIFIERS |
                                           _UNSUPPORTED_JOINS):
                mods.append(toks[j][1].lower())
                j += 1
            if j < n and toks[j][0] == "word" and \
                    toks[j][1].lower() == "join":
                bad = [m for m in mods if m in _UNSUPPORTED_JOINS]
                if bad:
                    raise YtError(
                        f"SQL: {bad[0].upper()} JOIN is not supported",
                        code=EErrorCode.QueryUnsupported)
                if "left" in mods:
                    out.append(("word", "LEFT"))
                i = j
                continue
        out.append(toks[i])
        i += 1
    return out


def _strip_table_aliases(toks):
    """Remove `[table] AS alias` / `[table] alias` (QL has no table
    aliases) and return the alias names, so qualified column refs can
    drop their prefixes."""
    out = []
    aliases: set = set()
    i, n = 0, len(toks)
    while i < n:
        kind, tok = toks[i]
        out.append(toks[i])
        if kind == "word" and tok.lower() in _TABLE_KEYWORDS and \
                i + 1 < n:
            out.append(toks[i + 1])          # the table reference
            i += 1
            j = i + 1
            if j < n and toks[j][0] == "word" and \
                    toks[j][1].lower() == "as":
                j += 1
            if j < n and toks[j][0] == "word" and \
                    "." not in toks[j][1] and \
                    toks[j][1].lower() not in _ALIAS_RESERVED:
                aliases.add(toks[j][1])
                i = j                        # alias tokens dropped
        i += 1
    return out, aliases


def _on_to_using(toks):
    """After alias stripping, `ON g = g AND h = h` is the degenerate
    same-column equality CH writes as `f.g = d.g` — in QL's flat join
    namespace that reads as ambiguous self-equality, so rewrite it to
    `USING g, h`.  Mixed-name equalities stay as ON."""
    clause_ends = {"where", "group", "order", "limit", "having",
                   "offset", "join", "left", "settings"}
    out = []
    i, n = 0, len(toks)
    while i < n:
        kind, tok = toks[i]
        if kind == "word" and tok.lower() == "on":
            pairs = []
            j = i + 1
            while j + 2 < n and toks[j][0] == "word" and \
                    toks[j + 1] == ("op", "=") and \
                    toks[j + 2][0] == "word":
                pairs.append((toks[j][1], toks[j + 2][1]))
                j += 3
                if j < n and toks[j][0] == "word" and \
                        toks[j][1].lower() == "and":
                    j += 1
                    continue
                break
            # Rewrite ONLY when the whole ON clause was consumed as
            # same-name pairs and scanning stopped at a clause boundary
            # (or the end) — a trailing non-equality conjunct
            # (ON a=b AND v>5) must keep the original text, not lose
            # its AND.
            ends_clean = j >= n or (toks[j][0] == "word" and
                                    toks[j][1].lower() in clause_ends)
            if ends_clean and pairs and \
                    all(a == b for a, b in pairs):
                out.append(("word", "USING"))
                for p, (name, _) in enumerate(pairs):
                    if p:
                        out.append(("op", ","))
                    out.append(("word", name))
                i = j
                continue
        out.append(toks[i])
        i += 1
    return out


def translate_sql(sql: str) -> str:
    """ClickHouse/ANSI-flavored SELECT → native QL text (flat queries;
    subqueries are orchestrated by execute_sql)."""
    toks = _rewrite_if_combinators(list(_tokens(sql.strip().rstrip(";"))))
    toks = _normalize_joins(toks)
    toks, aliases = _strip_table_aliases(toks)
    if aliases:
        # Qualified refs (f.col) lose their table prefix: the joined
        # namespace is flat in QL.
        toks = [(kind, tok.split(".", 1)[1])
                if kind == "word" and "." in tok and
                tok.split(".", 1)[0] in aliases else (kind, tok)
                for kind, tok in toks]
        toks = _on_to_using(toks)
    out: list[str] = []
    expecting_table = False
    limit_value = None
    offset_value = None
    state = "normal"
    distinct_items: "list[str] | None" = None
    collecting_distinct = False
    for kind, tok in toks:
        low = tok.lower()
        if state == "limit" and kind == "num":
            limit_value = tok
            state = "limit_tail"
            continue
        if state == "limit_tail":
            if tok == ",":
                # CH shorthand: LIMIT offset, count.
                state = "limit_second"
                continue
            state = "normal"
        if state == "limit_second" and kind == "num":
            offset_value, limit_value = limit_value, tok
            state = "normal"
            continue
        if state == "offset" and kind == "num":
            offset_value = tok
            state = "normal"
            continue
        if kind == "word" and low == "limit":
            state = "limit"
            continue
        if kind == "word" and low == "offset":
            state = "offset"
            continue
        if kind == "word" and low == "distinct" and \
                out and out[-1].lower() == "select":
            collecting_distinct = True
            distinct_items = []
            continue
        if collecting_distinct:
            if kind == "word" and low in _TABLE_KEYWORDS:
                collecting_distinct = False
            elif kind == "word":
                distinct_items.append(tok)
                out.append(tok)
                continue
            elif tok == ",":
                out.append(tok)
                continue
            else:
                raise YtError(
                    "SQL: SELECT DISTINCT supports bare column lists "
                    "only", code=EErrorCode.QueryParseError)
        if expecting_table:
            out.append(_table_ref(kind, tok))
            expecting_table = False
            continue
        if kind == "word" and low in _TABLE_KEYWORDS:
            out.append(tok)
            expecting_table = True
            continue
        if kind == "dquote":
            # ANSI: double quotes are identifiers.
            out.append(tok[1:-1])
            continue
        if kind == "btick":
            out.append(tok[1:-1])
            continue
        if kind == "op" and tok == "<>":
            out.append("!=")
            continue
        if kind == "op" and tok == "==":
            out.append("=")
            continue
        if kind == "word" and low in _AGG_RENAMES:
            out.append(_AGG_RENAMES[low])
            continue
        if kind == "word" and low in _CAST_RENAMES:
            out.append(_CAST_RENAMES[low])
            continue
        if kind == "word" and low == "tostring":
            raise YtError("SQL: toString is not supported (no string "
                          "casts)", code=EErrorCode.QueryUnsupported)
        out.append(tok)
    if distinct_items:
        lows = [t.lower() for t in out]
        if "group" in lows:
            raise YtError("SQL: DISTINCT cannot combine with GROUP BY",
                          code=EErrorCode.QueryParseError)
        group_toks = ["GROUP", "BY"]
        for i, item in enumerate(distinct_items):
            if i:
                group_toks.append(",")
            group_toks.append(item)
        insert_at = lows.index("order") if "order" in lows else len(out)
        out[insert_at:insert_at] = group_toks
    ql = _respace(out)
    if ql.lower().startswith("select "):
        ql = ql[len("select "):]
    # QL clause order: ... OFFSET m LIMIT n.
    if offset_value is not None:
        ql += f" OFFSET {offset_value}"
    if limit_value is not None:
        ql += f" LIMIT {limit_value}"
    return ql


def _table_ref(kind: str, tok: str) -> str:
    if kind == "bracket":
        return tok                       # already QL form
    if kind == "dquote" or kind == "btick":
        return f"[{tok[1:-1]}]"
    if kind == "word":
        # Bare identifier: treat as an absolute cypress path component
        # under the root ("FROM my_table" → [//my_table], matching CHYT's
        # default-database-as-directory mapping).
        path = tok if tok.startswith("//") else f"//{tok}"
        return f"[{path}]"
    if kind == "string":
        return f"[{tok[1:-1]}]"
    raise YtError(f"SQL: bad table reference {tok!r}",
                  code=EErrorCode.QueryParseError)


_NO_SPACE_BEFORE = {",", ")", "."}
_NO_SPACE_AFTER = {"(", "."}


def _respace(tokens: "list[str]") -> str:
    parts: list[str] = []
    prev = ""
    for tok in tokens:
        if parts and tok not in _NO_SPACE_BEFORE and \
                prev not in _NO_SPACE_AFTER:
            parts.append(" ")
        parts.append(tok)
        prev = tok
    return "".join(parts)


_SUBQUERY_TABLE = "//__chyt_subquery__"


def _mask_strings(sql: str) -> str:
    """Same-length copy with quoted literals blanked, so clause searches
    and paren counting cannot match inside strings."""
    out = list(sql)
    i = 0
    while i < len(sql):
        if sql[i] == "'":
            j = i + 1
            while j < len(sql):
                if sql[j] == "\\":
                    j += 2
                    continue
                if sql[j] == "'":
                    break
                j += 1
            for k in range(i + 1, min(j, len(sql))):
                out[k] = "_"
            i = j + 1
        else:
            i += 1
    return "".join(out)


def _split_subquery(sql: str) -> "tuple[str, str] | None":
    """`outer FROM ( inner ) [AS alias] rest` → (inner SQL, outer SQL
    with the parenthesized subquery replaced by a synthetic table ref).
    Returns None when the query has no FROM-subquery."""
    masked = _mask_strings(sql)
    m = re.search(r"\bfrom\s*\(", masked, re.IGNORECASE)
    if m is None:
        return None
    start = masked.index("(", m.start())
    depth = 0
    for i in range(start, len(masked)):
        if masked[i] == "(":
            depth += 1
        elif masked[i] == ")":
            depth -= 1
            if depth == 0:
                inner = sql[start + 1: i]
                rest = sql[i + 1:]
                # Drop an optional `[AS] alias` after the subquery (QL
                # has one namespace; clause keywords are not aliases).
                alias = re.match(r"\s*(?:as\s+)?([A-Za-z_][A-Za-z0-9_]*)",
                                 rest, re.IGNORECASE)
                if alias and alias.group(1).lower() not in (
                        "where", "group", "order", "having", "limit",
                        "offset", "join", "on"):
                    rest = rest[alias.end():]
                outer = (sql[: m.start()] +
                         f"FROM [{_SUBQUERY_TABLE}]" + rest)
                return inner, outer
    raise YtError("SQL: unbalanced parens in FROM (...)",
                  code=EErrorCode.QueryParseError)


def _infer_schema(rows: "list[dict]"):
    """Column types from materialized subquery rows (None-only columns
    default to int64)."""
    from ytsaurus_tpu.schema import TableSchema
    if not rows:
        raise YtError("SQL: empty subquery result (schema unknown)",
                      code=EErrorCode.QueryExecutionError)
    kinds: dict = {}
    for row in rows:
        for name, value in row.items():
            if value is None:
                kinds.setdefault(name, None)
            elif isinstance(value, bool):
                kinds[name] = "boolean"
            elif isinstance(value, int):
                if kinds.get(name) not in ("double", "uint64"):
                    kinds[name] = "uint64" if value >= 2**63 else "int64"
            elif isinstance(value, float):
                kinds[name] = "double"
            elif isinstance(value, (bytes, str)):
                kinds[name] = "string"
    cols = [(name, kind or "int64") for name, kind in kinds.items()]
    return TableSchema.make(cols)


def execute_sql(client, sql: str) -> "list[dict]":
    """CH-dialect execution, including one level of FROM-subquery: the
    inner SELECT runs first and the outer query evaluates over its
    materialized rowset (CHYT collapses subqueries into engine passes
    the same way; here each pass IS a full coordinated query)."""
    sql = sql.strip().rstrip(";")
    split = _split_subquery(sql)
    if split is None:
        return client.select_rows(translate_sql(sql))
    inner_sql, outer_sql = split
    inner_rows = execute_sql(client, inner_sql)     # nested levels recurse
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.query import select_rows as chunk_select
    decoded = [{k: (v.decode() if isinstance(v, bytes) else v)
                for k, v in r.items()} for r in inner_rows]
    if decoded:
        schema = _infer_schema(decoded)
    else:
        # Empty inner result is routine (selective WHERE), not an
        # error: take the schema from the inner PLAN so the outer query
        # can still aggregate to its CH-correct empty/zero result.
        schema = _planned_schema(client, inner_sql)
    chunk = ColumnarChunk.from_rows(schema, decoded)
    result = chunk_select(translate_sql(outer_sql),
                          {_SUBQUERY_TABLE: chunk})
    return result.to_rows()


def _planned_schema(client, inner_sql: str):
    """Output schema of a (flat) inner query via the QL builder — used
    when no rows materialized to infer types from."""
    from ytsaurus_tpu.client import _SchemaResolver
    from ytsaurus_tpu.query.builder import build_query
    if _split_subquery(inner_sql) is not None:
        raise YtError(
            "SQL: empty nested subquery result (schema unknown)",
            code=EErrorCode.QueryExecutionError)
    plan = build_query(translate_sql(inner_sql), _SchemaResolver(client))
    return plan.output_schema().to_unsorted()


def register() -> None:
    from ytsaurus_tpu.server.query_tracker import register_engine
    register_engine("chyt", execute_sql)
    register_engine("sql", execute_sql)


register()
