"""Persistent AOT compile-artifact cache (ISSUE 10 tentpole, piece c).

The disk tier of the compile ladder: memory LRU → THIS → fresh compile.
AOT-compiled executables (jax serialize_executable products of the
evaluator's `lower().compile()`) persist to a bounded directory keyed
by (plan shape fingerprint, capacity bucket, binding shapes/structure,
backend, jax version), so a rolling restart of query daemons
WARM-STARTS: the first query of each shape deserializes a ready
executable in milliseconds instead of cold-compiling it — the XLA
analog of the reference's on-disk LLVM image cache discipline
(engine_api/cg_cache.h keyed by llvm::FoldingSet fingerprint).

Safety posture is LOUD-BUT-SAFE: every artifact carries a versioned
JSON header that is refused loudly (warning log + `disk_errors`
sensor) on an aot-schema / jax-version / backend mismatch — the same
versioned-capture discipline as the workload log — and ANY load
failure (truncated file, pickle corruption, deserialize error) falls
back to a fresh compile; a query can never fail because the disk tier
rotted.  The directory is size-capped with oldest-mtime eviction
(loads touch mtime, so eviction is LRU-ish across processes sharing
the cache dir).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Optional

import jax

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils import sanitizers

logger = get_logger("AotCache")

_FP_PUBLISH = failpoints.register_site(
    "aot.publish",
    error=lambda s: YtError(f"injected artifact publish failure at {s}",
                            code=EErrorCode.TransportError))
_FP_FETCH = failpoints.register_site(
    "aot.fetch",
    error=lambda s: YtError(f"injected artifact fetch failure at {s}",
                            code=EErrorCode.TransportError))

# Bump when the on-disk artifact layout changes incompatibly: readers
# refuse mismatched headers loudly instead of unpickling garbage.
AOT_SCHEMA_VERSION = 1

_SUFFIX = ".aot"


def _backend() -> str:
    try:
        return jax.default_backend()
    except Exception:   # noqa: BLE001 — backend probe must never raise
        return "unknown"


def artifact_digest(key: tuple) -> str:
    """The cluster-stable name of one compile artifact: digests the
    full cache key (fingerprint, capacity, binding shapes + structure —
    plain ints/strings, identical across processes) plus backend, jax
    version, and the artifact schema, so replicas of one homogeneous
    cluster agree on names and an upgraded replica simply sees a cold
    tier."""
    text = repr((key, _backend(), jax.__version__, AOT_SCHEMA_VERSION))
    return hashlib.sha256(text.encode()).hexdigest()[:40]


def encode_artifact(compiled, fingerprint: str,
                    compile_seconds: float) -> bytes:
    """Serialize one AOT executable to the shared artifact wire/disk
    format: one versioned JSON header line + the pickled
    serialize_executable product.  Raises on unserializable
    executables — callers treat that as 'cannot persist'."""
    from jax.experimental.serialize_executable import serialize
    payload, in_tree, out_tree = serialize(compiled)
    header = json.dumps({
        "aot_schema": AOT_SCHEMA_VERSION,
        "jax": jax.__version__,
        "backend": _backend(),
        "fingerprint": fingerprint,
        "compile_seconds": round(compile_seconds, 6),
        "created_at": time.time(),
    }).encode() + b"\n"
    return header + pickle.dumps((payload, in_tree, out_tree))


def _artifact_header_problem(header) -> Optional[str]:
    if not isinstance(header, dict):
        return "missing header"
    if header.get("aot_schema") != AOT_SCHEMA_VERSION:
        return (f"aot schema {header.get('aot_schema')!r}, this "
                f"build speaks {AOT_SCHEMA_VERSION}")
    if header.get("jax") != jax.__version__:
        return (f"compiled under jax {header.get('jax')!r}, this "
                f"process runs {jax.__version__}")
    if header.get("backend") != _backend():
        return (f"compiled for backend {header.get('backend')!r}, "
                f"this process runs {_backend()!r}")
    return None


def decode_artifact(blob: bytes, origin: str):
    """Deserialize one artifact blob back into a loaded executable, or
    None — loud-but-safe, same versioned-header discipline as the disk
    tier (a rotted or mismatched artifact falls back to a fresh
    compile, never fails the query)."""
    try:
        newline = blob.index(b"\n")
        header = json.loads(blob[:newline] or b"{}")
        problem = _artifact_header_problem(header)
        if problem is not None:
            logger.warning("refusing compile artifact %s: %s",
                           origin, problem)
            return None
        payload, in_tree, out_tree = pickle.loads(blob[newline + 1:])
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )
        return deserialize_and_load(payload, in_tree, out_tree)
    except Exception as exc:   # noqa: BLE001 — loud-but-safe
        logger.warning("compile artifact %s unreadable (%r); "
                       "falling back to fresh compile", origin, exc)
        return None


class DiskCompileCache:
    """One process's view of an on-disk compile-artifact directory."""

    def __init__(self, config):
        self._dir = config.disk_cache_dir
        self._capacity_bytes = config.disk_cache_capacity_bytes
        self._min_seconds = config.disk_cache_min_compile_seconds
        # guards: bytes_n, files_n (gauge mirrors), eviction scans;
        # load/store file I/O itself is atomic-per-file (tmp+replace).
        # hot=False: this lock intentionally covers disk scans.
        self._lock = sanitizers.register_lock(
            "aot_cache.DiskCompileCache._lock", hot=False)
        self.hits_n = 0
        self.misses_n = 0
        self.errors_n = 0
        self.stores_n = 0
        self.evictions_n = 0
        prof = Profiler("/query/compile_cache")
        self._hits = prof.counter("disk_hits")
        self._misses = prof.counter("disk_misses")
        self._errors = prof.counter("disk_errors")
        self._bytes = prof.gauge("disk_bytes")
        self._files = prof.gauge("disk_files")
        self._refresh_gauges()

    # -- keying ----------------------------------------------------------------

    def _path(self, key: tuple) -> str:
        """Artifact path for one full compile-cache key — the same
        `artifact_digest` name the cluster store uses, so the tiers
        agree on identity."""
        return os.path.join(self._dir, artifact_digest(key) + _SUFFIX)

    # -- load ------------------------------------------------------------------

    def load(self, key: tuple):
        """Deserialize the executable for `key`, or None (counted as a
        disk miss / error).  Never raises."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                header_line = f.readline()
                header = json.loads(header_line or b"{}")
                problem = self._header_problem(header)
                if problem is not None:
                    logger.warning(
                        "refusing compile artifact %s: %s", path, problem)
                    self._count_error()
                    return None
                payload, in_tree, out_tree = pickle.loads(f.read())
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )
            fn = deserialize_and_load(payload, in_tree, out_tree)
        except FileNotFoundError:
            self._count_miss()
            return None
        except Exception as exc:   # noqa: BLE001 — loud-but-safe: a
            # rotted artifact (truncation, pickle/deserialize failure)
            # must fall back to a fresh compile, never fail the query.
            logger.warning("compile artifact %s unreadable (%r); "
                           "falling back to fresh compile", path, exc)
            self._count_error()
            return None
        try:
            os.utime(path)           # LRU touch for mtime eviction
        except OSError:
            pass
        with self._lock:
            self.hits_n += 1
        self._hits.increment()
        return fn

    def _header_problem(self, header: dict) -> Optional[str]:
        return _artifact_header_problem(header)

    # -- store -----------------------------------------------------------------

    def store(self, key: tuple, compiled, fingerprint: str,
              compile_seconds: float) -> bool:
        """Serialize one freshly AOT-compiled executable.  Best-effort:
        failures are counted + logged, never raised."""
        if compile_seconds < self._min_seconds:
            return False
        path = self._path(key)
        try:
            blob = encode_artifact(compiled, fingerprint,
                                   compile_seconds)
            os.makedirs(self._dir, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except Exception as exc:   # noqa: BLE001 — persistence is an
            # optimization; a full disk or an unserializable executable
            # (callbacks, donated buffers) must not fail the query.
            logger.warning("cannot persist compile artifact %s: %r",
                           path, exc)
            self._count_error()
            return False
        with self._lock:
            self.stores_n += 1
            self._evict_locked()
        return True

    # -- bounds ----------------------------------------------------------------

    def _scan_locked(self) -> list[tuple[float, int, str]]:
        """(mtime, size, path) per artifact; unreadable entries skipped."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self._dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, path))
        return out

    def _evict_locked(self) -> None:
        entries = self._scan_locked()
        total = sum(size for _mt, size, _p in entries)
        if self._capacity_bytes and total > self._capacity_bytes:
            for _mtime, size, path in sorted(entries):
                try:
                    os.remove(path)
                except OSError:
                    continue
                self.evictions_n += 1
                total -= size
                if total <= self._capacity_bytes:
                    break
            entries = self._scan_locked()
            total = sum(size for _mt, size, _p in entries)
        self._bytes.set(float(total))
        self._files.set(float(len(entries)))

    def _refresh_gauges(self) -> None:
        with self._lock:
            entries = self._scan_locked()
            self._bytes.set(float(sum(s for _m, s, _p in entries)))
            self._files.set(float(len(entries)))

    def _count_miss(self) -> None:
        with self._lock:
            self.misses_n += 1
        self._misses.increment()

    def _count_error(self) -> None:
        with self._lock:
            self.errors_n += 1
        self._errors.increment()

    # -- views -----------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            entries = self._scan_locked()
            return {
                "dir": self._dir,
                "hits": self.hits_n,
                "misses": self.misses_n,
                "errors": self.errors_n,
                "stores": self.stores_n,
                "evictions": self.evictions_n,
                "files": len(entries),
                "bytes": sum(s for _m, s, _p in entries),
                "capacity_bytes": self._capacity_bytes,
            }


# -- cluster tier (ISSUE 17) ---------------------------------------------------

class ClusterArtifactStore:
    """The CLUSTER tier of the compile ladder: memory LRU → disk →
    THIS → fresh compile.  Artifacts publish-on-compile to the
    chunk-backed remote store and fetch-on-miss, so a replica added
    mid-storm serves its first query of every hot shape by
    deserializing a ready executable over the wire — zero inline
    compiles on scale-out (the elastic arm of the JIT cold-start tax,
    PAPERS.md arxiv 2311.04692).

    `blob_store` is anything with `put_blob(chunk_id, bytes)` /
    `get_blob(chunk_id)` — FsChunkStore locally, RpcChunkStore across
    daemons (rendezvous placement + replication ride for free).
    Artifact names are `aot-<artifact_digest>`: content-addressed, so
    replicas of one homogeneous cluster converge on one copy and a
    double publish is idempotent.

    Same loud-but-safe posture as the disk tier: every failure is
    counted + logged, never raised into a query.  Failpoints
    `aot.publish` / `aot.fetch` inject store faults (the chaos leg's
    artifact-store failure)."""

    _CHUNK_PREFIX = "aot-"

    def __init__(self, blob_store, min_compile_seconds: float = 0.0):
        self._store = blob_store
        self._min_seconds = min_compile_seconds
        # guards: hits_n, misses_n, errors_n, publishes_n
        self._lock = sanitizers.register_lock(
            "aot_cache.ClusterArtifactStore._lock", hot=False)
        self.hits_n = 0
        self.misses_n = 0
        self.errors_n = 0
        self.publishes_n = 0
        prof = Profiler("/query/compile_cache")
        self._hits = prof.counter("cluster_hits")
        self._misses = prof.counter("cluster_misses")
        self._errors = prof.counter("cluster_errors")
        self._publishes = prof.counter("cluster_publishes")

    def _chunk_id(self, key: tuple) -> str:
        return self._CHUNK_PREFIX + artifact_digest(key)

    def fetch(self, key: tuple):
        """Fetch-on-miss: the loaded executable for `key`, or None
        (counted as a cluster miss / error).  Never raises."""
        chunk_id = self._chunk_id(key)
        try:
            _FP_FETCH.hit()
            blob = self._store.get_blob(chunk_id)
        except Exception as exc:   # noqa: BLE001 — a missing or
            # unreachable artifact falls back to the next tier (fresh
            # compile), never fails the query.  Absence and store
            # failure both land here: blob stores raise on unknown ids.
            self._tally("misses_n", self._misses)
            logger.debug("cluster artifact %s unavailable: %r",
                         chunk_id, exc)
            return None
        fn = decode_artifact(blob, f"cluster:{chunk_id}")
        if fn is None:
            self._tally("errors_n", self._errors)
            return None
        self._tally("hits_n", self._hits)
        return fn

    def publish(self, key: tuple, compiled, fingerprint: str,
                compile_seconds: float) -> bool:
        """Publish-on-compile: push one freshly AOT-compiled executable
        to the cluster store.  Best-effort; returns True on publish."""
        if compile_seconds < self._min_seconds:
            return False
        chunk_id = self._chunk_id(key)
        try:
            _FP_PUBLISH.hit()
            blob = encode_artifact(compiled, fingerprint,
                                   compile_seconds)
            self._store.put_blob(chunk_id, blob)
        except Exception as exc:   # noqa: BLE001 — persistence is an
            # optimization; an unserializable executable or a down
            # store must not fail the query.
            logger.warning("cannot publish compile artifact %s: %r",
                           chunk_id, exc)
            self._tally("errors_n", self._errors)
            return False
        self._tally("publishes_n", self._publishes)
        return True

    def _tally(self, name: str, counter) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        counter.increment()

    def snapshot(self) -> dict:
        with self._lock:
            return {"hits": self.hits_n, "misses": self.misses_n,
                    "errors": self.errors_n,
                    "publishes": self.publishes_n}


# -- globals -------------------------------------------------------------------

_cache: Optional[DiskCompileCache] = None
_cache_dir: Optional[str] = None
_cluster_store: Optional[ClusterArtifactStore] = None
# guards: _cache, _cache_dir, _cluster_store
_cache_lock = sanitizers.register_lock("aot_cache._cache_lock",
                                       hot=False)


def get_cluster_store() -> Optional[ClusterArtifactStore]:
    """The process's cluster artifact tier, or None when no daemon has
    bound one (set_cluster_store) — the default for plain clients."""
    with _cache_lock:
        return _cluster_store


def set_cluster_store(store: Optional[ClusterArtifactStore]) -> None:
    """Bind (or clear, with None) the cluster artifact tier.  Daemons
    call this once their chunk store is up; the evaluator then
    fetches-on-miss and publishes-on-compile through it."""
    global _cluster_store
    with _cache_lock:
        _cluster_store = store


def get_disk_cache() -> Optional[DiskCompileCache]:
    """The process disk tier, or None when CompileConfig.disk_cache_dir
    is unset (the default — tests and serving opt in explicitly)."""
    global _cache, _cache_dir
    from ytsaurus_tpu.config import compile_config
    cfg = compile_config()
    if not cfg.disk_cache_dir:
        return None
    with _cache_lock:
        if _cache is None or _cache_dir != cfg.disk_cache_dir:
            _cache = DiskCompileCache(cfg)
            _cache_dir = cfg.disk_cache_dir
        return _cache


def configure(cfg) -> None:
    """Rebind the global disk cache (called by config.set_compile_config;
    None restores the lazy default)."""
    global _cache, _cache_dir
    with _cache_lock:
        if cfg is None or not cfg.disk_cache_dir:
            _cache, _cache_dir = None, None
        else:
            _cache = DiskCompileCache(cfg)
            _cache_dir = cfg.disk_cache_dir
