"""Kafka proxy: the Kafka wire protocol over ordered tables.

Ref: yt/yt/server/kafka_proxy/server.h (+ the kafka protocol codec under
yt/yt/client/kafka/) — the reference terminates the Kafka binary
protocol in front of queues so stock Kafka clients can produce/consume
YT queues.  The proxy speaks v0 for every API (the baseline all client
libraries support) and negotiates up to v1 for Produce/Fetch via
ApiVersions (v1 adds throttle_time_ms framing to those responses):

  ApiVersions(18)  Metadata(3)  ListOffsets(2)  Produce(0..1)
  Fetch(0..1)  OffsetCommit(8)  OffsetFetch(9)
  FindCoordinator(10)  JoinGroup(11)  Heartbeat(12)  LeaveGroup(13)
  SyncGroup(14)

Topic model: topic `name` maps to the ordered table `<root>/name`
(auto-created on first Metadata when auto_create, like Kafka's
auto.create.topics).  One partition (0) per topic — the ordered-table
model; partitioned topics become N tables, as the reference maps tablet
ranges.  Messages are (key, value) byte strings riding an ordered table
with string columns `key` and `value`; Kafka offsets ARE $row_index, so
monotone/gapless offset semantics fall straight out of the queue model.
Consumer groups map to consumer tables under `<root>/.consumers/<group>`
through the queue-agent registration machinery.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import zlib
from typing import Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.server.kafka_groups import GroupCoordinator
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("kafka_proxy")

TOPIC_SCHEMA = TableSchema.make([("key", "string"), ("value", "string")])

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14
API_VERSIONS = 18

# api key → highest supported version.  v1 adds throttle_time_ms to
# Produce/Fetch responses (request bodies are unchanged), which is what
# ApiVersions-aware clients negotiate down to; everything else is v0.
SUPPORTED_VERSIONS = {
    API_PRODUCE: 1, API_FETCH: 1, API_LIST_OFFSETS: 0, API_METADATA: 0,
    API_OFFSET_COMMIT: 0, API_OFFSET_FETCH: 0, API_FIND_COORDINATOR: 0,
    API_JOIN_GROUP: 0, API_HEARTBEAT: 0, API_LEAVE_GROUP: 0,
    API_SYNC_GROUP: 0, API_VERSIONS: 0,
}
SUPPORTED_APIS = tuple(SUPPORTED_VERSIONS)

ERR_NONE = 0
ERR_CORRUPT_MESSAGE = 2
ERR_UNKNOWN_TOPIC = 3
ERR_UNSUPPORTED_VERSION = 35


# -- wire primitives (big-endian, per the public Kafka protocol spec) --------

class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        out = self.data[self.pos: self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)


def i8(v: int) -> bytes:
    return struct.pack(">b", v)


def i16(v: int) -> bytes:
    return struct.pack(">h", v)


def i32(v: int) -> bytes:
    return struct.pack(">i", v)


def i64(v: int) -> bytes:
    return struct.pack(">q", v)


def string(v: "Optional[str]") -> bytes:
    if v is None:
        return i16(-1)
    raw = v.encode("utf-8")
    return i16(len(raw)) + raw


def bytes_(v: "Optional[bytes]") -> bytes:
    if v is None:
        return i32(-1)
    return i32(len(v)) + v


def array(items: list) -> bytes:
    return i32(len(items)) + b"".join(items)


def encode_message(key: "Optional[bytes]", value: "Optional[bytes]",
                   offset: int) -> bytes:
    """One MessageSet entry (message format v0): offset + size + message,
    where message = crc32(magic..value) | magic | attrs | key | value."""
    body = i8(0) + i8(0) + bytes_(key) + bytes_(value)
    crc = struct.unpack(">i", struct.pack(">I",
                                          zlib.crc32(body) & 0xFFFFFFFF))[0]
    return i64(offset) + i32(len(body) + 4) + i32(crc) + body


def decode_message_set(data: bytes) -> "list[tuple[Optional[bytes], Optional[bytes]]]":
    """(key, value) pairs out of a v0 MessageSet blob (offsets assigned
    by the broker are ignored on the produce path)."""
    out = []
    r = Reader(data)
    while r.pos + 12 <= len(r.data):
        r.i64()                     # producer-side offset: ignored
        size = r.i32()
        if r.pos + size > len(r.data):
            break                   # partial trailing message: drop
        msg = Reader(r._take(size))
        msg.i32()                   # crc (trusted transport here)
        msg.i8()                    # magic
        attributes = msg.i8()
        if attributes & 0x07:
            # Compressed wrapper message: storing the compressed blob
            # verbatim would hand consumers garbage re-framed as
            # uncompressed.  Refuse loudly (clients fall back to
            # compression.type=none).
            raise YtError("compressed message sets are not supported",
                          code=ERR_CORRUPT_MESSAGE)
        key = msg.bytes_()
        value = msg.bytes_()
        out.append((key, value))
    return out


# -- the proxy ---------------------------------------------------------------

class KafkaProxy:
    """One TCP listener speaking Kafka v0 in front of a YtClient."""

    def __init__(self, client, topic_root: str = "//kafka",
                 host: str = "127.0.0.1", port: int = 0,
                 auto_create: bool = True, fetch_max_rows: int = 1000):
        self.client = client
        self.topic_root = topic_root.rstrip("/")
        self.auto_create = auto_create
        self.fetch_max_rows = fetch_max_rows
        proxy = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        header = _recv_exact(self.request, 4)
                        if header is None:
                            return
                        (length,) = struct.unpack(">i", header)
                        payload = _recv_exact(self.request, length)
                        if payload is None:
                            return
                        try:
                            response = proxy.handle_request(payload)
                        except Exception as exc:  # noqa: BLE001
                            # Unparseable request or internal failure:
                            # close the connection (broker behavior for
                            # protocol violations) rather than kill the
                            # server thread or desync framing.
                            logger.warning("kafka request failed: %s",
                                           exc)
                            return
                        if response is None:
                            continue            # acks=0: no response
                        self.request.sendall(
                            struct.pack(">i", len(response)) + response)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: "threading.Thread | None" = None
        # Consumer-group membership (ref group_coordinator.h): this
        # proxy IS every group's coordinator (single-proxy model).
        self.groups = GroupCoordinator()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "KafkaProxy":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="kafka-proxy")
        self._thread.start()
        logger.info("kafka proxy serving on %s (topics under %s)",
                    self.address, self.topic_root)
        return self

    def stop(self) -> None:
        self.groups.stop()
        self._server.shutdown()
        self._server.server_close()

    # -- topic plumbing --------------------------------------------------------

    def _topic_path(self, topic: str) -> str:
        if "/" in topic or topic.startswith("."):
            raise YtError(f"Bad topic name {topic!r}")
        return f"{self.topic_root}/{topic}"

    def _topic_exists(self, topic: str) -> bool:
        try:
            return self.client.exists(self._topic_path(topic))
        except YtError:
            return False

    def _ensure_topic(self, topic: str) -> bool:
        if self._topic_exists(topic):
            self._tablet(topic)     # mount on demand (restarted primary)
            return True
        if not self.auto_create:
            return False
        path = self._topic_path(topic)
        try:
            self.client.create("table", path, recursive=True,
                               attributes={"schema": TOPIC_SCHEMA,
                                           "dynamic": True})
        except YtError:
            # Concurrent auto-create from another connection: fine as
            # long as the table exists (mount below is idempure enough).
            if not self._topic_exists(topic):
                return False
        try:
            self.client.mount_table(path)
        except YtError:
            pass                    # already mounted by the racer
        return True

    def _tablet(self, topic: str):
        """The topic's ordered tablet, mounting on demand (a restarted
        primary serves existing topics without an explicit mount)."""
        path = self._topic_path(topic)
        try:
            (tablet,) = self.client._mounted_tablets(path)
        except YtError:
            self.client.mount_table(path)
            (tablet,) = self.client._mounted_tablets(path)
        return tablet

    def _consumer_path(self, group: str) -> str:
        return f"{self.topic_root}/.consumers/{group}"

    # -- request dispatch ------------------------------------------------------

    def handle_request(self, payload: bytes) -> "Optional[bytes]":
        """Returns the response frame body, or None when the protocol
        says no response is sent (acks=0 produce, fatal version
        mismatch handled by closing)."""
        r = Reader(payload)
        api_key = r.i16()
        api_version = r.i16()
        correlation_id = r.i32()
        r.string()                  # client_id
        max_version = SUPPORTED_VERSIONS.get(api_key)
        if max_version is None:
            # Unknown API key: the right diagnosis is the KEY, and the
            # connection closes (no version of it has a known shape).
            raise YtError(f"unsupported api key {api_key}",
                          code=ERR_UNSUPPORTED_VERSION)
        if not 0 <= api_version <= max_version:
            if api_key == API_VERSIONS:
                # Spec: answer UNSUPPORTED_VERSION in the v0 shape so
                # the client can retry with a version we speak.
                return i32(correlation_id) + i16(
                    ERR_UNSUPPORTED_VERSION) + array(
                    [i16(k) + i16(0) + i16(SUPPORTED_VERSIONS[k])
                     for k in SUPPORTED_APIS])
            # Body shapes differ beyond the advertised version: raising
            # makes the connection handler CLOSE the socket (a None
            # return would mean "no response due" and leave the client
            # hanging on an open connection).
            raise YtError(f"unsupported api version {api_version} for "
                          f"key {api_key}",
                          code=ERR_UNSUPPORTED_VERSION)
        handler = {
            API_VERSIONS: self._api_versions,
            API_METADATA: self._metadata,
            API_PRODUCE: lambda rr: self._produce(rr, api_version),
            API_FETCH: lambda rr: self._fetch(rr, api_version),
            API_LIST_OFFSETS: self._list_offsets,
            API_OFFSET_COMMIT: self._offset_commit,
            API_OFFSET_FETCH: self._offset_fetch,
            API_FIND_COORDINATOR: self._find_coordinator,
            API_JOIN_GROUP: self._join_group,
            API_HEARTBEAT: self._heartbeat,
            API_LEAVE_GROUP: self._leave_group,
            API_SYNC_GROUP: self._sync_group,
        }.get(api_key)
        body = handler(r)
        if body is None:
            return None             # acks=0 produce
        return i32(correlation_id) + body

    def _api_versions(self, r: Reader) -> bytes:
        return i16(ERR_NONE) + array(
            [i16(k) + i16(0) + i16(SUPPORTED_VERSIONS[k])
             for k in SUPPORTED_APIS])

    def _metadata(self, r: Reader) -> bytes:
        n = r.i32()
        topics = [r.string() for _ in range(max(n, 0))]
        if not topics:
            # All known topics: children of the topic root.
            try:
                topics = [t for t in self.client.list(self.topic_root)
                          if not t.startswith(".")]
            except YtError:
                topics = []
        brokers = array([i32(0) + string(self.host) + i32(self.port)])
        topic_bodies = []
        for topic in topics:
            ok = self._ensure_topic(topic)
            partitions = array([
                i16(ERR_NONE) + i32(0) + i32(0) +
                array([i32(0)]) + array([i32(0)])]) if ok else array([])
            topic_bodies.append(
                i16(ERR_NONE if ok else ERR_UNKNOWN_TOPIC) +
                string(topic) + partitions)
        return brokers + array(topic_bodies)

    def _produce(self, r: Reader,
                 version: int = 0) -> "Optional[bytes]":
        acks = r.i16()
        r.i32()                     # timeout
        n_topics = r.i32()
        topic_bodies = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            part_bodies = []
            for _ in range(n_parts):
                partition = r.i32()
                message_set = r.bytes_() or b""
                try:
                    records = decode_message_set(message_set)
                except YtError:
                    part_bodies.append(
                        i32(partition) + i16(ERR_CORRUPT_MESSAGE) +
                        i64(-1))
                    continue
                if not self._ensure_topic(topic):
                    part_bodies.append(
                        i32(partition) + i16(ERR_UNKNOWN_TOPIC) + i64(-1))
                    continue
                rows = [{"key": k, "value": v} for k, v in records]
                base = self.client.push_queue(
                    self._topic_path(topic), rows) if rows else -1
                part_bodies.append(
                    i32(partition) + i16(ERR_NONE) + i64(base))
            topic_bodies.append(string(topic) + array(part_bodies))
        if acks == 0:
            # The client will not read a response; sending one would
            # desync its next request's framing.
            return None
        out = array(topic_bodies)
        if version >= 1:
            out += i32(0)               # throttle_time_ms (v1 tail)
        return out

    def _fetch(self, r: Reader, version: int = 0) -> bytes:
        import time as _time
        r.i32()                     # replica_id
        max_wait_ms = r.i32()
        min_bytes = r.i32()
        n_topics = r.i32()
        requests = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            parts = []
            for _ in range(n_parts):
                parts.append((r.i32(), r.i64(), r.i32()))
            requests.append((topic, parts))
        # Kafka long-poll: block up to max_wait_ms until data exists
        # past the requested offsets (capped — a poller must not pin a
        # handler thread forever).  The wait polls only row counts; the
        # full response is built ONCE on wake/timeout.  Error responses
        # (unknown topic) and min_bytes<=0 return immediately, like a
        # real broker.
        deadline = _time.monotonic() + min(max(max_wait_ms, 0), 30_000) \
            / 1000.0
        if min_bytes > 0:
            while _time.monotonic() < deadline:
                ready = False
                for topic, parts in requests:
                    if not self._topic_exists(topic):
                        ready = True            # error body: answer now
                        break
                    high = self._tablet(topic).row_count
                    if any(offset < high for _, offset, _ in parts):
                        ready = True
                        break
                if ready:
                    break
                _time.sleep(min(0.05,
                                max(deadline - _time.monotonic(), 0)))
        topic_bodies, _ = self._build_fetch(requests)
        prefix = i32(0) if version >= 1 else b""    # throttle_time_ms
        return prefix + array(topic_bodies)

    def _build_fetch(self, requests) -> "tuple[list[bytes], int]":
        topic_bodies = []
        data_bytes = 0
        for topic, parts in requests:
            part_bodies = []
            for partition, fetch_offset, max_bytes in parts:
                if not self._topic_exists(topic):
                    part_bodies.append(
                        i32(partition) + i16(ERR_UNKNOWN_TOPIC) + i64(-1) +
                        bytes_(b""))
                    continue
                path = self._topic_path(topic)
                tablet = self._tablet(topic)
                high = tablet.row_count
                rows = self.client.pull_queue(
                    path, offset=fetch_offset,
                    limit=self.fetch_max_rows) if fetch_offset < high else []
                out = bytearray()
                for idx, row in enumerate(rows):
                    msg = encode_message(row.get("key"), row.get("value"),
                                         fetch_offset + idx)
                    if len(out) + len(msg) > max_bytes and out:
                        break
                    out.extend(msg)
                data_bytes += len(out)
                part_bodies.append(
                    i32(partition) + i16(ERR_NONE) + i64(high) +
                    bytes_(bytes(out)))
            topic_bodies.append(string(topic) + array(part_bodies))
        return topic_bodies, data_bytes

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()                     # replica_id
        n_topics = r.i32()
        topic_bodies = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            part_bodies = []
            for _ in range(n_parts):
                partition = r.i32()
                timestamp = r.i64()
                r.i32()             # max_num_offsets
                if not self._topic_exists(topic):
                    part_bodies.append(
                        i32(partition) + i16(ERR_UNKNOWN_TOPIC) + array([]))
                    continue
                tablet = self._tablet(topic)
                if timestamp == -2:             # earliest
                    offset = getattr(tablet, "trimmed_count", 0)
                else:                           # latest
                    offset = tablet.row_count
                part_bodies.append(
                    i32(partition) + i16(ERR_NONE) + array([i64(offset)]))
            topic_bodies.append(string(topic) + array(part_bodies))
        return array(topic_bodies)

    def _offset_commit(self, r: Reader) -> bytes:
        group = r.string()
        n_topics = r.i32()
        topic_bodies = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            part_bodies = []
            for _ in range(n_parts):
                partition = r.i32()
                offset = r.i64()
                r.string()          # metadata
                err = ERR_NONE
                try:
                    path = self._topic_path(topic)
                    consumer = self._consumer_path(group)
                    if not self.client.exists(consumer):
                        self.client.register_queue_consumer(
                            path, consumer, vital=False)
                    regs = self.client._table_node(path).attributes.get(
                        "registrations") or {}
                    if consumer not in regs:
                        self.client.register_queue_consumer(
                            path, consumer, vital=False)
                    self.client.advance_consumer(consumer, path, offset)
                except YtError as exc:
                    logger.warning("offset commit failed: %s", exc)
                    err = ERR_UNKNOWN_TOPIC
                part_bodies.append(i32(partition) + i16(err))
            topic_bodies.append(string(topic) + array(part_bodies))
        return array(topic_bodies)

    # -- consumer groups (v0 shapes; ref group_coordinator.h) ------------------

    def _find_coordinator(self, r: Reader) -> bytes:
        r.string()                  # group_id: this proxy coordinates all
        return i16(ERR_NONE) + i32(0) + string(self.host) + i32(self.port)

    def _join_group(self, r: Reader) -> bytes:
        group_id = r.string() or ""
        session_timeout = r.i32()
        member_id = r.string() or ""
        protocol_type = r.string() or ""
        n = r.i32()
        protocols = []
        for _ in range(max(n, 0)):
            name = r.string() or ""
            protocols.append((name, r.bytes_() or b""))
        result = self.groups.join_group(group_id, session_timeout,
                                        member_id, protocol_type,
                                        protocols)
        if result.get("error"):
            return i16(result["error"]) + i32(-1) + string("") + \
                string("") + string(member_id) + array([])
        members = array([string(mid) + bytes_(meta)
                         for mid, meta in result["members"]])
        return i16(ERR_NONE) + i32(result["generation"]) + \
            string(result["protocol"]) + string(result["leader_id"]) + \
            string(result["member_id"]) + members

    def _sync_group(self, r: Reader) -> bytes:
        group_id = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        n = r.i32()
        assignments = []
        for _ in range(max(n, 0)):
            mid = r.string() or ""
            assignments.append((mid, r.bytes_() or b""))
        err, assignment = self.groups.sync_group(
            group_id, generation, member_id, assignments)
        return i16(err) + bytes_(assignment)

    def _heartbeat(self, r: Reader) -> bytes:
        group_id = r.string() or ""
        generation = r.i32()
        member_id = r.string() or ""
        return i16(self.groups.heartbeat(group_id, generation, member_id))

    def _leave_group(self, r: Reader) -> bytes:
        group_id = r.string() or ""
        member_id = r.string() or ""
        return i16(self.groups.leave_group(group_id, member_id))

    def _offset_fetch(self, r: Reader) -> bytes:
        group = r.string()
        n_topics = r.i32()
        topic_bodies = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            part_bodies = []
            for _ in range(n_parts):
                partition = r.i32()
                offset = -1
                try:
                    from ytsaurus_tpu.server.queue_agent import (
                        _consumer_offset,
                    )
                    consumer = self._consumer_path(group)
                    if self.client.exists(consumer):
                        offset = _consumer_offset(
                            self.client, consumer, self._topic_path(topic))
                except YtError:
                    offset = -1
                part_bodies.append(
                    i32(partition) + i64(offset) + string("") +
                    i16(ERR_NONE))
            topic_bodies.append(string(topic) + array(part_bodies))
        return array(topic_bodies)


def _recv_exact(sock: socket.socket, n: int) -> "Optional[bytes]":
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
