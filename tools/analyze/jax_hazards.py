"""JAX tracing-hazard pass (`yt analyze --pass jax`).

The static complement of PR 7's `classify_miss`: the compilation
observatory explains a recompilation storm AFTER it ships; this pass
flags the code shapes that cause one — plus the quieter pathology, the
hidden device→host synchronization that never throws but serializes the
dispatch queue ("An Empirical Analysis of Just-in-Time Compilation in
Modern Databases", arxiv 2311.04692).

Scope: the declared HOT-PATH modules (`ops/`, `query/engine/`,
`tablet/mvcc.py`, `parallel/`) for host-sync; jit-decorated functions
anywhere for traced-branch; dynamic-shape TREE-WIDE (ISSUE 10: with
capacity bucketing universal, an unbucketed dynamic capacity flowing
into `run_plan`/`run_plan_async` or any jitted callee is a compile-
storm seed no matter which layer it lives in).

Rules
-----
  host-sync       `.item()`, `block_until_ready`, `np.asarray(x)` of a
                  potentially device-resident value, and `float()/int()`
                  on a jax expression — each is a device→host sync; in a
                  hot path it must be an ALLOWLISTED sync point or carry
                  `# analyze: allow(host-sync): reason`.
  traced-branch   Python `if`/`while` on a traced parameter inside a
                  `@jax.jit` function — a concretization error at best,
                  a silent per-value recompile via static_argnums at
                  worst.  Shape/dtype/ndim/size attribute tests are
                  static and exempt.
  dynamic-shape   a dynamically-bounded slice (`x[:n]` with non-constant
                  `n`) passed straight into a locally-jitted callee OR
                  an evaluator dispatch (`run_plan`/`run_plan_async`) —
                  every distinct length compiles a fresh program unless
                  the bound went through a pow2 bucketing helper
                  (`pad_capacity`, `next_pow2`, ...).  Checked
                  tree-wide.
  decode-in-hot-path
                  (ISSUE 19) a dict-vocab gather (`vocab[...]`,
                  `take` over a dictionary) or a `decode*` helper call
                  in a hot-path module — encoded-plane kernels execute
                  on dict CODES; decoded strings materialize only at
                  the sync-point boundary (GROUP BY decodes once per
                  group at finish) or via the O(1) literal→code
                  binders (`_vocab_code`/`_range_code`).
  whole-plan-sync in the whole-plan SPMD modules (ISSUE 12) the fused
                  program permits exactly ONE device→host transfer —
                  the final stacked count read (`_read_counts`); any
                  other sync site is a finding (it would re-stitch the
                  plan).  Replaces the generic host-sync rule there.
                  The fused multiway-join path (ISSUE 14, `_run_join`)
                  rides the same contract: its quota demands and join
                  telemetry return stacked WITH the count through that
                  one read, never as separate transfers.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.analyze.core import Finding, SourceFile, dotted_name

PASS_NAME = "jax"

# Hot-path scope: path prefixes (repo-relative) the host-sync rule
# polices.  Everything else may sync freely — host boundaries are the
# POINT of the coordinator/client layers.
HOT_PREFIXES = (
    "ytsaurus_tpu/ops/",
    "ytsaurus_tpu/query/engine/",
    "ytsaurus_tpu/query/vector.py",
    "ytsaurus_tpu/parallel/",
    "ytsaurus_tpu/tablet/mvcc.py",
)

# Functions that ARE the sanctioned host-sync points of the hot modules:
# the one place a pipeline materializes (every caller funnels through
# them, so the sync count stays O(1) per query, not O(sites)).
SYNC_POINT_FUNCTIONS = {
    "finish", "finish_all", "to_rows", "batched_nearest",
    # The interpreter tier (ISSUE 18) pulls a chunk's planes to numpy
    # exactly once, here, before evaluating host-side.
    "materialize_planes",
}

# Whole-plan SPMD modules (ISSUE 12): the fused program must not sync
# BETWEEN stages — the one permitted device→host transfer is the final
# stacked count read.  These modules get the stricter `whole-plan-sync`
# rule (one sanctioned function, empty baseline) instead of the generic
# hot-path host-sync rule.
WHOLE_PLAN_MODULES = ("ytsaurus_tpu/parallel/whole_plan.py",)
WHOLE_PLAN_SYNC_FUNCTIONS = {"_read_counts"}

# Names that neutralize a dynamic slice bound: the repo's pow2
# capacity-bucketing helpers.
BUCKET_HELPERS = {"pad_capacity", "next_pow2", "bucket_capacity"}

# Compiled-dispatch entry points the dynamic-shape rule watches in
# EVERY module (method calls included): feeding them an unbucketed
# dynamically-sized plane compiles one program per distinct length.
PLAN_CALLEES = {"run_plan", "run_plan_async"}

# Encoded-plane execution (ISSUE 19): filter/group/join hot paths run
# on dict CODES; materializing decoded strings there (a vocab gather, a
# decode helper call) re-introduces the per-row host work the encoded
# path exists to eliminate.  Decode belongs at the materialization
# boundary (the sync-point functions) — or behind a reasoned waiver.
DECODE_BINDER_FUNCTIONS = {
    # O(1) host probes of the SORTED vocab that bind a literal to its
    # code at prepare time — the encoded path's entry points, the exact
    # opposite of a per-row decode.
    "_vocab_code", "_range_code",
}

_VOCAB_LEAVES = ("vocab", "dictionary", "vocabulary")

_JIT_DECORATORS = {"jit", "jax.jit", "partial", "functools.partial"}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def is_hot(path: str) -> bool:
    return any(path == p or path.startswith(p) for p in HOT_PREFIXES)


def _enclosing_function_name(stack: "list[ast.AST]") -> Optional[str]:
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name
    return None


def _jnp_names(fn: ast.AST) -> "set[str]":
    """Names bound (directly) from jnp.* expressions within a function —
    the local inference behind `float(x)`/`int(x)` flagging."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            if any(isinstance(n, ast.Name) and n.id == "jnp"
                   for n in ast.walk(node.value)):
                out.add(node.targets[0].id)
    return out


def _is_hostlike(node: ast.AST) -> bool:
    """Expressions that are clearly ALREADY host values: literals,
    list/tuple displays, pure-np expressions, and len()/range() calls."""
    if isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.Dict,
                         ast.ListComp, ast.GeneratorExp)):
        return True
    name = dotted_name(node)
    if name.startswith("np."):
        return True
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func)
        return callee.startswith("np.") or callee in ("len", "range",
                                                      "sorted")
    return False


def _sync_sites(f: SourceFile):
    """Yield (line, description) for every device→host sync site in a
    module — the shared detector behind the host-sync and
    whole-plan-sync rules."""
    # Per-FUNCTION jnp-name inference, mapped back to line ranges: a
    # numpy-only helper must not inherit another function's jax names.
    fn_ranges: list[tuple[int, int, set[str]]] = []
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_ranges.append((node.lineno, node.end_lineno or node.lineno,
                              _jnp_names(node)))

    def jnp_locals_at(line: int) -> "set[str]":
        best: set[str] = set()
        best_span = None
        for lo, hi, names in fn_ranges:     # innermost enclosing def
            if lo <= line <= hi and (best_span is None or
                                     hi - lo < best_span):
                best, best_span = names, hi - lo
        return best

    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        line = node.lineno
        callee = dotted_name(node.func)
        site = None
        if callee.endswith(".item") and not node.args:
            site = f"`{callee}()` blocks on a device→host transfer"
        elif callee.endswith("block_until_ready") or \
                callee == "jax.block_until_ready":
            site = "`block_until_ready` is an explicit device sync"
        elif callee == "np.asarray" and node.args and \
                not _is_hostlike(node.args[0]):
            site = ("`np.asarray(...)` of a potentially device-resident "
                    "value synchronizes and copies to host")
        elif callee in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            arg_names = {n.id for n in ast.walk(arg)
                         if isinstance(n, ast.Name)}
            if "jnp" in arg_names or (arg_names & jnp_locals_at(line)):
                site = (f"`{callee}()` on a jax expression forces a "
                        f"device→host sync")
        if site is not None:
            yield line, site


def _function_ranges(tree: ast.AST, names: "set[str]"
                     ) -> "list[tuple[int, int]]":
    out: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            out.append((node.lineno, node.end_lineno or node.lineno))
    return out


def _check_host_sync(f: SourceFile, findings: "list[Finding]") -> None:
    # Function-granular allowlist: sites inside a declared sync-point
    # function are sanctioned.
    sync_ranges = _function_ranges(f.tree, SYNC_POINT_FUNCTIONS)

    def sanctioned(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in sync_ranges)

    for line, site in _sync_sites(f):
        if sanctioned(line) or f.waived("host-sync", line):
            continue
        findings.append(Finding(
            PASS_NAME, "host-sync", f.path, line,
            f"{site}; hot-path modules must sync only at declared "
            f"sync points — waive with `# analyze: "
            f"allow(host-sync): reason` if intentional"))


def _check_whole_plan_sync(f: SourceFile,
                           findings: "list[Finding]") -> None:
    """ISSUE 12: the fused SPMD program body must not synchronize
    between stages — the single sanctioned transfer is the final
    stacked count read (`_read_counts`).  Stricter than host-sync: no
    function-name escape hatch beyond that one reader; anything else
    needs a reasoned waiver."""
    sanctioned_ranges = _function_ranges(f.tree, WHOLE_PLAN_SYNC_FUNCTIONS)

    def sanctioned(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in sanctioned_ranges)

    for line, site in _sync_sites(f):
        if sanctioned(line) or f.waived("whole-plan-sync", line):
            continue
        findings.append(Finding(
            PASS_NAME, "whole-plan-sync", f.path, line,
            f"{site}; the whole-plan fused program permits exactly ONE "
            f"host sync — the final stacked count transfer in "
            f"{', '.join(sorted(WHOLE_PLAN_SYNC_FUNCTIONS))} — waive "
            f"with `# analyze: allow(whole-plan-sync): reason` if "
            f"intentional"))


def _is_vocab_expr(node: ast.AST) -> bool:
    """Expressions that name a string-column vocabulary: `vocab`,
    `col.dictionary`, `merged_vocab`, ..."""
    leaf = dotted_name(node).rsplit(".", 1)[-1].lstrip("_").lower()
    return bool(leaf) and leaf.endswith(_VOCAB_LEAVES)


def _decode_sites(f: SourceFile):
    """Yield (line, description) for every site that materializes
    DECODED strings from a dict-encoded column."""
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Subscript) and _is_vocab_expr(node.value):
            yield node.lineno, (
                f"`{ast.unparse(node.value)}[...]` gathers decoded "
                f"strings out of a dict vocabulary")
        elif isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            leaf = callee.rsplit(".", 1)[-1]
            if leaf == "take" and any(_is_vocab_expr(a) for a in
                                      [node.func, *node.args]):
                yield node.lineno, (
                    "`take` over a dict vocabulary materializes "
                    "decoded strings")
            else:
                stripped = leaf.lstrip("_").lower()
                if stripped == "decode" and \
                        isinstance(node.func, ast.Attribute) and \
                        not _is_vocab_expr(node.func.value):
                    # `some_bytes.decode("utf-8")` — a codec call on a
                    # host value, not a vocab materialization.
                    continue
                if stripped == "decode" or stripped.startswith(
                        ("decode_row", "decode_chunk", "decode_string",
                         "decode_col", "decode_plane")):
                    yield node.lineno, (
                        f"decode helper `{callee}` materializes "
                        f"string values")


def _check_decode_in_hot_path(f: SourceFile,
                              findings: "list[Finding]") -> None:
    """ISSUE 19: hot paths execute on dict codes; decoded-string
    materialization is sanctioned only at the declared materialization
    boundary (the sync-point functions) and inside the O(1) literal→code
    binders — anywhere else it needs a reasoned waiver."""
    sanctioned_ranges = _function_ranges(
        f.tree, SYNC_POINT_FUNCTIONS | DECODE_BINDER_FUNCTIONS)

    def sanctioned(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in sanctioned_ranges)

    for line, site in _decode_sites(f):
        if sanctioned(line) or f.waived("decode-in-hot-path", line):
            continue
        findings.append(Finding(
            PASS_NAME, "decode-in-hot-path", f.path, line,
            f"{site}; hot-path kernels execute on dict CODES — decode "
            f"at the materialization boundary "
            f"({', '.join(sorted(SYNC_POINT_FUNCTIONS))}) or waive "
            f"with `# analyze: allow(decode-in-hot-path): reason`"))


def _jitted_functions(tree: ast.AST):
    """(fn_node, static_params) for defs decorated with jax.jit (incl.
    `@partial(jax.jit, static_argnums=...)`)."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            static: set[str] = set()
            target = deco
            if isinstance(deco, ast.Call):
                target = deco.func
            name = dotted_name(target)
            if name not in _JIT_DECORATORS:
                continue
            if name.endswith("partial"):
                if not (isinstance(deco, ast.Call) and deco.args and
                        dotted_name(deco.args[0]) in ("jit", "jax.jit")):
                    continue
            if isinstance(deco, ast.Call):
                params = [a.arg for a in node.args.args]
                for kw in deco.keywords:
                    if kw.arg == "static_argnums":
                        for elt in ast.walk(kw.value):
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, int) and \
                                    elt.value < len(params):
                                static.add(params[elt.value])
                    elif kw.arg == "static_argnames":
                        for elt in ast.walk(kw.value):
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, str):
                                static.add(elt.value)
            yield node, static
            break


class _StaticStripper(ast.NodeTransformer):
    """Remove static-structure subtrees (x.shape, len(x), x.dtype,
    isinstance(...)) before scanning a test for traced names."""

    def visit_Attribute(self, node):
        if node.attr in _STATIC_ATTRS:
            return ast.copy_location(ast.Constant(value=0), node)
        return self.generic_visit(node)

    def visit_Call(self, node):
        callee = dotted_name(node.func)
        if callee in ("len", "isinstance", "getattr", "hasattr"):
            return ast.copy_location(ast.Constant(value=0), node)
        return self.generic_visit(node)


def _check_traced_branches(f: SourceFile,
                           findings: "list[Finding]") -> None:
    for fn, static in _jitted_functions(f.tree):
        params = {a.arg for a in [*fn.args.args, *fn.args.posonlyargs,
                                  *fn.args.kwonlyargs]} - static
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if f.waived("traced-branch", node.lineno):
                continue
            stripped = _StaticStripper().visit(
                ast.fix_missing_locations(
                    ast.parse(ast.unparse(node.test), mode="eval")))
            names = {n.id for n in ast.walk(stripped)
                     if isinstance(n, ast.Name)}
            hit = sorted(names & params)
            if hit:
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(Finding(
                    PASS_NAME, "traced-branch", f.path, node.lineno,
                    f"Python `{kind}` on traced value(s) "
                    f"{', '.join(hit)} inside jitted "
                    f"`{fn.name}` — concretization error under "
                    f"tracing; use jnp.where/lax.cond or mark the "
                    f"argument static"))


def _locally_jitted_names(tree: ast.AST) -> "set[str]":
    """Names bound to `jax.jit(...)` results plus jit-decorated defs —
    the callees the dynamic-shape rule watches."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                dotted_name(node.value.func) in ("jax.jit", "jit"):
            out.add(node.targets[0].id)
    for fn, _static in _jitted_functions(tree):
        out.add(fn.name)
    return out


def _dynamic_slice_bound(arg: ast.AST) -> Optional[str]:
    """`x[:n]` / `x[a:b]` with a non-constant, non-bucketed bound →
    the offending bound's source text."""
    if not (isinstance(arg, ast.Subscript) and
            isinstance(arg.slice, ast.Slice)):
        return None
    for bound in (arg.slice.lower, arg.slice.upper):
        if bound is None or isinstance(bound, ast.Constant):
            continue
        if isinstance(bound, ast.Call) and \
                dotted_name(bound.func).rsplit(".", 1)[-1] in BUCKET_HELPERS:
            continue
        if isinstance(bound, ast.UnaryOp) and \
                isinstance(bound.operand, ast.Constant):
            continue
        return ast.unparse(bound)
    return None


def _check_dynamic_shapes(f: SourceFile,
                          findings: "list[Finding]") -> None:
    jitted = _locally_jitted_names(f.tree)
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in jitted:
            callee, kind = node.func.id, "jitted callee"
        elif isinstance(node.func, ast.Name) and \
                node.func.id in PLAN_CALLEES:
            callee, kind = node.func.id, "compiled dispatch"
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in PLAN_CALLEES:
            callee, kind = node.func.attr, "compiled dispatch"
        else:
            continue
        if f.waived("dynamic-shape", node.lineno):
            continue
        for arg in node.args:
            bound = _dynamic_slice_bound(arg)
            if bound is not None:
                findings.append(Finding(
                    PASS_NAME, "dynamic-shape", f.path, node.lineno,
                    f"{kind} {callee!r} receives a "
                    f"dynamically-bounded slice (bound `{bound}`): "
                    f"every distinct length compiles a fresh program — "
                    f"pad through a pow2 bucket helper "
                    f"({', '.join(sorted(BUCKET_HELPERS))})"))


def run(files: "list[SourceFile]") -> "list[Finding]":
    findings: list[Finding] = []
    for f in files:
        if f.path in WHOLE_PLAN_MODULES:
            # The stricter whole-plan rule REPLACES the generic hot-path
            # rule here (one sanctioned sync, not a function set).
            _check_whole_plan_sync(f, findings)
        elif is_hot(f.path):
            _check_host_sync(f, findings)
        if is_hot(f.path):
            # Encoded-plane discipline (ISSUE 19) applies to every hot
            # module, whole-plan included.
            _check_decode_in_hot_path(f, findings)
        # Dynamic-shape is TREE-WIDE (ISSUE 10): bucketing is universal
        # now, so an unbucketed capacity is a finding wherever it lives.
        _check_dynamic_shapes(f, findings)
        _check_traced_branches(f, findings)
    return findings
