"""Multicell Cypress: portal entrances delegating subtrees to secondary
cells (ref cypress_server portal_entrance/portal_exit + cell_master
multicell; Hive carries cross-cell lifecycle).
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.errors import YtError


@pytest.fixture
def cells(tmp_path):
    primary = connect(str(tmp_path / "primary"))
    secondary_root = str(tmp_path / "secondary")
    secondary = connect(secondary_root)
    return primary, secondary, secondary_root


def test_portal_routes_cypress_verbs(cells):
    primary, secondary, secondary_root = cells
    primary.create("portal_entrance", "//federated", recursive=True,
                   attributes={"cell_root": secondary_root,
                               "cell_tag": 2})
    # Writes beneath the portal land on the secondary cell's master.
    primary.create("map_node", "//federated/home", recursive=True)
    primary.set("//federated/home/@owner", "beta-team")
    assert primary.get("//federated/home/@owner") == "beta-team"
    assert primary.exists("//federated/home")
    assert primary.list("//federated") == ["home"]
    # ...observable directly on the secondary, absent from the primary.
    assert secondary.get("//federated/home/@owner") == "beta-team"
    assert primary.cluster.master.tree.try_resolve(
        "//federated/home") is None
    # The entrance node itself stays primary metadata.
    assert primary.get("//federated/@cell_tag") == 2
    # remove routes too.
    primary.remove("//federated/home")
    assert not primary.exists("//federated/home")
    assert not secondary.exists("//federated/home")


def test_portal_routes_table_data(cells):
    primary, secondary, secondary_root = cells
    primary.create("portal_entrance", "//cold", recursive=True,
                   attributes={"cell_root": secondary_root})
    rows = [{"k": i, "v": f"r{i}"} for i in range(10)]
    primary.write_table("//cold/archive", rows)
    got = primary.read_table("//cold/archive")
    assert [r["k"] for r in got] == list(range(10))
    # Chunk data + metadata live on the secondary cell.
    assert secondary.get("//cold/archive/@row_count") == 10
    assert primary.cluster.master.tree.try_resolve("//cold/archive") is None


def test_portal_removal_dismantles_exit_via_hive(cells):
    primary, secondary, secondary_root = cells
    primary.create("portal_entrance", "//p", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.create("document", "//p/a/b", recursive=True)
    assert secondary.exists("//p/a/b")
    primary.remove("//p")
    assert not primary.exists("//p")
    # The exit subtree is gone on the secondary — removed by the Hive
    # message handler, atomically with the inbox ack.
    assert not secondary.exists("//p")
    # Idempotence: re-creating and removing again works (fresh seqnos).
    primary.create("portal_entrance", "//p", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.create("document", "//p/c", recursive=True)
    primary.remove("//p")
    assert not secondary.exists("//p")


def test_ancestor_remove_dismantles_nested_exits(cells):
    """Removing an ANCESTOR of a portal must dismantle the exit too, or
    the secondary leaks the subtree and a recreated portal resurrects
    stale data."""
    primary, secondary, secondary_root = cells
    primary.create("map_node", "//dir", recursive=True)
    primary.create("portal_entrance", "//dir/p",
                   attributes={"cell_root": secondary_root})
    primary.write_table("//dir/p/data", [{"k": 1}])
    assert secondary.exists("//dir/p/data")
    primary.remove("//dir")
    assert not secondary.exists("//dir/p"), "exit subtree leaked"
    # Recreating the portal starts clean.
    primary.create("map_node", "//dir", recursive=True)
    primary.create("portal_entrance", "//dir/p",
                   attributes={"cell_root": secondary_root})
    assert primary.list("//dir/p") == []


def test_portal_create_ignore_existing(cells):
    primary, _, secondary_root = cells
    primary.create("portal_entrance", "//idem", recursive=True,
                   attributes={"cell_root": secondary_root})
    # Idempotent bootstrap re-run.
    primary.create("portal_entrance", "//idem", recursive=True,
                   attributes={"cell_root": secondary_root},
                   ignore_existing=True)
    with pytest.raises(YtError):
        primary.create("portal_entrance", "//idem", recursive=True,
                       attributes={"cell_root": secondary_root})


def test_get_on_entrance_resolves_to_exit(cells):
    primary, _, secondary_root = cells
    primary.create("portal_entrance", "//g", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.set("//g/leaf", 7)
    got = primary.get("//g")
    assert got.get("leaf") == 7          # exit content, not the entrance
    # Attribute reads still address the ENTRANCE node.
    assert primary.get("//g/@cell_root") == secondary_root


def test_tx_under_portal_rejected(cells):
    primary, _, secondary_root = cells
    primary.create("portal_entrance", "//txp", recursive=True,
                   attributes={"cell_root": secondary_root})
    tx = primary.start_tx()
    with pytest.raises(YtError):
        primary.set("//txp/x", 1, tx=tx)
    with pytest.raises(YtError):
        primary.remove("//txp/x", force=True, tx=tx)
    primary.abort_tx(tx)


def test_portal_requires_cell_root(cells):
    primary, _, _ = cells
    with pytest.raises(YtError):
        primary.create("portal_entrance", "//bad", recursive=True,
                       attributes={})


def test_chained_portal_cleanup_reaches_third_cell(cells, tmp_path):
    """Dismantling a portal whose EXIT contains another portal must
    dismantle the third cell's exit too — otherwise recreating the
    chain resurrects stale third-cell data."""
    primary, secondary, secondary_root = cells
    third_root = str(tmp_path / "third")
    third = connect(third_root)
    primary.create("portal_entrance", "//a", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.create("portal_entrance", "//a/b",
                   attributes={"cell_root": third_root})
    primary.set("//a/b/leaf", 42)
    assert third.get("//a/b/leaf") == 42
    primary.remove("//a")
    assert not third.exists("//a/b"), "third-cell exit leaked"
    # Recreate the chain: no resurrection.
    primary.create("portal_entrance", "//a", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.create("portal_entrance", "//a/b",
                   attributes={"cell_root": third_root})
    assert not primary.exists("//a/b/leaf")


def test_portal_acl_checked_at_entrance(cells):
    """Primary principals work through portals: the primary validates
    its ACLs at the entrance, the cell executes under cell trust (the
    secondary has no copy of the primary's user registry)."""
    from ytsaurus_tpu.cypress.security import authenticated_user

    primary, secondary, secondary_root = cells
    primary.cluster.security.create_user("alice")
    primary.create("portal_entrance", "//acl", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.set("//acl/@acl", [{"action": "allow", "subjects": ["alice"],
                                "permissions": ["read", "write"]}])
    with authenticated_user("alice"):
        primary.set("//acl/doc", 5)
        assert primary.get("//acl/doc") == 5
    # Deny alice at the entrance: routed writes refuse on the PRIMARY.
    primary.set("//acl/@acl", [{"action": "deny", "subjects": ["alice"],
                                "permissions": ["write"]}])
    from ytsaurus_tpu.errors import YtError as _E
    with authenticated_user("alice"):
        with pytest.raises(_E):
            primary.set("//acl/doc", 6)
    assert primary.get("//acl/doc") == 5


def test_nonroutable_verbs_fail_loudly(cells):
    primary, _, secondary_root = cells
    primary.create("portal_entrance", "//nr", recursive=True,
                   attributes={"cell_root": secondary_root})
    primary.create("map_node", "//plain", recursive=True)
    for call in (
            lambda: primary.mount_table("//nr/t"),
            lambda: primary.copy("//plain", "//nr/shadow"),
            lambda: primary.copy("//nr/x", "//plain/y"),
            lambda: primary.move("//plain", "//nr/m"),
            lambda: primary.link("//plain", "//nr/l")):
        with pytest.raises(YtError) as err:
            call()
        assert "portal" in str(err.value)


def test_failed_ancestor_remove_keeps_exit_intact(cells):
    """A REFUSED primary remove must not have destroyed exit data (the
    dismantle happens only after the primary mutation commits)."""
    primary, secondary, secondary_root = cells
    primary.create("map_node", "//guard", recursive=True)
    primary.create("portal_entrance", "//guard/p",
                   attributes={"cell_root": secondary_root})
    primary.set("//guard/p/keep", 1)
    # A transactional remove of a portal-bearing subtree is refused...
    tx = primary.start_tx()
    with pytest.raises(YtError):
        primary.remove("//guard", tx=tx)
    primary.abort_tx(tx)
    # ...and the exit data survives the refusal.
    assert secondary.get("//guard/p/keep") == 1
    assert primary.get("//guard/p/keep") == 1


def test_chained_portals(cells, tmp_path):
    primary, secondary, secondary_root = cells
    third_root = str(tmp_path / "third")
    third = connect(third_root)
    primary.create("portal_entrance", "//a", recursive=True,
                   attributes={"cell_root": secondary_root})
    # A portal INSIDE the secondary cell chains to a third cell.
    primary.create("portal_entrance", "//a/b",
                   attributes={"cell_root": third_root})
    primary.set("//a/b/leaf", 42)
    assert third.get("//a/b/leaf") == 42
    assert primary.get("//a/b/leaf") == 42