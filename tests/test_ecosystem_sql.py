"""CHYT-analog SQL dialect: translation + execution via query tracker.

Ref model: yt/chyt (ClickHouse SQL over YT tables) served through the
query tracker's engine registry (server/query_tracker/chyt_engine.cpp).
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.ecosystem.sql import translate_sql
from ytsaurus_tpu.server.query_tracker import QueryTracker


def test_translate_basics():
    assert translate_sql('SELECT a, b FROM "//t" WHERE a <> 2') == \
        "a, b FROM [//t] WHERE a != 2"
    assert translate_sql("SELECT * FROM `//dir/t` LIMIT 5") == \
        "* FROM [//dir/t] LIMIT 5"
    assert translate_sql("SELECT x FROM t ORDER BY x DESC "
                         "LIMIT 10 OFFSET 20") == \
        "x FROM [//t] ORDER BY x DESC OFFSET 20 LIMIT 10"
    assert translate_sql(
        'SELECT uniq(u) AS c FROM "//t" GROUP BY g;') == \
        "cardinality (u) AS c FROM [//t] GROUP BY g"
    # ANSI double-quoted identifiers outside FROM become bare names.
    assert translate_sql('SELECT "weird name" FROM [//t]') == \
        "weird name FROM [//t]"


def test_sql_execution(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//sales", [
        {"region": "eu", "amount": 10},
        {"region": "us", "amount": 20},
        {"region": "eu", "amount": 30}])
    qt = QueryTracker(client)
    qid = qt.start_query(
        'SELECT region, sum(amount) AS total FROM "//sales" '
        "GROUP BY region ORDER BY region ASC LIMIT 10",
        engine="chyt", sync=True)
    assert qt.read_query_result(qid) == [
        {"region": b"eu", "total": 40}, {"region": b"us", "total": 20}]
    # Alias engine name.
    qid2 = qt.start_query(
        "SELECT region, count(*) AS n FROM `//sales` GROUP BY region "
        "ORDER BY region ASC LIMIT 5", engine="sql", sync=True)
    assert qt.read_query_result(qid2) == [
        {"region": b"eu", "n": 2}, {"region": b"us", "n": 1}]


def test_sql_join(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//facts", [{"k": 1, "g": 0}, {"k": 2, "g": 1}])
    client.write_table("//dims", [{"g": 0, "name": "even"},
                                  {"g": 1, "name": "odd"}])
    qt = QueryTracker(client)
    qid = qt.start_query(
        'SELECT k, name FROM "//facts" JOIN "//dims" USING g '
        "ORDER BY k ASC LIMIT 10", engine="chyt", sync=True)
    assert qt.read_query_result(qid) == [
        {"k": 1, "name": b"even"}, {"k": 2, "name": b"odd"}]


def test_sql_errors_surface(tmp_path):
    client = connect(str(tmp_path))
    qt = QueryTracker(client)
    qid = qt.start_query("SELECT ~~~ nonsense", engine="chyt", sync=True)
    record = qt.get_query(qid)
    assert record["state"] == "failed"
    with pytest.raises(YtError):
        qt.read_query_result(qid)
