"""WAL backends for the master: local file or quorum-of-N locations.

Ref: Hydra quorum changelogs — mutations are acknowledged by a majority of
changelog replicas before apply (server/lib/hydra/changelog.h + journal
quorum semantics, server/master/journal_server/journal_node.h:19).

Protocol invariant: every location holds a PREFIX of the single-writer
log.  Remote appends are position-checked (the data node rejects a
non-contiguous append), so a replica that missed records can never grow a
hole; it is marked unsynced, earns no quorum credit, and is caught up from
the writer's in-memory committed log before acking again.  Recovery reads
every reachable location and takes the longest prefix present on >= quorum
locations — sound because prefixes are guaranteed, not assumed.

Snapshots are replicated to the journal locations BEFORE the journals are
truncated (build_snapshot), so a total local-disk loss still recovers:
newest quorum snapshot + committed journal tail.
"""

from __future__ import annotations

import os
from typing import Optional

from ytsaurus_tpu.cypress.master import Changelog
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("quorum")


class LocalWal:
    """Single-location WAL: today's fsync'd changelog file.

    A `.init` marker distinguishes "this location has legitimately empty
    history" from "this is a fresh disk that never saw the log" — a fresh
    disk must NOT vote a zero-length prefix in quorum recovery (it would
    truncate acknowledged records)."""

    def __init__(self, path: str):
        self.path = path
        self._log: Optional[Changelog] = None
        self.was_initialized = os.path.exists(path + ".init") or \
            os.path.exists(path)

    def _mark_initialized(self) -> None:
        marker = self.path + ".init"
        if not os.path.exists(marker):
            os.makedirs(os.path.dirname(marker) or ".", exist_ok=True)
            with open(marker, "wb") as f:
                f.flush()
                os.fsync(f.fileno())

    def recover(self) -> list[dict]:
        records, valid = Changelog.read_all(self.path)
        self._mark_initialized()
        # Drop a torn tail so future appends stay recoverable.
        if os.path.exists(self.path) and \
                os.path.getsize(self.path) > valid:
            with open(self.path, "r+b") as f:
                f.truncate(valid)
                f.flush()
                os.fsync(f.fileno())
        self._log = Changelog(self.path)
        return records

    def append(self, record: dict) -> None:
        self._log.append(record)

    def reset(self) -> None:
        """Truncate after a snapshot."""
        self._log.close()
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._log = Changelog(self.path)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()

    # Snapshot replication is a no-op for a single-location WAL.
    def store_snapshot(self, seq: int, blob: bytes) -> None:
        pass

    def fetch_snapshot(self) -> "tuple[int, bytes] | None":
        return None


class _Replica:
    def __init__(self, channel):
        self.channel = channel
        self.synced_len: Optional[int] = None    # None = unknown/unsynced


class QuorumWal:
    """WAL over one local location + remote journal locations."""

    def __init__(self, local_path: str, journal_name: str,
                 remote_channels: list, quorum: int = 2,
                 bootstrap_from_local: bool = False,
                 lease_ttl: float = 0.0,
                 count_local_ack: bool = True):
        self.local = LocalWal(local_path)
        self.journal_name = journal_name
        self.replicas = [_Replica(ch) for ch in remote_channels]
        self.quorum = quorum
        # >0 under leader election: epoch acquisition also claims the
        # leader lease on each granting location (see LeaderElector).
        self.lease_ttl = lease_ttl
        # count_local_ack=False = REMOTE-ONLY quorum, required under
        # multi-master failover: a successor master recovers with a
        # FRESH local location, so a record acked against "local + k
        # remotes" may sit on only k remotes — the read and write
        # quorums must intersect over the SHARED (remote) locations
        # alone.  The local file still takes every append; it just earns
        # no quorum credit and no recovery vote (it accelerates
        # restart-in-place, like a Hydra follower's local changelog).
        self.count_local_ack = count_local_ack
        # True exactly when this quorum configuration is being adopted for
        # the first time over an existing single-location log: the local
        # history is authoritative and seeds the replicas.
        self.bootstrap_from_local = bootstrap_from_local
        if quorum > 1 + len(self.replicas):
            raise YtError(f"quorum {quorum} unreachable with "
                          f"{1 + len(self.replicas)} locations")
        self._records: list[dict] = []     # committed log (truncated w/ WAL)
        self.epoch: int = 0                # 0 = not yet acquired
        import uuid
        self.writer_id: str = uuid.uuid4().hex[:12]

    # -- epoch fencing ---------------------------------------------------------

    def _local_epoch_path(self) -> str:
        return self.local.path + ".epoch"

    def _local_stored_epoch(self) -> int:
        from ytsaurus_tpu.utils.diskio import read_epoch_file
        return read_epoch_file(self._local_epoch_path())[0]

    def _store_local_epoch(self, epoch: int) -> None:
        from ytsaurus_tpu.utils.diskio import write_epoch_file
        write_epoch_file(self._local_epoch_path(), epoch, self.writer_id)

    def _fence_body(self) -> dict:
        return {"epoch": self.epoch or None, "writer": self.writer_id}

    def acquire_epoch(self) -> int:
        """Claim write ownership: epoch = max(stored)+1, granted by a
        MAJORITY of locations (ref Hydra changelog acquisition).  Any
        previous writer's appends are rejected from then on — split-brain
        masters fence each other instead of interleaving one log."""
        observed = [self._local_stored_epoch()]
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_epoch",
                    {"journal": self.journal_name})
                observed.append(int(body.get("epoch", 0)))
            except YtError:
                pass
        candidate = max(observed) + 1
        self._store_local_epoch(candidate)
        if not self.replicas:
            # Single-location deployment: one process owns the file.
            self.epoch = candidate
            return candidate
        # Grants are counted over the SHARED remote locations only: two
        # candidate masters have disjoint local locations, so quorums
        # counting locals need not intersect.  A STRICT majority of the
        # remote locations must grant — for even remote counts that is
        # n/2+1 (2-of-2 for two journal nodes), so any two successful
        # acquisitions share a granting remote and the later epoch fences
        # the earlier writer there.  The cost is liveness: with two
        # remotes, one dead remote blocks acquisition.  That is the
        # trade the fencing guarantee requires (ceil(n/2) grants would
        # let two candidates win on disjoint halves and commit divergent
        # logs, each using own-local + its granted remote for appends).
        grants = 0
        acquire_body = {"journal": self.journal_name, "epoch": candidate,
                        "writer": self.writer_id}
        if self.lease_ttl > 0:
            acquire_body["lease_ttl"] = self.lease_ttl
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_acquire", dict(acquire_body),
                    idempotent=False)
                if body.get("granted"):
                    grants += 1
            except YtError:
                pass
        needed = len(self.replicas) // 2 + 1
        if grants < needed:
            raise YtError(
                f"epoch acquisition granted by {grants}/{needed} remote "
                "locations", code=EErrorCode.PeerUnavailable)
        self.epoch = candidate
        return candidate

    # -- replica sync ----------------------------------------------------------

    def _catch_up(self, replica: _Replica, _retry_ok: bool = True) -> bool:
        """Bring one replica to the full committed log; True on success."""
        try:
            if replica.synced_len is None:
                # Length-only probe; the position-checked append protocol
                # guarantees the replica holds a prefix, so the count alone
                # decides between catch-up and tail discard.
                body, _ = replica.channel.call(
                    "data_node", "journal_count",
                    {"journal": self.journal_name})
                have = int(body.get("count", 0))
                if have > len(self._records):
                    # Longer than the committed log → uncommitted tail from
                    # a previous incarnation; discard it.
                    replica.channel.call(
                        "data_node", "journal_reset",
                        {"journal": self.journal_name,
                         **self._fence_body()}, idempotent=False)
                    have = 0
                replica.synced_len = have
            if replica.synced_len < len(self._records):
                missing = self._records[replica.synced_len:]
                replica.channel.call(
                    "data_node", "journal_append",
                    {"journal": self.journal_name, "records": missing,
                     "position": replica.synced_len,
                     **self._fence_body()}, idempotent=False)
                replica.synced_len = len(self._records)
            return True
        except YtError as err:
            replica.synced_len = None
            if err.code == EErrorCode.JournalEpochFenced:
                if _retry_ok and self._maybe_reacquire():
                    return self._catch_up(replica, _retry_ok=False)
                raise self._fenced_error(err)
            logger.warning("journal replica catch-up failed: %s", err)
            return False

    # -- write path ------------------------------------------------------------

    def _maybe_reacquire(self) -> bool:
        """Recovery from an ORPHANED fence: a takeover that died between
        acquiring its epoch and reaching quorum leaves a higher epoch
        behind with NO records.  Re-acquire only on POSITIVE evidence: a
        strict majority of remote locations answered the probe and none
        holds records beyond our committed log.  An unreachable replica is
        inconclusive, not absolving — it may be the very location holding
        a new master's records, and a partitioned stale master that
        treated silence as absence would claim a higher epoch and resume
        writing.  Any longer log means a real new master: fail-stop."""
        probed = 0
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_count",
                    {"journal": self.journal_name})
                probed += 1
                if int(body.get("count", 0)) > len(self._records):
                    return False
            except YtError:
                continue
        if probed < len(self.replicas) // 2 + 1:
            return False
        try:
            self.acquire_epoch()
            logger.warning("re-acquired journal %s after an orphaned "
                           "fence (epoch now %d)", self.journal_name,
                           self.epoch)
            return True
        except YtError:
            return False

    def _fenced_error(self, err: YtError) -> YtError:
        return YtError(
            "WAL writer fenced: a newer master acquired the journal; "
            "this master must stop writing",
            code=EErrorCode.JournalEpochFenced, inner_errors=[err])

    def append(self, record: dict) -> None:
        position = len(self._records)
        acks = 0
        errors = []
        reacquired = False
        try:
            self.local.append(record)
            if self.count_local_ack:
                acks += 1
        except OSError as exc:          # local disk failure
            errors.append(YtError(f"local WAL append failed: {exc}"))
        for replica in self.replicas:
            if replica.synced_len != position and not self._sync_to(
                    replica, position):
                continue
            attempts = 0
            while True:
                attempts += 1
                try:
                    replica.channel.call(
                        "data_node", "journal_append",
                        {"journal": self.journal_name, "records": [record],
                         "position": position, **self._fence_body()},
                        idempotent=False)
                    replica.synced_len = position + 1
                    acks += 1
                except YtError as err:
                    replica.synced_len = None
                    errors.append(err)
                    if err.code == EErrorCode.JournalEpochFenced:
                        if not reacquired and attempts == 1 and \
                                self._maybe_reacquire():
                            reacquired = True
                            continue        # retry under the new epoch
                        # A newer master owns this journal: fail-stop —
                        # assembling a quorum from the remaining
                        # locations would interleave two writers.
                        raise self._fenced_error(err)
                break
        if acks < self.quorum:
            raise YtError(
                f"WAL append reached {acks}/{self.quorum} locations",
                code=EErrorCode.PeerUnavailable, inner_errors=errors[:3])
        self._records.append(record)

    def _sync_to(self, replica: _Replica, position: int) -> bool:
        """Catch a lagging replica up to `position` committed records."""
        if not self._catch_up(replica):
            return False
        return replica.synced_len == position

    # -- recovery --------------------------------------------------------------

    def recover(self) -> list[dict]:
        local_initialized = self.local.was_initialized
        local_records = self.local.recover()
        if self.bootstrap_from_local:
            # First adoption of this quorum config: local history (possibly
            # written under a local-only WAL) is authoritative.
            self._records = list(local_records)
            self.acquire_epoch()
            for replica in self.replicas:
                replica.synced_len = None
                self._catch_up(replica)
            return list(self._records)
        # Under remote-only quorum the local history holds no vote (a
        # successor's fresh local must not dilute the read quorum, and a
        # stale local must not stretch it).
        lists: list[Optional[list]] = [
            local_records if local_initialized and self.count_local_ack
            else None]
        if not local_initialized and local_records:
            raise YtError("local WAL has records but no init marker")
        for replica in self.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_read",
                    {"journal": self.journal_name})
                if not body.get("initialized", True):
                    # A journal this data node never held must not vote a
                    # zero-length prefix (fresh node disk).
                    lists.append(None)
                    continue
                lists.append(list(body.get("records", [])))
            except YtError as err:
                logger.warning("journal location unreachable in recovery: "
                               "%s", err)
                lists.append(None)
        voting = sum(1 for lst in lists if lst is not None)
        if voting < self.quorum:
            raise YtError(
                f"cannot recover: {voting}/{self.quorum} initialized WAL "
                "locations reachable (a fresh/wiped location cannot vote; "
                "bring more journal owners online)",
                code=EErrorCode.PeerUnavailable)
        # Longest prefix confirmed by >= quorum voting locations.
        # Position-checked appends guarantee each location IS a prefix, so
        # length comparison is sound.
        lengths = sorted((len(lst) for lst in lists if lst is not None),
                         reverse=True)
        committed = lengths[self.quorum - 1]
        source = next(lst for lst in lists
                      if lst is not None and len(lst) >= committed)
        self._records = source[:committed]
        # Re-align the local location; remote replicas catch up lazily at
        # the next append (and earn no quorum credit until they do).
        self._realign_local()
        # Fence any previous writer BEFORE this incarnation writes (ref
        # Hydra changelog acquisition at epoch start).
        self.acquire_epoch()
        for replica, lst in zip(self.replicas, lists[1:]):
            replica.synced_len = None if lst is None or \
                len(lst) != committed else committed
            if replica.synced_len is None:
                self._catch_up(replica)
        return list(self._records)

    def extend(self, channels: list) -> int:
        """Grow the membership AFTER recovery: seed each new location with
        the full committed log (position-checked appends from 0), then
        adopt the larger quorum.  Seeding first keeps the invariant that
        >= quorum locations hold every committed record — adopting the
        quorum before seeding would make the existing history
        unrecoverable under the new threshold.  Returns the number of
        locations successfully added."""
        added = 0
        for channel in channels:
            replica = _Replica(channel)
            replica.synced_len = None
            self.replicas.append(replica)
            if self._catch_up(replica) and \
                    replica.synced_len == len(self._records):
                added += 1
            else:
                self.replicas.pop()
        if added:
            locations = len(self.replicas) + \
                (1 if self.count_local_ack else 0)
            self.quorum = locations // 2 + 1
        return added

    def _realign_local(self) -> None:
        self.local.reset()
        for record in self._records:
            self.local.append(record)

    def reset(self) -> None:
        self.local.reset()
        self._records = []
        for replica in self.replicas:
            try:
                replica.channel.call(
                    "data_node", "journal_reset",
                    {"journal": self.journal_name, **self._fence_body()},
                    idempotent=False)
                replica.synced_len = 0
            except YtError:
                replica.synced_len = None

    def close(self) -> None:
        self.local.close()

    # -- replicated snapshots --------------------------------------------------

    def store_snapshot(self, seq: int, blob: bytes) -> None:
        """Replicate the snapshot to enough journal locations BEFORE the
        journals are truncated: quorum-1 remotes when the local copy
        counts toward the quorum, a full remote quorum otherwise."""
        acks = 0
        errors = []
        for replica in self.replicas:
            try:
                replica.channel.call(
                    "data_node", "snapshot_put",
                    {"name": self.journal_name, "seq": seq,
                     **self._fence_body()}, [blob],
                    idempotent=False)
                acks += 1
            except YtError as err:
                errors.append(err)
        needed = self.quorum - 1 if self.count_local_ack else self.quorum
        if acks < needed:
            raise YtError(
                f"snapshot replication reached {acks}/{needed} "
                "remote locations", code=EErrorCode.PeerUnavailable,
                inner_errors=errors[:3])

    def fetch_snapshot(self) -> "tuple[int, bytes] | None":
        """Newest snapshot available on any journal location."""
        best: "tuple[int, bytes] | None" = None
        for replica in self.replicas:
            try:
                body, attachments = replica.channel.call(
                    "data_node", "snapshot_get",
                    {"name": self.journal_name})
                if body.get("seq") is None:
                    continue
                seq = int(body["seq"])
                if best is None or seq > best[0]:
                    best = (seq, attachments[0])
            except YtError:
                continue
        return best
