"""Distributed trace contexts with sampling and baggage.

Ref shape: core/tracing/trace_context.h:75 — a TTraceContext carries
(trace id, span id, parent span id, sampled flag, baggage), is propagated
implicitly through fibers and explicitly through RPC headers, and finished
spans go to an exporter (Jaeger in the reference).

Redesign: a `contextvars`-based ambient context (survives asyncio + thread
pools via explicit capture in the RPC layer), spans finished into an
in-process ring buffer that Orchid/tests read; the wire encoding is a plain
dict injected into the RPC envelope.
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
from typing import Any, Optional

_current: contextvars.ContextVar[Optional["TraceContext"]] = \
    contextvars.ContextVar("trace_context", default=None)


class SpanRecord:
    """One finished span (exporter unit)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name", "start",
                 "duration", "tags", "baggage")

    def __init__(self, ctx: "TraceContext", duration: float):
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_span_id = ctx.parent_span_id
        self.name = ctx.name
        self.start = ctx.start_time
        self.duration = duration
        self.tags = dict(ctx.tags)
        self.baggage = dict(ctx.baggage)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class SpanCollector:
    """Ring buffer of finished sampled spans."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._spans: list[SpanRecord] = []

    def add(self, span: SpanRecord) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.capacity:
                del self._spans[:len(self._spans) - self.capacity]

    def drain(self) -> list[SpanRecord]:
        with self._lock:
            spans, self._spans = self._spans, []
            return spans

    def snapshot(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def find(self, trace_id: str) -> list[SpanRecord]:
        return [s for s in self.snapshot() if s.trace_id == trace_id]


_collector = SpanCollector()


def get_collector() -> SpanCollector:
    return _collector


class TraceContext:
    """One span; use as a context manager to time + activate it."""

    def __init__(self, name: str, *, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None, sampled: bool = True,
                 baggage: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id or uuid.uuid4().hex
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_span_id = parent_span_id
        self.sampled = sampled
        self.baggage: dict[str, Any] = dict(baggage or {})
        self.tags: dict[str, Any] = {}
        self.start_time = 0.0
        self._token = None

    # -- structure -------------------------------------------------------------

    def create_child(self, name: str) -> "TraceContext":
        return TraceContext(name, trace_id=self.trace_id,
                            parent_span_id=self.span_id,
                            sampled=self.sampled, baggage=self.baggage)

    def add_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def set_baggage(self, key: str, value: Any) -> None:
        self.baggage[key] = value

    # -- activation ------------------------------------------------------------

    def __enter__(self) -> "TraceContext":
        self.start_time = time.time()
        self._t0 = time.perf_counter()
        self._token = _current.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        _current.reset(self._token)
        if self.sampled:
            _collector.add(SpanRecord(self, time.perf_counter() - self._t0))
        return False

    # -- wire ------------------------------------------------------------------

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled, "baggage": self.baggage}

    @classmethod
    def from_wire(cls, wire: Optional[dict], name: str) -> "TraceContext":
        if not wire:
            return cls(name)
        def _text(v):
            return v.decode() if isinstance(v, bytes) else v
        wire = {(_text(k)): v for k, v in wire.items()}
        return cls(name, trace_id=_text(wire.get("trace_id")),
                   parent_span_id=_text(wire.get("span_id")),
                   sampled=bool(wire.get("sampled", True)),
                   baggage={_text(k): (_text(v) if isinstance(v, bytes)
                                       else v)
                            for k, v in (wire.get("baggage") or {}).items()})


def current_trace() -> Optional[TraceContext]:
    return _current.get()


def start_span(name: str, **tags) -> TraceContext:
    """Child of the ambient context, or a fresh root."""
    parent = _current.get()
    ctx = parent.create_child(name) if parent is not None \
        else TraceContext(name)
    ctx.tags.update(tags)
    return ctx
