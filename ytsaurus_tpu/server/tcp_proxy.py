"""TCP proxy: a leader-following byte router for the native RPC plane.

Ref: yt/yt/server/tcp_proxy — a dumb-but-availability-critical process
that terminates client TCP and splices it to the right backend, so
clients hold ONE stable address while masters fail over behind it.
Routing is per-connection: at accept time the proxy asks each master
for its role (MasterService.get_role) and splices to the current
leader; an established connection pins its backend (mid-stream
re-routing would corrupt request framing), and a failover surfaces as a
reconnect — exactly the contract FailoverChannel/RetryingChannel
already handle client-side.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Sequence

from ytsaurus_tpu.rpc import Channel
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("tcp_proxy")


class TcpProxy:
    # analyze: allow(failpoint): backend connect failures already count as probe_failures and rotate; tcp-proxy routing tests cover it
    def __init__(self, backends: "Sequence[str]", host: str = "127.0.0.1",
                 port: int = 0, probe_timeout: float = 5.0):
        self.backends = list(backends)
        self.probe_timeout = probe_timeout
        self.stats = {"connections": 0, "routed_to": {}, "probe_failures": 0}
        proxy = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                upstream = None
                for attempt in range(2):
                    backend = proxy.pick_backend()
                    if backend is None:
                        return          # no live leader: drop, client retries
                    host, bport = backend.rsplit(":", 1)
                    try:
                        upstream = socket.create_connection(
                            (host, int(bport)),
                            timeout=proxy.probe_timeout)
                        break
                    except OSError:
                        # Cached leader died: invalidate and re-probe once.
                        proxy.invalidate_leader()
                        upstream = None
                if upstream is None:
                    return
                # The connect timeout must NOT survive onto the spliced
                # stream: an idle-but-healthy client connection would be
                # torn down at the first recv timeout.
                upstream.settimeout(None)
                proxy.stats["connections"] += 1
                proxy.stats["routed_to"][backend] = \
                    proxy.stats["routed_to"].get(backend, 0) + 1
                _splice(self.request, upstream)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._leader_lock = threading.Lock()
        self._cached_leader: "str | None" = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def invalidate_leader(self) -> None:
        with self._leader_lock:
            self._cached_leader = None

    def pick_backend(self) -> "str | None":
        """The current leader among the backends, cached until a connect
        or probe against it fails (per-connection re-probing would stall
        every accept behind a hung master and multiply probe load).  A
        lone backend is assumed leader."""
        if len(self.backends) == 1:
            return self.backends[0]
        with self._leader_lock:
            if self._cached_leader is not None:
                return self._cached_leader
        follower = None
        for address in self.backends:
            ch = Channel(address, timeout=self.probe_timeout)
            try:
                body, _ = ch.call("master", "get_role", {})
                role = body.get("role")
                role = role.decode() if isinstance(role, bytes) else role
                if role == "leader":
                    with self._leader_lock:
                        self._cached_leader = address
                    return address
                follower = follower or address
            except Exception:       # noqa: BLE001 — probe next backend
                self.stats["probe_failures"] += 1
            finally:
                ch.close()
        return follower             # degraded: serve reads off a follower

    def start(self) -> "TcpProxy":
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="tcp-proxy").start()
        logger.info("tcp proxy on %s -> %s", self.address, self.backends)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _splice(a: socket.socket, b: socket.socket) -> None:
    """Bidirectional byte pump until either side closes."""
    def pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for sock in (src, dst):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    t = threading.Thread(target=pump, args=(b, a), daemon=True)
    t.start()
    pump(a, b)
    t.join()
    b.close()
