"""Sort operation kernels: single-device chunk sort + mesh-wide shuffle sort.

Ref: the Sort controller family (controller_agent/controllers/
sort_controller.cpp).  Single-chip: one device lexsort over the concatenated
columnar input (the simple_sort job analog, job_proxy/sort_job).  Multi-chip:
parallel/shuffle.sort_table (partition + all_to_all).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Sequence

import jax.numpy as jnp

from ytsaurus_tpu.chunks.columnar import ColumnarChunk, concat_chunks
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import packed_sort_indices
from ytsaurus_tpu.schema import SortOrder, TableSchema


def sort_chunk(chunk: ColumnarChunk, key_columns: Sequence[str],
               descending: bool = False) -> ColumnarChunk:
    """Device lexsort of one chunk by the given key columns."""
    for name in key_columns:
        if name not in chunk.schema:
            raise YtError(f"No such sort column {name!r}",
                          code=EErrorCode.QueryTypeError)
    mask = chunk.row_valid
    # Packed composite keys: the device sort carries the fewest possible
    # u64 operands (mask bit + null/value fields); payload columns are
    # gathered by the permutation afterwards.
    items = [((~mask), jnp.ones_like(mask), False, 1)]
    for name in key_columns:
        col = chunk.column(name)
        dictionary = getattr(col, "dictionary", None)
        bits = max(len(dictionary) - 1, 1).bit_length() \
            if dictionary is not None else 64
        items.append((col.data, col.valid, descending, bits))
    order = packed_sort_indices(items)
    columns = {}
    for name, col in chunk.columns.items():
        host_values = None
        if col.host_values is not None:
            idx_host = [int(i) for i in order[: chunk.row_count]]
            host_values = [col.host_values[i] for i in idx_host]
            host_values += [None] * (chunk.capacity - len(host_values))
        columns[name] = replace(col, data=col.data[order],
                                valid=col.valid[order],
                                host_values=host_values)
    order_kind = SortOrder.descending if descending else SortOrder.ascending
    schema = _with_key_order(chunk.schema, list(key_columns), order_kind)
    return ColumnarChunk(schema=schema, row_count=chunk.row_count,
                         columns=columns)


def sort_chunks(chunks: Sequence[ColumnarChunk], key_columns: Sequence[str],
                descending: bool = False) -> ColumnarChunk:
    merged = concat_chunks(list(chunks)) if len(chunks) > 1 else chunks[0]
    return sort_chunk(merged, key_columns, descending)


def _with_key_order(schema: TableSchema, key_names: list[str],
                    order: SortOrder) -> TableSchema:
    reordered = [schema.get(k) for k in key_names] + \
        [c for c in schema if c.name not in key_names]
    cols = []
    for i, col in enumerate(reordered):
        cols.append(col.with_sort_order(order if i < len(key_names) else None))
    return TableSchema(columns=tuple(cols))
