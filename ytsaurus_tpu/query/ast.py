"""Untyped AST produced by the QL parser.

Mirrors the node taxonomy of the reference AST (library/query/base/ast.h):
literal / reference / function / unary / binary / in / between / transform /
case / like expressions, plus the query skeleton (select, source, joins,
where, group-by, having, order-by, offset, limit).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Union


class Expr:
    pass


@dataclass(frozen=True)
class Literal(Expr):
    value: object            # int, float, str, bool, or None
    is_uint: bool = False


@dataclass(frozen=True)
class Reference(Expr):
    name: str                # column name
    table: Optional[str] = None   # join alias qualifier


@dataclass(frozen=True)
class Placeholder(Expr):
    """A `?` parameter slot, numbered in appearance order.  Substituted
    with a literal from the `params` list before type checking (the
    NEAREST query-vector position and scalar binds both ride this)."""
    index: int


@dataclass(frozen=True)
class FunctionCall(Expr):
    name: str                # lower-cased
    args: tuple[Expr, ...]


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str                  # '-', '+', '~', 'not'
    operand: Expr


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str                  # arithmetic/comparison/logical/bitwise
    lhs: Expr
    rhs: Expr


@dataclass(frozen=True)
class InExpr(Expr):
    operands: tuple[Expr, ...]       # tuple being tested (1+ exprs)
    values: tuple[tuple, ...]        # literal tuples


@dataclass(frozen=True)
class BetweenExpr(Expr):
    operands: tuple[Expr, ...]
    ranges: tuple[tuple, ...]        # ((lower_tuple, upper_tuple), ...)
    negated: bool = False


@dataclass(frozen=True)
class TransformExpr(Expr):
    operands: tuple[Expr, ...]
    from_values: tuple[tuple, ...]
    to_values: tuple[object, ...]
    default: Optional[Expr] = None


@dataclass(frozen=True)
class CaseExpr(Expr):
    operand: Optional[Expr]                    # CASE x WHEN ... or CASE WHEN ...
    when_then: tuple[tuple[Expr, Expr], ...]
    default: Optional[Expr] = None


@dataclass(frozen=True)
class LikeExpr(Expr):
    text: Expr
    pattern: Expr
    negated: bool = False
    case_insensitive: bool = False   # ILIKE
    escape: Optional[Expr] = None


@dataclass(frozen=True)
class FrameBound:
    """One end of a ROWS frame."""
    kind: str                # unbounded_preceding | preceding | current_row
                             # | following | unbounded_following
    offset: Optional[int] = None   # literal row count for (preceding|following)


@dataclass(frozen=True)
class WindowSpec:
    partition_by: tuple[Expr, ...] = ()
    order_by: tuple["OrderItem", ...] = ()
    frame: Optional[tuple[FrameBound, FrameBound]] = None   # ROWS BETWEEN a AND b


@dataclass(frozen=True)
class WindowExpr(Expr):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [ROWS BETWEEN ...])."""
    function: str            # lower-cased window function name
    args: tuple[Expr, ...]
    spec: WindowSpec


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass(frozen=True)
class Join:
    table: str                       # foreign table path
    alias: Optional[str]
    is_left: bool
    using: tuple[str, ...] = ()      # USING columns
    on: tuple[tuple[Expr, Expr], ...] = ()  # (self_expr, foreign_expr) pairs


@dataclass(frozen=True)
class OrderItem:
    expr: Expr
    descending: bool = False


@dataclass(frozen=True)
class QueryAst:
    select: Optional[tuple[SelectItem, ...]]   # None == SELECT *
    source: Optional[str]                      # table path (None for expression eval)
    source_alias: Optional[str] = None
    joins: tuple[Join, ...] = ()
    where: Optional[Expr] = None
    group_by: tuple[SelectItem, ...] = ()
    with_totals: bool = False
    having: Optional[Expr] = None
    order_by: tuple[OrderItem, ...] = ()
    offset: Optional[int] = None
    limit: Optional[int] = None
