"""Compare sort-engine wall times on the current backend.

Usage: python tools/bench_sort_engines.py [--rows N] [--words W]
       [--engines network,lsd32,radix,radix_scatter,radix_pallas]

Times stable_argsort_u32 per engine at the given scale and prints one
line per engine; used to pick LSD_SORT_THRESHOLD / engine defaults on
real hardware (the cliffs are TPU-generation specific).
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _sync(x):
    np.asarray(x.ravel()[:1])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=16 * 1024 * 1024)
    parser.add_argument("--words", type=int, default=2)
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--engines", default="radix,radix_scatter,"
                                             "radix_pallas,lsd32,network")
    parser.add_argument("--timeout", type=float, default=240.0,
                        help="skip remaining iters past this many seconds")
    args = parser.parse_args()

    from ytsaurus_tpu.utils.backend import ensure_backend
    jax = ensure_backend()
    import jax.numpy as jnp

    from ytsaurus_tpu.ops.segments import stable_argsort_u32

    platform = jax.devices()[0].platform
    key = jax.random.PRNGKey(0)
    words = [jax.random.randint(jax.random.fold_in(key, i), (args.rows,),
                                0, 1 << 31, dtype=jnp.uint32) * 2
             for i in range(args.words)]
    print(f"# rows={args.rows} words={args.words} device={platform}")
    for engine in args.engines.split(","):
        # The engine is read from env at trace time; a fresh jit per
        # engine keeps the traces separate.
        os.environ["YT_TPU_SORT_ENGINE"] = engine
        run = jax.jit(lambda ws: stable_argsort_u32(ws))
        t0 = time.perf_counter()
        try:
            out = run(words)
            _sync(out)
        except Exception as exc:  # noqa: BLE001 - report and continue
            print(f"{engine}: FAILED {exc!r}")
            continue
        compile_s = time.perf_counter() - t0
        times = []
        deadline = time.monotonic() + args.timeout
        for _ in range(args.iters):
            if time.monotonic() > deadline:
                break
            t0 = time.perf_counter()
            out = run(words)
            _sync(out)
            times.append(time.perf_counter() - t0)
        best = min(times) if times else float("nan")
        print(f"{engine}: best={best * 1e3:.1f}ms compile={compile_s:.1f}s "
              f"({args.rows / best / 1e6:.0f}M rows/s)")


if __name__ == "__main__":
    main()
