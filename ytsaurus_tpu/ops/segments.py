"""Segmented reductions and sort-key helpers used by GROUP BY / ORDER BY.

These are the XLA analogs of the reference's cg_routines hot loops
(library/query/engine/cg_routines/registry.cpp: GroupOpHelper, OrderOpHelper):
instead of a per-row JIT'd hash-table loop, grouping is lex-sort + segment
reduction over static-capacity planes — batch-friendly for the VPU/MXU.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.schema import EValueType


def sort_key_planes(data: jax.Array, valid: jax.Array,
                    descending: bool = False) -> list[jax.Array]:
    """Produce ascending-order integer/float planes encoding (null, value).

    YT comparison semantics: null sorts before any value.  For descending
    order the value plane is complemented so a single ascending lexsort works.
    Returns [value_plane, null_plane] ordered minor→major for jnp.lexsort.
    """
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    if descending:
        if jnp.issubdtype(data.dtype, jnp.integer):
            value = ~data   # order-reversing for signed and unsigned alike
        else:
            value = -data
        # Nulls sort before any value; descending reverses that → nulls last:
        # key 0 for valid rows, 1 for nulls.
        null_key = (~valid).astype(jnp.int8)
    else:
        value = data
        # Ascending: nulls first → key 0 for null, 1 for valid.
        null_key = valid.astype(jnp.int8)
    value = jnp.where(valid, value, jnp.zeros_like(value))
    return [value, null_key]


def lexsort_indices(key_planes: list[jax.Array]) -> jax.Array:
    """Stable ascending argsort over multiple key planes (major key LAST).

    (jnp.lexsort already lowers to ONE variadic lax.sort with a composite
    comparator in current JAX — do not hand-roll it.)"""
    return jnp.lexsort(key_planes)


def segment_boundaries(sorted_keys: list[tuple[jax.Array, jax.Array]],
                       in_mask: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Given key (data, valid) planes already in sorted order plus the row
    mask (also sorted so that masked-out rows are at the end), return
    (segment_ids, num_segments).  Masked-out rows get segment id
    == num_real_segments (they land in trailing garbage segments)."""
    cap = in_mask.shape[0]
    change = jnp.zeros(cap, dtype=bool)
    for data, valid in sorted_keys:
        prev_data = jnp.roll(data, 1)
        prev_valid = jnp.roll(valid, 1)
        differs = (data != prev_data) | (valid != prev_valid)
        change = change | differs
    change = change.at[0].set(False)
    # New segment whenever keys change, restricted to in-mask rows.
    boundary = change & in_mask
    seg = jnp.cumsum(boundary.astype(jnp.int64))
    num_segments = jnp.where(jnp.any(in_mask), seg[-1] + 1, 0)
    # Rows outside the mask go to a trailing segment.
    seg = jnp.where(in_mask, seg, num_segments)
    return seg, num_segments


# Below this many segments, scatter-based segment ops are replaced by masked
# broadcast-reductions: XLA fuses the (S, N) compare+select into the reduce
# (bandwidth-bound VPU work), while TPU scatter-adds serialize badly
# (~130 ms per 2M-row f64 plane measured on v5e vs ~1 ms for the fused form).
_DENSE_SEGMENT_LIMIT = 256

# The CPU backend inverts the TPU scatter economics: XLA:CPU lowers
# scatter-add/min/max to a serial update loop that costs ~1 pass over the
# rows REGARDLESS of segment count (round-14: 84 ms for a 2M-row 10k-group
# i64 sum vs 828 ms presort + 91 ms scan on the sort-based path), while the
# dense broadcast-reduce costs nseg passes.  Engine dispatch below picks
# per backend; YT_TPU_SEGMENT_ENGINE ∈ {scan, scatter} overrides (read at
# trace time — switching it mid-process does not invalidate cached
# programs, same contract as YT_TPU_SORT_ENGINE).
_DENSE_SEGMENT_LIMIT_SCATTER = 16


def segment_engine() -> str:
    """Reduction engine for segment counts above the dense limit:
    "scan" (presort + segmented associative scan — the TPU path) or
    "scatter" (native .at[].add/min/max — the CPU path)."""
    engine = os.environ.get("YT_TPU_SEGMENT_ENGINE", "auto")
    if engine == "auto":
        return "scatter" if jax.default_backend() == "cpu" else "scan"
    if engine not in ("scan", "scatter"):
        raise ValueError(f"unknown YT_TPU_SEGMENT_ENGINE {engine!r}")
    return engine


def _dense_limit() -> int:
    # Scatter costs ~flat in nseg, so the dense crossover sits far lower
    # than the scan engine's (dense cost grows ~linearly with nseg).
    return _DENSE_SEGMENT_LIMIT_SCATTER if segment_engine() == "scatter" \
        else _DENSE_SEGMENT_LIMIT


def _scatter_segment_reduce(function: str, data: jax.Array,
                            seg_ids: jax.Array, num_segments: int):
    """Single-pass native scatter reduce.  Out-of-range segment ids (the
    general group path parks masked rows at a traced id that can equal
    num_segments) drop silently — exactly the trailing-garbage contract of
    the other engines."""
    if function == "sum":
        init = jnp.zeros(num_segments, dtype=data.dtype)
        return init.at[seg_ids].add(data, mode="drop")
    neutral = _reduce_neutral(data.dtype, function)
    init = jnp.full(num_segments, neutral, dtype=data.dtype)
    if function == "min":
        return init.at[seg_ids].min(data, mode="drop")
    if function == "max":
        return init.at[seg_ids].max(data, mode="drop")
    raise ValueError(function)


def _dense_segment_reduce(function: str, data: jax.Array, seg_ids: jax.Array,
                          num_segments: int):
    sids = jnp.arange(num_segments, dtype=seg_ids.dtype)

    if function == "sum":
        def one(s):
            return jnp.where(seg_ids == s, data, jnp.zeros_like(data)).sum()
    elif function == "min":
        neutral = _reduce_neutral(data.dtype, "min")
        def one(s):
            return jnp.where(seg_ids == s, data, neutral).min()
    elif function == "max":
        neutral = _reduce_neutral(data.dtype, "max")
        def one(s):
            return jnp.where(seg_ids == s, data, neutral).max()
    else:
        raise ValueError(function)
    return jax.vmap(one)(sids)


def _sorted_segment_reduce(function: str, data: jax.Array,
                           seg_ids: jax.Array, num_segments: int):
    """Segment reduce for NONDECREASING seg_ids with no scatter: a
    segmented associative scan (the combine resets at segment starts, so
    float sums keep per-segment precision) + a searchsorted gather at each
    segment's last row.  TPU scatter-adds serialize (~130 ms per 2M-row
    f64 plane measured on v5e); log-depth scans and gathers do not."""
    cap = data.shape[0]
    starts = jnp.concatenate([
        jnp.ones(1, dtype=bool), seg_ids[1:] != seg_ids[:-1]])
    if function == "sum":
        combine_val = lambda a, b: a + b
    elif function == "min":
        combine_val = jnp.minimum
    elif function == "max":
        combine_val = jnp.maximum
    else:
        raise ValueError(function)

    def combine(x, y):
        xv, xf = x
        yv, yf = y
        return jnp.where(yf, yv, combine_val(xv, yv)), xf | yf

    scanned, _ = jax.lax.associative_scan(combine, (data, starts))
    sids = jnp.arange(num_segments, dtype=seg_ids.dtype)
    left = jnp.searchsorted(seg_ids, sids, side="left")
    right = jnp.searchsorted(seg_ids, sids, side="right")
    out = scanned[jnp.clip(right - 1, 0, cap - 1)]
    if function == "sum":
        neutral = jnp.zeros((), dtype=data.dtype)
    else:
        neutral = _reduce_neutral(data.dtype, function)
    return jnp.where(right > left, out, neutral)


def _segment_reduce(function: str, data: jax.Array, seg_ids: jax.Array,
                    num_segments: int, assume_sorted: bool = False):
    if num_segments <= _dense_limit():
        return _dense_segment_reduce(function, data, seg_ids, num_segments)
    if segment_engine() == "scatter":
        # CPU: one native scatter pass, sorted or not.  (Float sums
        # accumulate in scatter-visit order rather than per-segment scan
        # order — the same sanctioned divergence the interpreter tier's
        # np.add.at already has.)
        return _scatter_segment_reduce(function, data, seg_ids,
                                       num_segments)
    if assume_sorted:
        return _sorted_segment_reduce(function, data, seg_ids, num_segments)
    # Unsorted mid/high cardinality: NEVER scatter (TPU scatter-adds with
    # duplicate indices serialize — measured 23.8 s for a 64M-row 10k-group
    # segment_sum on v5e).  One u32 sort by segment id + a segmented scan
    # is orders of magnitude cheaper.  Hot paths pre-sort ONCE for all
    # aggregates (lowering's group stage) and take assume_sorted instead.
    order = stable_argsort_u32([seg_ids.astype(jnp.uint32)])
    return _sorted_segment_reduce(function, data[order], seg_ids[order],
                                  num_segments)


def presort_segments(seg_ids: jax.Array,
                     num_segments: int) -> "jax.Array | None":
    """Shared presort policy for multi-aggregate group stages: returns the
    row order to apply once (then pass assume_sorted=True for every
    aggregate), or None when the reduce needs no ordering — the dense
    broadcast path, and the ENTIRE scatter engine (CPU), whose reduces are
    order-independent single passes; skipping the group-stage sort there
    is the round-14 groupby win.  Keeping the dispatch HERE keeps it in
    lockstep with _segment_reduce's threshold."""
    if num_segments <= _dense_limit() or segment_engine() == "scatter":
        return None
    return stable_argsort_u32([seg_ids.astype(jnp.uint32)])


def segment_aggregate(function: str, data: jax.Array, valid: jax.Array,
                      seg_ids: jax.Array, num_segments: int,
                      value_type: EValueType,
                      assume_sorted: bool = False
                      ) -> tuple[jax.Array, jax.Array]:
    """Aggregate `data` per segment, skipping nulls. Returns (out, out_valid)
    planes of length num_segments (static capacity).  assume_sorted=True
    (nondecreasing seg_ids — the hash-grouped general path) switches to the
    scatter-free segmented-scan reduction."""
    contributes = valid
    count = _segment_reduce(
        "sum", contributes.astype(jnp.int64), seg_ids, num_segments,
        assume_sorted)
    any_valid = count > 0
    if function == "count":
        return count, jnp.ones_like(any_valid)
    if function == "sum":
        masked = jnp.where(contributes, data, jnp.zeros_like(data))
        out = _segment_reduce("sum", masked, seg_ids, num_segments,
                              assume_sorted)
        return out, any_valid
    if function == "min" or function == "max":
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
        neutral = _reduce_neutral(data.dtype, function)
        masked = jnp.where(contributes, data, neutral)
        out = _segment_reduce(function, masked, seg_ids, num_segments,
                              assume_sorted)
        if value_type is EValueType.boolean:
            out = out.astype(jnp.bool_)
        return out, any_valid
    if function == "first":
        first_idx = _segment_first_index(contributes, seg_ids, num_segments,
                                         assume_sorted)
        return data[first_idx], any_valid
    raise ValueError(f"Unknown segment aggregate {function!r}")


def _reduce_neutral(dtype, function: str):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(np.inf if function == "min" else -np.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if function == "min" else info.min, dtype=dtype)


def _segment_first_index(eligible: jax.Array, seg_ids: jax.Array,
                         num_segments: int,
                         assume_sorted: bool = False) -> jax.Array:
    """First row index per segment among `eligible` rows (clipped sentinel
    when a segment has none — callers must mask validity separately)."""
    cap = eligible.shape[0]
    idx = jnp.where(eligible, jnp.arange(cap), cap - 1)
    first = _segment_reduce("min", idx, seg_ids, num_segments,
                            assume_sorted)
    return jnp.clip(first, 0, cap - 1)


def segment_arg_by(value_data: jax.Array, value_valid: jax.Array,
                   by_data: jax.Array, by_valid: jax.Array,
                   seg_ids: jax.Array, num_segments: int,
                   take_max: bool,
                   assume_sorted: bool = False
                   ) -> tuple[jax.Array, jax.Array]:
    """Per segment: the value at the row whose `by` key is smallest/largest
    (argmin/argmax; rows with null or NaN `by` don't compete; ties take the
    first row)."""
    if by_data.dtype == jnp.bool_:
        by_data = by_data.astype(jnp.int8)
    competes = by_valid
    if jnp.issubdtype(by_data.dtype, jnp.floating):
        # NaN poisons the reduce AND never equals the extreme, which would
        # select an arbitrary row flagged valid.
        competes = competes & ~jnp.isnan(by_data)
    fn = "max" if take_max else "min"
    neutral = _reduce_neutral(by_data.dtype, fn)
    masked_by = jnp.where(competes, by_data, neutral)
    extreme = _segment_reduce(fn, masked_by, seg_ids, num_segments,
                              assume_sorted)
    winner = competes & (masked_by == extreme[seg_ids])
    first_idx = _segment_first_index(winner, seg_ids, num_segments,
                                     assume_sorted)
    any_competes = _segment_reduce(
        "sum", competes.astype(jnp.int64), seg_ids, num_segments,
        assume_sorted) > 0
    return value_data[first_idx], value_valid[first_idx] & any_competes


def segment_distinct_count(data: jax.Array, valid: jax.Array,
                           seg_ids: jax.Array, num_segments: int
                           ) -> tuple[jax.Array, jax.Array]:
    """Exact per-segment distinct count of `data` (nulls don't count).

    One extra lexsort by (segment, value): a row is "new" when its (segment,
    value) differs from the previous row's.  The reference's `cardinality`
    is an HLL approximation (library/query engine UDF); exact is affordable
    here because the sort is one fused device pass.
    """
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    value = jnp.where(valid, data, jnp.zeros_like(data))
    nan_flag = jnp.zeros(value.shape[0], dtype=jnp.int8)
    if jnp.issubdtype(value.dtype, jnp.floating):
        # Float equality pitfalls: NaN != NaN (every NaN would count) and
        # -0.0 == +0.0 bit-wise distinct.  Canonicalize: -0.0 → +0.0 via
        # `+ 0.0`; NaNs → +inf with a side flag so NaN stays distinct from a
        # real +inf.  (No bitcast: f64→i64 bitcasts don't lower on TPU X64.)
        is_nan = jnp.isnan(value)
        nan_flag = is_nan.astype(jnp.int8)
        value = jnp.where(is_nan, jnp.full_like(value, jnp.inf),
                          value + 0.0)
    flags_word = (valid.astype(jnp.uint32) << np.uint32(1)) | \
        nan_flag.astype(jnp.uint32)
    order = stable_argsort_u32(
        [seg_ids.astype(jnp.uint32), flags_word,
         *monotone_u32_words(value, jnp.ones_like(valid))])
    seg_s = seg_ids[order]
    val_s = value[order]
    valid_s = valid[order]
    nan_s = nan_flag[order]
    prev_seg = jnp.roll(seg_s, 1)
    prev_val = jnp.roll(val_s, 1)
    prev_valid = jnp.roll(valid_s, 1)
    prev_nan = jnp.roll(nan_s, 1)
    new_value = (seg_s != prev_seg) | (val_s != prev_val) | \
        (valid_s != prev_valid) | (nan_s != prev_nan)
    new_value = new_value.at[0].set(True)
    flags = (new_value & valid_s).astype(jnp.int64)
    # seg_s is the major sort key above, so it is nondecreasing.
    counts = _segment_reduce("sum", flags, seg_s, num_segments,
                             assume_sorted=True)
    return counts.astype(jnp.uint64), jnp.ones(num_segments, dtype=bool)


# --- segmented prefix scans (window-function backbone) ------------------------
#
# Window functions (query/engine/window.py) lower to these: ranking is a
# segmented position/peer scan, running aggregates are segmented inclusive
# scans, ROWS frames are scan differences (sum/count) or doubling-table
# range queries (min/max).  All operate on SEGMENT-SORTED planes (equal
# partition keys adjacent); `starts[i]` marks row i as the first of its
# segment (starts[0] must be True for a non-empty plane).


def _scan_combine(combine_val):
    """Segmented-scan monoid over (value, start_flag) pairs: the combine
    resets at segment starts (associative — the standard construction)."""
    def combine(x, y):
        xv, xf = x
        yv, yf = y
        return jnp.where(yf, yv, combine_val(xv, yv)), xf | yf
    return combine


def segment_scan(function: str, data: jax.Array,
                 starts: jax.Array) -> jax.Array:
    """Segmented INCLUSIVE prefix scan (sum/min/max), log-depth via
    associative_scan — no scatters, the TPU-native window primitive."""
    if function == "sum":
        combine_val = lambda a, b: a + b
    elif function == "min":
        combine_val = jnp.minimum
    elif function == "max":
        combine_val = jnp.maximum
    else:
        raise ValueError(f"Unknown scan function {function!r}")
    scanned, _ = jax.lax.associative_scan(
        _scan_combine(combine_val), (data, starts))
    return scanned


def segment_suffix_scan(function: str, data: jax.Array,
                        starts: jax.Array) -> jax.Array:
    """Segmented inclusive SUFFIX scan (combine toward segment ends):
    reverse the plane, rebuild start flags from the forward ends, scan,
    reverse back."""
    n = data.shape[0]
    ends = jnp.concatenate([starts[1:], jnp.ones(1, dtype=bool)])
    return segment_scan(function, data[::-1], ends[::-1])[::-1]


def segment_start_index(starts: jax.Array) -> jax.Array:
    """Per row: index of its segment's FIRST row.  Running max of
    (starts ? i : 0) — segment starts arrive in increasing index order,
    so no reset is needed."""
    iota = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return jax.lax.associative_scan(
        jnp.maximum, jnp.where(starts, iota, jnp.zeros_like(iota)))


def segment_end_index(starts: jax.Array) -> jax.Array:
    """Per row: index of its segment's LAST row (reverse of
    segment_start_index over the mirrored plane)."""
    n = starts.shape[0]
    ends = jnp.concatenate([starts[1:], jnp.ones(1, dtype=bool)])
    iota = jnp.arange(n, dtype=jnp.int32)
    rev_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(ends[::-1], iota, jnp.zeros_like(iota)))
    return (n - 1) - rev_start[::-1]


def segment_position(starts: jax.Array) -> jax.Array:
    """0-based row position within its segment (row_number() - 1)."""
    iota = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return iota - segment_start_index(starts)


def segment_shift(data: jax.Array, valid: jax.Array, starts: jax.Array,
                  shift: int, seg_lo: "jax.Array | None" = None,
                  seg_hi: "jax.Array | None" = None
                  ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Within-segment shifted gather: row i reads row i-shift (shift>0 =
    lag, shift<0 = lead).  Returns (data, valid, in_segment) — rows whose
    source falls outside their own segment get in_segment=False and the
    caller substitutes the default.  Callers that already hold the
    per-row segment bounds pass seg_lo/seg_hi to skip recomputing the
    two index scans."""
    n = data.shape[0]
    src = jnp.arange(n, dtype=jnp.int32) - shift
    if seg_lo is None:
        seg_lo = segment_start_index(starts)
    if seg_hi is None:
        seg_hi = segment_end_index(starts)
    in_seg = (src >= seg_lo) & (src <= seg_hi)
    src = jnp.clip(src, 0, n - 1)
    return data[src], valid[src], in_seg


def segment_range_extreme(function: str, data: jax.Array, valid: jax.Array,
                          lo: jax.Array, hi: jax.Array,
                          max_width: int) -> jax.Array:
    """Per-row min/max over rows [lo_i, hi_i] (a ROWS frame already
    clipped inside the row's segment; lo_i <= hi_i, hi_i - lo_i + 1 <=
    max_width).  Sparse-table range query: level p holds the reduce of
    the 2^p rows starting at each index (O(n log w) build, two gathers
    per query) — the log-depth sliding-window reduction bounded frames
    need where a prefix-scan difference only works for sums."""
    n = data.shape[0]
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    neutral = _reduce_neutral(data.dtype, function)
    combine = jnp.minimum if function == "min" else jnp.maximum
    base = jnp.where(valid, data, neutral)
    n_levels = max(int(max_width).bit_length() - 1, 1)   # floor(log2(w))
    levels = [base]
    for p in range(1, n_levels + 1):
        half = 1 << (p - 1)
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[half:], jnp.full(half, neutral, dtype=prev.dtype)])
        levels.append(combine(prev, shifted))
    table = jnp.stack(levels)                    # (n_levels+1, n)
    length = (hi - lo + 1).astype(jnp.int32)
    # p = floor(log2(length)) via static comparisons (exact, no floats).
    p = jnp.zeros(n, dtype=jnp.int32)
    for k in range(1, n_levels + 1):
        p = p + (length >= (1 << k)).astype(jnp.int32)
    pow_p = (jnp.ones(n, dtype=jnp.int32) << p)
    flat = table.reshape(-1)
    left = flat[p * n + jnp.clip(lo, 0, n - 1)]
    right = flat[p * n + jnp.clip(hi - pow_p + 1, 0, n - 1)]
    return combine(left, right)


def compact_mask(mask: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Indices that move in-mask rows to the front (stable); plus count."""
    order = stable_argsort_u32([(~mask).astype(jnp.uint32)])
    return order, jnp.sum(mask.astype(jnp.int64))


# --- packed sort keys ---------------------------------------------------------
#
# lax.sort moves EVERY operand plane through the whole sort network, so the
# cost of a lexsort grows with plane count x plane width — and on TPU each
# 64-bit operand's comparator is EMULATED as u32 limb pairs inside every
# stage of the O(n log^2 n) network.  The planes from sort_key_planes
# (value + null per key, plus the row mask) are collapsed here into as few
# u32 words as possible via order-preserving bit packing: a two-dict-key
# ORDER BY + mask becomes ONE u32 operand; an i64 key becomes two native
# u32 words.  (The reference's row comparers JIT a composite comparator —
# row_comparer_api; on TPU the composite packed KEY is the idiomatic
# equivalent.)

_SIGN64 = np.uint64(1 << 63)
_SIGN32 = np.uint32(1 << 31)


def _f64_bits_u32(data: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(hi, lo) u32 words of an f64 plane.  TPUs have no 64-bit lanes:
    the X64 rewriter stores f64 as u32 pairs, and a same-width
    bitcast f64→u64 is UNIMPLEMENTED there (measured on v5e: the AOT
    compile fails) — but the 64→32 split bitcast is exactly its native
    representation."""
    words = jax.lax.bitcast_convert_type(data.astype(jnp.float64),
                                         jnp.uint32)
    return words[..., 1], words[..., 0]        # little-endian


def monotone_u32_words(data: jax.Array,
                       valid: jax.Array) -> list[jax.Array]:
    """Order-preserving encoding as u32 WORDS, major first.

    The device sort's comparator cost is per-operand-word; TPU compares
    u32 natively but emulates u64 as limb pairs INSIDE every comparator
    of the O(n log^2 n) sort network.  Encoding once into u32 words moves
    the limb split out of the network: 64-bit types cost one elementwise
    decomposition pass, then every comparator is native."""
    if data.dtype == jnp.bool_:
        words = [data.astype(jnp.uint32)]
    elif data.dtype == jnp.float32:
        bits = jax.lax.bitcast_convert_type(data, jnp.uint32)
        sign = (bits >> np.uint32(31)).astype(bool)
        words = [jnp.where(sign, ~bits, bits | _SIGN32)]
    elif jnp.issubdtype(data.dtype, jnp.floating):
        hi, lo = _f64_bits_u32(data)
        sign = (hi >> np.uint32(31)).astype(bool)
        words = [jnp.where(sign, ~hi, hi | _SIGN32),
                 jnp.where(sign, ~lo, lo)]
    elif data.dtype in (jnp.int32, jnp.int16, jnp.int8):
        words = [data.astype(jnp.int32).astype(jnp.uint32) ^ _SIGN32]
    elif data.dtype in (jnp.uint32, jnp.uint16, jnp.uint8):
        words = [data.astype(jnp.uint32)]
    elif jnp.issubdtype(data.dtype, jnp.unsignedinteger):
        x = data.astype(jnp.uint64)
        words = [(x >> np.uint64(32)).astype(jnp.uint32),
                 x.astype(jnp.uint32)]
    else:
        x = data.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64
        words = [(x >> np.uint64(32)).astype(jnp.uint32),
                 x.astype(jnp.uint32)]
    zero = jnp.zeros((), jnp.uint32)
    return [jnp.where(valid, w, zero) for w in words]


def pack_key_planes_bits(items) -> tuple[list[jax.Array], list[int]]:
    """items: (data, valid, descending, value_bits) MAJOR key first.

    value_bits <= 31 asserts the encoded value fits [0, 2^bits) AND
    leaves room for its null bit in one u32 word (dictionary codes,
    booleans, small ints); anything wider goes full-width via
    monotone_u32_words.  Each field carries a null bit above its value
    (ascending: null sorts first; descending: null sorts last — YT
    comparator semantics).  Returns (u32 planes major-first, significant
    LOW bits per plane): the last word is shifted down so its unused bits
    sit HIGH and zero, letting the radix engine skip whole byte passes
    (a 12-bit packed key costs 2 passes, not 4).  TPU compares u32
    natively, so no sort path ever touches an emulated 64-bit
    comparator."""
    words: list[jax.Array] = []
    bits_left = 0

    def push(plane: jax.Array, width: int) -> None:
        nonlocal bits_left
        if width > bits_left:
            words.append(jnp.zeros_like(plane))
            bits_left = 32
        bits_left -= width
        words[-1] = words[-1] | (plane << np.uint32(bits_left))

    for data, valid, descending, value_bits in items:
        null_plane = ((~valid) if descending else valid).astype(jnp.uint32)
        if value_bits > 31:        # 32-bit value + null bit exceed one word
            value_words = monotone_u32_words(data, valid)
            if descending:
                value_words = [jnp.where(valid, ~w, jnp.zeros_like(w))
                               for w in value_words]
            push(null_plane, 1)
            for w in value_words:      # full words, less significant
                push(w, 32)
        else:
            enc = data.astype(jnp.uint32) & np.uint32(
                (1 << value_bits) - 1)
            if descending:
                enc = np.uint32((1 << value_bits) - 1) - enc
            enc = jnp.where(valid, enc, jnp.zeros_like(enc))
            push((null_plane << np.uint32(value_bits)) | enc,
                 value_bits + 1)
    sig = [32] * len(words)
    if words and bits_left:
        # Unused bits of the final word move from LOW to HIGH (zeros):
        # relative order is unchanged, and byte passes above the
        # significant width can be skipped.
        words[-1] = words[-1] >> np.uint32(bits_left)
        sig[-1] = 32 - bits_left
    return words, sig


# Above this row count, sorts leave the single-pass network (which
# re-evaluates the composite comparator inside every compare-exchange of
# an O(n log^2 n) network whose depth grows with the FULL row count) for
# the tiled radix engine.  Tunable: the v5e cliff sits past ~8M.
LSD_SORT_THRESHOLD = int(os.environ.get("YT_TPU_LSD_SORT_THRESHOLD",
                                        8 * 1024 * 1024))


def stable_argsort_u32(words: list[jax.Array],
                       lsd: "bool | None" = None,
                       word_bits: "list[int] | None" = None) -> jax.Array:
    """Stable ascending argsort over u32 key words (major first); the
    payload rides as a u32 iota so no 64-bit plane enters the sort.

    word_bits[k] (optional) bounds the significant LOW bits of word k —
    the radix engine skips byte passes above the bound.

    Engine dispatch (YT_TPU_SORT_ENGINE overrides):
      network — one variadic lax.sort; best below the ~8M network cliff.
      lsd32   — one full-width stable u32 lax.sort per word (round-2
                engine, kept for measurement).
      radix   — tiled 8-bit LSD counting sort (ops/radix.py): per-TILE
                sort networks + histogram rank movement; depth never
                grows with n.  Default past LSD_SORT_THRESHOLD.
      radix_scatter — radix with the permutation-scatter write path.
      radix_pallas (alias: pallas) — counting pass as a Pallas TPU
                kernel + permutation scatter (ops/pallas_radix.py).

    Unknown engine names raise (a typo must not silently run the
    one-pass network into the very cliff the engines exist to avoid).
    """
    n = words[0].shape[0]
    engine = os.environ.get("YT_TPU_SORT_ENGINE", "auto")
    if lsd is not None:                      # explicit caller override
        engine = "lsd32" if lsd else "network"
    if engine == "auto":
        # The network's comparator cost grows with operand count too
        # (round-1 observation: full multi-plane lexsorts collapse past
        # ~4M rows), so the cliff threshold scales down with word count.
        effective = min(LSD_SORT_THRESHOLD,
                        2 * LSD_SORT_THRESHOLD // max(len(words), 1))
        engine = "network" if n <= effective else "radix"
    if engine in ("radix", "radix_scatter", "radix_pallas", "pallas"):
        from ytsaurus_tpu.ops.radix import radix_argsort_u32
        sub_engine = {"radix": "gather", "radix_scatter": "scatter",
                      "radix_pallas": "pallas",
                      "pallas": "pallas"}[engine]
        return radix_argsort_u32(words, word_bits, engine=sub_engine)
    if engine not in ("network", "lsd32"):
        raise ValueError(f"unknown YT_TPU_SORT_ENGINE {engine!r}")
    iota = jnp.arange(n, dtype=jnp.uint32)
    if engine == "lsd32":
        perm = iota
        for word in reversed(words):
            keys = jnp.take(word, perm)
            _, perm = jax.lax.sort((keys, perm), num_keys=1,
                                   is_stable=True)
        return perm
    out = jax.lax.sort((*words, iota), num_keys=len(words),
                       is_stable=True)
    return out[-1]


def packed_sort_indices(items) -> jax.Array:
    """Stable ascending argsort over packed key fields (major first)."""
    words, bits = pack_key_planes_bits(items)
    return stable_argsort_u32(words, word_bits=bits)


# --- exact grouping order -----------------------------------------------------

def hash_group_order(key_planes, mask) -> jax.Array:
    """Row ordering that makes equal group keys adjacent, masked rows
    last, using the EXACT order-preserving key encoding.

    History: rounds 1-2 ordered rows by a 128-bit hash of the key planes
    (cheap fixed operand count, but a full double-word collision could
    silently merge or fragment a group).  The tiled radix engine makes
    the exact encoding the cheaper path as well for typical key shapes —
    one int64 key is 9 byte passes versus the hash's 16 — so group
    identity no longer rides on any hash bits at all: the analog of
    TGroupByClosure's exact hash table semantics
    (yt/yt/library/query/engine/cg_routines/registry.cpp:1230), reached
    by counting-sort adjacency instead of open addressing.

    Encoding: word0 packs [masked-out bit (most significant) | one
    validity bit per key], then each key contributes its full monotone
    u32 words.  Invalid values are zeroed by monotone_u32_words, so the
    validity bit alone distinguishes NULL from literal zero."""
    n = mask.shape[0]
    words: list[jax.Array] = []
    bits: list[int] = []
    flags = (~mask).astype(jnp.uint32)
    nflag = 1
    for data, valid in key_planes:
        if nflag == 32:            # >31 keys: overflow into another word
            words.append(flags)
            bits.append(nflag)
            flags = jnp.zeros(n, dtype=jnp.uint32)
            nflag = 0
        flags = (flags << np.uint32(1)) | valid.astype(jnp.uint32)
        nflag += 1
    words.append(flags)
    bits.append(nflag)
    for data, valid in key_planes:
        vw = monotone_u32_words(data, valid)
        words.extend(vw)
        bits.extend([32] * len(vw))
    return stable_argsort_u32(words, word_bits=bits)
