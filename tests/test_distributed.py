"""SPMD (shard_map) distributed execution tests on the virtual 8-device mesh."""

import numpy as np
import pytest

from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.parallel.distributed import DistributedEvaluator, ShardedTable
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"), ("v", "double")])
T = "//t"


@pytest.fixture(scope="module")
def table8():
    from ytsaurus_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(42)
    chunks = []
    for s in range(8):
        n = 100 + s * 13
        chunks.append(ColumnarChunk.from_arrays(
            SCHEMA,
            {"k": np.arange(n) + s * 10_000,
             "g": rng.integers(0, 5, n),
             "v": rng.uniform(0, 10, n)}))
    return make_mesh(8), chunks


def _numpy_rows(chunks):
    rows = []
    for c in chunks:
        rows.extend(c.to_rows())
    return rows


def test_spmd_group_by_matches_host(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        f"g, sum(v) AS s, count(*) AS c, avg(v) AS a FROM [{T}] GROUP BY g",
        {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    # numpy oracle
    rows = _numpy_rows(chunks)
    want = {}
    for r in rows:
        e = want.setdefault(r["g"], [0.0, 0])
        e[0] += r["v"]
        e[1] += 1
    assert len(out) == len(want)
    for r in sorted(out, key=lambda r: r["g"]):
        s, c = want[r["g"]]
        assert abs(r["s"] - s) < 1e-6
        assert r["c"] == c
        assert abs(r["a"] - s / c) < 1e-9


def test_spmd_filter_scan(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"k FROM [{T}] WHERE v > 9.0", {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    want = sorted(r["k"] for r in _numpy_rows(chunks) if r["v"] > 9.0)
    assert sorted(r["k"] for r in out) == want


def test_spmd_top_k(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"k, v FROM [{T}] ORDER BY v DESC LIMIT 5", {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    want = sorted(_numpy_rows(chunks), key=lambda r: -r["v"])[:5]
    assert [r["k"] for r in out] == [r["k"] for r in want]


def test_spmd_string_group_keys():
    import jax
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string")])
    names = ["ant", "bee", "cat", "dog"]
    chunks = []
    for d in range(8):
        rows = [(d * 100 + i, names[(d + i) % 4]) for i in range(10)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"s, count(*) AS c FROM [{T}] GROUP BY s", {T: schema})
    out = ev.run(plan, table).to_rows()
    assert sorted((r["s"], r["c"]) for r in out) == \
        [(b"ant", 20), (b"bee", 20), (b"cat", 20), (b"dog", 20)]


def test_spmd_shuffled_group_by_matches_gather():
    # High-cardinality GROUP BY via all_to_all repartition: results must
    # match the gather-merge path and the numpy oracle exactly.
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    rng = np.random.default_rng(5)
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "double")])
    chunks = []
    for s in range(8):
        n = 400
        chunks.append(ColumnarChunk.from_arrays(
            schema, {"k": np.arange(n) + s * n,
                     "g": rng.integers(0, 500, n),      # ~500 groups
                     "v": rng.uniform(0, 1, n)}))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g "
        "ORDER BY g LIMIT 1000", {T: schema})
    shuffled = ev.run(plan, table, shuffle=True).to_rows()
    gathered = ev.run(plan, table, shuffle=False).to_rows()
    # Sums accumulate in different orders across the two paths → compare
    # with a float tolerance, exact for keys/counts.
    assert [r["g"] for r in shuffled] == [r["g"] for r in gathered]
    assert [r["c"] for r in shuffled] == [r["c"] for r in gathered]
    assert all(abs(a["s"] - b["s"]) < 1e-9
               for a, b in zip(shuffled, gathered))
    # numpy oracle
    want = {}
    for c in chunks:
        for r in c.to_rows():
            e = want.setdefault(r["g"], [0.0, 0])
            e[0] += r["v"]
            e[1] += 1
    assert len(shuffled) == len(want)
    for r in shuffled:
        s, cnt = want[r["g"]]
        assert abs(r["s"] - s) < 1e-9 and r["c"] == cnt


def test_spmd_shuffled_having_and_strings():
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string"),
                               ("v", "int64")])
    words = [f"w{i:03d}" for i in range(60)]
    chunks = []
    for d in range(8):
        rows = [(d * 100 + i, words[(d * 13 + i) % 60], i) for i in range(50)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        "s, sum(v) AS t FROM [//t] GROUP BY s HAVING sum(v) > 150 "
        "ORDER BY s LIMIT 100", {T: schema})
    shuffled = ev.run(plan, table, shuffle=True).to_rows()
    gathered = ev.run(plan, table, shuffle=False).to_rows()
    assert shuffled == gathered and len(shuffled) > 0
