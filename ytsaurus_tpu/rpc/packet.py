"""Framed multipart packet codec.

Wire layout (all little-endian), after the reference's multipart-with-
checksums idea (core/bus/tcp/packet.h:9) but a fresh, minimal format:

    u32 magic          0x59545042 ("YTPB")
    u32 part_count     (< 65536)
    u64 x part_count   part lengths
    u64 x part_count   part CRC-64s (native codec, utils CRC fallback)
    bytes              parts, concatenated

Part 0 is the envelope (binary YSON), part 1 the body (binary YSON),
parts 2+ raw attachments.  Corruption anywhere fails the whole packet.
"""

from __future__ import annotations

import asyncio
import struct

from ytsaurus_tpu.native import checksum

MAGIC = 0x59545042
MAX_PARTS = 65536
MAX_PART_SIZE = 1 << 33        # 8 GiB hard cap per part

_HEAD = struct.Struct("<II")


class PacketError(Exception):
    """Malformed or corrupted packet — the connection must be dropped."""


def encode_packet(parts: list[bytes]) -> bytes:
    if len(parts) >= MAX_PARTS:
        raise PacketError(f"too many parts ({len(parts)})")
    out = bytearray(_HEAD.pack(MAGIC, len(parts)))
    for p in parts:
        out += struct.pack("<Q", len(p))
    for p in parts:
        out += struct.pack("<Q", checksum(bytes(p)))
    for p in parts:
        out += p
    return bytes(out)


async def write_packet(writer: asyncio.StreamWriter,
                       parts: list[bytes]) -> None:
    writer.write(encode_packet(parts))
    await writer.drain()


async def read_packet(reader: asyncio.StreamReader) -> list[bytes]:
    head = await reader.readexactly(_HEAD.size)
    magic, count = _HEAD.unpack(head)
    if magic != MAGIC:
        raise PacketError(f"bad magic {magic:#x}")
    if count >= MAX_PARTS:
        raise PacketError(f"bad part count {count}")
    meta = await reader.readexactly(16 * count)
    lengths = struct.unpack(f"<{count}Q", meta[: 8 * count])
    crcs = struct.unpack(f"<{count}Q", meta[8 * count:])
    for ln in lengths:
        if ln > MAX_PART_SIZE:
            raise PacketError(f"part too large ({ln})")
    parts = []
    for ln, crc in zip(lengths, crcs):
        data = await reader.readexactly(ln)
        if checksum(data) != crc:
            raise PacketError("part checksum mismatch")
        parts.append(data)
    return parts
