"""Block compression codec registry.

Ref: yt/yt/core/compression/public.h (None/Snappy/Lz4/Brotli/Zlib/Zstd/
Lzma/Bzip2 codec enum).  Stdlib codecs are always present; lz4/zstd register
when importable.  Codec names are stored in chunk metas, so they are stable
identifiers.
"""

from __future__ import annotations

import bz2
import lzma
import zlib
from typing import Callable

from ytsaurus_tpu.errors import EErrorCode, YtError

_CODECS: dict[str, tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {}


def register_codec(name: str, compress, decompress) -> None:
    _CODECS[name] = (compress, decompress)


register_codec("none", lambda b: b, lambda b: b)
for level in (1, 6, 9):
    register_codec(f"zlib_{level}",
                   (lambda lv: lambda b: zlib.compress(b, lv))(level),
                   zlib.decompress)
register_codec("lzma", lzma.compress, lzma.decompress)
register_codec("bzip2", bz2.compress, bz2.decompress)

try:  # optional
    import lz4.frame as _lz4

    register_codec("lz4", _lz4.compress, _lz4.decompress)
except Exception:  # pragma: no cover
    pass

try:  # optional
    import zstandard as _zstd

    register_codec("zstd_3",
                   lambda b: _zstd.ZstdCompressor(level=3).compress(b),
                   lambda b: _zstd.ZstdDecompressor().decompress(b))
except Exception:  # pragma: no cover
    pass


def get_codec(name: str):
    codec = _CODECS.get(name)
    if codec is None:
        raise YtError(f"Unknown compression codec {name!r}",
                      code=EErrorCode.ChunkFormatError)
    return codec


def codec_names() -> list[str]:
    return sorted(_CODECS)
