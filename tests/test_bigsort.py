"""External (spill-capable) sort: HBM-budgeted range partitioning.

Ref model: the Sort controller partition tree
(controllers/sort_controller.cpp:459 — partitions sized so each final
sort fits one job's memory), samples_fetcher key sampling, partition_job
row routing.  Redesigned host-spill pipeline in ops/bigsort.py.
"""

import numpy as np
import pytest

from ytsaurus_tpu.chunks.columnar import ColumnarChunk

# ~1 min of spill-pipeline compiles: excluded from the tier-1 quick pass
# (-m 'not slow'); run explicitly via `pytest tests/test_bigsort.py`.
pytestmark = pytest.mark.slow
from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.ops.bigsort import SpillStats, external_sort
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([("k", "int64"), ("v", "double")])


def _blocks(keys: np.ndarray, block_rows: int = 5000):
    rng = np.random.default_rng(7)
    out = []
    for lo in range(0, len(keys), block_rows):
        k = keys[lo: lo + block_rows]
        out.append(ColumnarChunk.from_arrays(
            SCHEMA, {"k": k, "v": rng.random(len(k))}))
    return out


def _sorted_keys(chunks) -> np.ndarray:
    return np.concatenate(
        [np.asarray(c.columns["k"].data[: c.row_count]) for c in chunks]
    ) if chunks else np.array([], dtype=np.int64)


def test_external_sort_uniform_keys_budget_respected():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 40, size=30_000)
    stats = SpillStats()
    out = list(external_sort(_blocks(keys), ["k"],
                             budget_bytes=2000 * 18 * 2, stats=stats))
    got = _sorted_keys(out)
    assert (got == np.sort(keys)).all()
    assert stats.ranges > 1                       # really partitioned
    assert stats.peak_range_rows <= stats.budget_rows
    # Every yielded chunk individually respects the budget too.
    assert max(c.row_count for c in out) <= stats.budget_rows


def test_external_sort_skewed_keys_resplit():
    rng = np.random.default_rng(1)
    keys = np.where(rng.random(30_000) < 0.9,
                    rng.integers(0, 10, 30_000),
                    rng.integers(0, 1 << 40, 30_000))
    stats = SpillStats()
    out = list(external_sort(_blocks(keys), ["k"],
                             budget_bytes=2000 * 18 * 2, stats=stats))
    assert (_sorted_keys(out) == np.sort(keys)).all()
    assert stats.resplits > 0                     # the tree went deeper
    # Only single-key runs (indivisible) may exceed the budget.
    _, counts = np.unique(keys, return_counts=True)
    biggest_dup = int(counts.max())
    assert stats.peak_range_rows <= max(stats.budget_rows,
                                        2 * biggest_dup)


def test_external_sort_descending_and_small_input():
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 1000, size=3_000)
    out = list(external_sort(_blocks(keys, 1000), ["k"],
                             budget_bytes=1 << 30, descending=True))
    assert len(out) == 1                          # HBM-resident fast path
    assert (_sorted_keys(out) == np.sort(keys)[::-1]).all()


def test_external_sort_nulls_first_and_stats():
    rows = [{"k": None if i % 7 == 0 else int(i * 13 % 997),
             "v": float(i)} for i in range(3000)]
    blocks = [ColumnarChunk.from_rows(SCHEMA, rows[i * 1000:(i + 1) * 1000])
              for i in range(3)]
    stats = SpillStats()
    out = list(external_sort(blocks, ["k"], budget_bytes=500 * 18 * 2,
                             stats=stats))
    flat = [r["k"] for c in out for r in c.to_rows()]
    n_null = sum(1 for r in rows if r["k"] is None)
    assert all(x is None for x in flat[:n_null])
    vals = [x for x in flat if x is not None]
    assert vals == sorted(vals)
    assert stats.spilled_rows == 3000
    assert sum(stats.range_rows) == 3000


def test_external_sort_multi_key():
    rng = np.random.default_rng(3)
    schema = TableSchema.make([("a", "int64"), ("b", "int64")])
    a = rng.integers(0, 8, size=20_000)
    b = rng.integers(0, 1 << 30, size=20_000)
    blocks = [ColumnarChunk.from_arrays(
        schema, {"a": a[lo: lo + 4000], "b": b[lo: lo + 4000]})
        for lo in range(0, 20_000, 4000)]
    out = list(external_sort(blocks, ["a", "b"],
                             budget_bytes=3000 * 18 * 2))
    got = [(r["a"], r["b"]) for c in out for r in c.to_rows()]
    assert got == sorted(zip(a.tolist(), b.tolist()))


def test_external_sort_rejects_string_keys():
    schema = TableSchema.make([("s", "string")])
    chunk = ColumnarChunk.from_rows(schema, [{"s": "x"}, {"s": "a"}])
    with pytest.raises(YtError):
        list(external_sort([chunk], ["s"], budget_bytes=100))


def test_sort_controller_spill_path(tmp_path):
    """run_sort over a tiny hbm_budget routes through the external sort
    and publishes one sorted chunk per range; reads still see one
    globally sorted table."""
    from ytsaurus_tpu.client import connect
    client = connect(str(tmp_path))
    rng = np.random.default_rng(5)
    rows = [{"k": int(k), "v": float(i)}
            for i, k in enumerate(rng.integers(0, 1 << 40, size=6000))]
    client.write_table("//in", rows)
    op = client.run_sort("//in", "//out", sort_by=["k"],
                         hbm_budget=1000 * 18 * 2)
    assert op.state == "completed"
    assert op.result["spill_ranges"] > 1
    assert client.get("//out/@chunk_ids") and \
        len(client.get("//out/@chunk_ids")) > 1
    out = [r["k"] for r in client.read_table("//out")]
    assert out == sorted(r["k"] for r in rows)
    assert client.get("//out/@sorted_by") == ["k"]
    # The spilled output still feeds downstream sorted consumers (reduce).
    got = {}
    client.run_reduce(lambda key, g: [{"k": key["k"], "n": len(g)}],
                      "//out", "//red", reduce_by="k")
    got = {r["k"]: r["n"] for r in client.read_table("//red")}
    assert sum(got.values()) == 6000


def test_external_sort_callable_suppliers():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 1 << 40, size=10_000)
    blocks = _blocks(keys, 2500)
    suppliers = [lambda c=c: c for c in blocks]
    out = list(external_sort(suppliers, ["k"],
                             budget_bytes=2000 * 18 * 2))
    assert (_sorted_keys(out) == np.sort(keys)).all()
