"""Structured logging (ref: core/logging — async structured logs with
per-category levels, size-rotated compressed files; here: stdlib logging
with a structured formatter, per-category level control via
YTSAURUS_TPU_LOG_LEVEL / _LOG_CATEGORIES, and optional rotated+gzipped
file output via YTSAURUS_TPU_LOG_FILE [+ _LOG_MAX_BYTES/_LOG_BACKUPS]
— the ref's rotating compressed writer, log_manager.cpp)."""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

_CONFIGURED = False


class _DynamicStderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time so redirection/capture works."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        import sys
        return sys.stderr


class StructuredFormatter(logging.Formatter):
    """One JSON object per line: timestamp, level, category, message, fields."""

    def format(self, record: logging.LogRecord) -> str:
        entry: dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "category": record.name,
            "message": record.getMessage(),
        }
        fields = getattr(record, "fields", None)
        if fields:
            entry.update(fields)
        return json.dumps(entry, default=str)


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True
    root = logging.getLogger("ytsaurus_tpu")
    level_name = os.environ.get("YTSAURUS_TPU_LOG_LEVEL", "WARNING").upper()
    root.setLevel(getattr(logging, level_name, logging.WARNING))
    handler = _DynamicStderrHandler()
    handler.setFormatter(StructuredFormatter())
    root.addHandler(handler)
    log_file = os.environ.get("YTSAURUS_TPU_LOG_FILE")
    if log_file:
        # One env var reaches EVERY daemon a launcher spawns, and the
        # rotating handler is not multi-process safe (a rotation in one
        # process unlinks the inode others still write).  Each process
        # therefore gets its own file: base-<pid>.ext.
        base, dot, ext = log_file.rpartition(".")
        if dot:
            log_file = f"{base}-{os.getpid()}.{ext}"
        else:
            log_file = f"{log_file}-{os.getpid()}"
        root.addHandler(make_rotating_handler(
            log_file,
            max_bytes=_env_int("YTSAURUS_TPU_LOG_MAX_BYTES", 64 << 20),
            backups=_env_int("YTSAURUS_TPU_LOG_BACKUPS", 3)))
    root.propagate = False
    # Per-category overrides: "Query=debug,Tablet=info"
    overrides = os.environ.get("YTSAURUS_TPU_LOG_CATEGORIES", "")
    for part in overrides.split(","):
        if "=" in part:
            category, _, lvl = part.partition("=")
            logging.getLogger(f"ytsaurus_tpu.{category.strip()}").setLevel(
                getattr(logging, lvl.strip().upper(), logging.WARNING))


def _env_int(name: str, default: int) -> int:
    """Lenient like the module's other knobs: a malformed value falls
    back instead of aborting the first get_logger() call."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def make_rotating_handler(path: str, max_bytes: int = 64 << 20,
                          backups: int = 3) -> logging.Handler:
    """Size-rotated file handler whose rotated segments gzip on the way
    out (ref core/logging's compressed rotating writer): `path` is the
    live log; `path.1.gz` … `path.N.gz` are the history, oldest
    dropped past `backups`."""
    import gzip
    import shutil
    from logging.handlers import RotatingFileHandler

    class _GzRotatingHandler(RotatingFileHandler):
        def rotation_filename(self, default_name: str) -> str:
            return default_name + ".gz"

        def rotate(self, source: str, dest: str) -> None:
            with open(source, "rb") as src, \
                    gzip.open(dest, "wb") as out:
                shutil.copyfileobj(src, out)
            os.remove(source)

    handler = _GzRotatingHandler(path, maxBytes=max_bytes,
                                 backupCount=backups)
    handler.setFormatter(StructuredFormatter())
    return handler


def get_logger(category: str) -> logging.Logger:
    """Category logger ('Query', 'Tablet', 'Master', …)."""
    _configure()
    return logging.getLogger(f"ytsaurus_tpu.{category}")


def log_event(logger: logging.Logger, level: int, message: str,
              **fields) -> None:
    """Structured event: message + key/value fields."""
    if logger.isEnabledFor(level):
        logger.log(level, message, extra={"fields": fields})
