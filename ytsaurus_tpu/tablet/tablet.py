"""Tablet: one shard of a dynamic table — stores, snapshots, MVCC reads.

Ref mapping (server/node/tablet_node):
  TTablet (tablet.h)                  → Tablet
  store_manager write path            → Tablet.write_rows/delete_rows (locks
                                        via the transaction manager)
  store_flusher / rotation            → Tablet.rotate_store + flush()
  store_compactor                     → Tablet.compact()
  tablet_snapshot_store lock-free     → versioned snapshot chunks built per
  reads                                 flush generation, merged on read at
                                        the requested timestamp
The columnar snapshot IS the TPU-native trick: MVCC version selection
(newest version ≤ read_ts per key, tombstones drop) happens as one
vectorized pass, not a per-row k-way heap merge (tablet_reader.cpp:651).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from ytsaurus_tpu.chunks.columnar import ColumnarChunk, concat_chunks
from ytsaurus_tpu.chunks.store import ChunkCache, FsChunkStore
from ytsaurus_tpu.config import tablet_config
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import invariants
from ytsaurus_tpu.utils.invariants import check as _invariant_check
from ytsaurus_tpu.utils.profiling import PoolSensorCache, Profiler
from ytsaurus_tpu.utils.tracing import child_span
from ytsaurus_tpu.schema import EValueType, SortOrder, TableSchema
from ytsaurus_tpu.tablet import mvcc
from ytsaurus_tpu.tablet.dynamic_store import SortedDynamicStore
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
from ytsaurus_tpu.utils import sanitizers

# Process-wide snapshot-cache sensors (rendered on /metrics as
# tablet_snapshot_cache_*; the structured view is monitoring /tablet).
_snap_profiler = Profiler("tablet/snapshot_cache")
_SNAP_HITS = _snap_profiler.counter("hits")
_SNAP_MISSES = _snap_profiler.counter("misses")
_SNAP_EVICTIONS = _snap_profiler.counter("evictions")
_SNAP_BYTES = _snap_profiler.gauge("bytes_pinned")

# Per-pool tablet read counters (ISSUE 6): the serving plane threads the
# admitted cohort's pool down to the tablet read, so per-tenant resource
# accounting sees tablet-level consumption, not just gateway-level.
_lookup_counters = PoolSensorCache("tablet/lookup", ("reads", "keys"))
# guards: _snap_bytes_pinned
_snap_lock = sanitizers.register_lock("tablet._snap_lock")
_snap_bytes_pinned = 0


def _snap_bytes_add(delta: int) -> None:
    global _snap_bytes_pinned
    with _snap_lock:
        _snap_bytes_pinned += delta
        _SNAP_BYTES.set(_snap_bytes_pinned)


def snapshot_cache_stats() -> dict:
    """Live snapshot-cache counters (monitoring /tablet data source)."""
    return {
        "hits": int(_SNAP_HITS.get()),
        "misses": int(_SNAP_MISSES.get()),
        "evictions": int(_SNAP_EVICTIONS.get()),
        "bytes_pinned": _snap_bytes_pinned,
    }


def _chunk_nbytes(chunk: ColumnarChunk) -> int:
    total = 0
    for col in chunk.columns.values():
        total += col.data.size * col.data.dtype.itemsize
        total += col.valid.size
    return total


def versioned_schema(schema: TableSchema) -> TableSchema:
    """Schema of versioned snapshot chunks: keys + $timestamp/$tombstone +
    per value column (value plane, $w: written-flag plane).  The written
    planes are the per-column timestamp dimension of TVersionedRow
    (client/table_client/versioned_row.h:90-141): a version only carries
    the columns it wrote, so partial writes merge per column on read.
    Keys keep their sort order; versions sort within key by descending
    timestamp at flush time."""
    from dataclasses import replace as _replace
    cols: list = []
    for c in schema:
        if c.sort_order is not None:
            cols.append((c.name, c.type.value, c.sort_order.value))
    cols.append(("$timestamp", "int64"))
    cols.append(("$tombstone", "boolean"))
    for c in schema:
        if c.sort_order is None:
            # Keep hunk thresholds so flushes store big values out-of-row.
            cols.append(_replace(c, sort_order=None, expression=None,
                                 aggregate=None, required=False))
            cols.append((f"$w:{c.name}", "boolean"))
    return TableSchema.make(cols)


class Tablet:
    def __init__(self, schema: TableSchema, chunk_store: FsChunkStore,
                 tablet_id: str = "0", pivot_key: Optional[tuple] = None,
                 chunk_cache: Optional[ChunkCache] = None):
        if not schema.is_sorted:
            raise YtError("Dynamic tables require a sorted schema",
                          code=EErrorCode.TabletNotMounted)
        self.schema = schema
        # Cached: schema.key_columns is a rebuilding property, and
        # normalize_key sits on the per-key serving hot path.
        self._key_columns = schema.key_columns
        self.tablet_id = tablet_id
        self.pivot_key = pivot_key
        self.chunk_store = chunk_store
        self.chunk_cache = chunk_cache or ChunkCache(chunk_store)
        self.active_store = SortedDynamicStore(schema)
        self.passive_stores: list[SortedDynamicStore] = []
        self.chunk_ids: list[str] = []      # versioned snapshot chunks
        self.mounted = True
        self.in_memory = False          # pin chunks in the cache when True
        self.flush_generation = 0
        # guards: active_store, passive_stores, chunk_ids, flush_generation, _snapshot_cache, _host_planes, _row_cache, _row_cache_gen
        self._lock = sanitizers.register_rlock("tablet.Tablet._lock",
                                               hot=False)
        # Host numpy views of chunk planes: a real LRU (promote on hit,
        # capacity from TabletConfig.host_plane_cache_capacity).
        self._host_planes: "OrderedDict[str, dict]" = OrderedDict()
        self._versioned_schema = versioned_schema(schema)
        # Snapshot cache: (generation, visible chunk, built_at) for
        # latest-class reads; invalidated by any write/flush/compact via
        # the generation key.  built_at (monotonic) is what bounded-
        # staleness reads (serving brown-out rung 1) check the staleness
        # bound against.  Counters are process-wide (/metrics).
        self._snapshot_cache: \
            "Optional[tuple[tuple, ColumnarChunk, float]]" = None
        # Max committed version timestamp of the sealed chunks, memoized
        # per flush generation (read from chunk meta stats).
        self._chunk_max_ts = 0
        self._chunk_max_ts_gen = -1
        # Lookup row cache (ref tablet_node/row_cache.h): key → merged row,
        # valid for one (write, flush) generation only.
        self._row_cache: "OrderedDict[tuple, Optional[dict]]" = OrderedDict()
        self._row_cache_gen: tuple = ()
        self.row_cache_capacity = 4096
        self.row_cache_hits = 0
        self.row_cache_misses = 0
        # Pow2 floor for batched-probe needle buckets (_pad_needles);
        # the serving gateway overrides it from ServingConfig.min_bucket.
        self.probe_bucket_min = 8

    # -- write path (called under the transaction manager) ---------------------

    def normalize_row(self, row: dict) -> dict:
        """Canonical host forms per column type (strings as bytes, matching
        what chunk decode produces)."""
        out = {}
        for name, value in row.items():
            col = self.schema.find(name)
            if col is None:
                raise YtError(f"Unknown column {name!r}",
                              code=EErrorCode.QueryTypeError)
            out[name] = _normalize_value(value, col.type)
        return out

    def normalize_key(self, key: tuple) -> tuple:
        key_cols = self._key_columns
        if len(key) != len(key_cols):
            raise YtError(f"Key width {len(key)} != {len(key_cols)}")
        return tuple(_normalize_value(v, c.type)
                     for v, c in zip(key, key_cols))

    def validate_required(self, normalized_row: dict,
                          partial: bool = False) -> None:
        """THE required-column check (single source: used by tablets,
        transactions, and columnar construction paths must agree).
        partial=True (update-mode writes): only columns the row STATES are
        checked — unstated required columns keep their old values."""
        for c in self.schema:
            if not c.required:
                continue
            if partial and c.name not in normalized_row:
                continue
            if normalized_row.get(c.name) is None:
                raise YtError(f"Required column {c.name!r} is null",
                              code=EErrorCode.QueryTypeError)

    def write_row(self, row: dict, timestamp: int,
                  update: bool = False) -> None:
        row = self.normalize_row(row)
        self.validate_required(row, partial=update)
        with self._lock:       # a concurrent flush() must not drop the write
            self._check_mounted()
            self.active_store.write_row(row, timestamp, update=update)

    def delete_row(self, key: tuple, timestamp: int) -> None:
        key = self.normalize_key(key)
        with self._lock:
            self._check_mounted()
            self.active_store.delete_row(key, timestamp)

    def last_committed_timestamp(self, key: tuple) -> Optional[int]:
        """Newest committed write/delete ts for conflict detection."""
        with self._lock:
            best = self.active_store.last_committed_timestamp(key)
            for store in self.passive_stores:
                ts = store.last_committed_timestamp(key)
                if ts is not None and (best is None or ts > best):
                    best = ts
            # Chunk stores: versions are ordered newest-first per key.
            for cid in self.chunk_ids:
                ts = _chunk_last_timestamp(
                    self._decode(cid), self.schema, key,
                    self._chunk_host_planes_locked(cid))
                if ts is not None and (best is None or ts > best):
                    best = ts
            return best

    def set_in_memory(self, enabled: bool) -> None:
        """Preload+pin (or release) this tablet's chunks in the cache."""
        with self._lock:
            self.in_memory = enabled
            for cid in self.chunk_ids:
                if enabled:
                    self.chunk_cache.pin(cid)
                else:
                    self.chunk_cache.unpin(cid)

    def _check_mounted(self):
        if not self.mounted:
            raise YtError(f"Tablet {self.tablet_id} is not mounted",
                          code=EErrorCode.TabletNotMounted)

    # -- rotation / flush / compaction -----------------------------------------

    def rotate_store(self) -> None:
        """Freeze the active store (ref store_rotator)."""
        with self._lock:
            if self.active_store.key_count == 0:
                return
            self.passive_stores.append(self.active_store)
            self.active_store = SortedDynamicStore(self.schema)

    def _vectorize(self, version_count: int) -> bool:
        """Columnar-pipeline dispatch: per-program overhead dominates
        tiny stores, so small version counts keep the Python merge
        (TabletConfig.vectorized_scan_min_rows; 0 forces columnar)."""
        return mvcc.supports(self.schema) and \
            version_count >= tablet_config().vectorized_scan_min_rows

    def flush(self) -> Optional[str]:
        """Rotate + write all passive stores into one versioned chunk.
        The merge sort runs as one device program over concatenated
        store planes (tablet/mvcc.py); tiny stores keep the host sort."""
        with self._lock:
            self.rotate_store()
            if not self.passive_stores:
                return None
            total = sum(s.store_row_count for s in self.passive_stores)
            if self._vectorize(total):
                parts = [s.to_versioned_chunk(self._versioned_schema)
                         for s in self.passive_stores
                         if s.store_row_count]
                chunk = mvcc.sorted_versioned_chunk(
                    concat_chunks(parts), self.schema)
                if invariants.enabled():
                    _invariant_check(
                        "versioned_rows",
                        (self.schema.key_column_names, chunk.to_rows()))
            else:
                rows: list[dict] = []
                for store in self.passive_stores:
                    rows.extend(store.versioned_rows())
                rows.sort(key=_versioned_sort_key(self.schema))
                _invariant_check("versioned_rows",
                                 (self.schema.key_column_names, rows))
                chunk = ColumnarChunk.from_rows(self._versioned_schema,
                                                rows)
            chunk_id = self.chunk_store.write_chunk(chunk)
            self.chunk_ids.append(chunk_id)
            if self.in_memory:
                self.chunk_cache.pin(chunk_id)
            self.passive_stores.clear()
            self.flush_generation += 1
            _invariant_check("tablet", self)
            return chunk_id

    def compact(self, retention_timestamp: int = 0) -> Optional[str]:
        """Merge all snapshot chunks into one, dropping versions that are
        superseded as of `retention_timestamp` (ref store_compactor +
        lsm heuristics, majorly simplified: full major compaction)."""
        with self._lock:
            if len(self.chunk_ids) <= 0:
                return None
            chunks = [self._decode(cid) for cid in self.chunk_ids]
            total = sum(c.row_count for c in chunks)
            chunk: Optional[ColumnarChunk] = None
            if self._vectorize(total):
                merged = concat_chunks(
                    [self._normalize_versioned(c) for c in chunks])
                out = mvcc.retained_chunk(merged, self.schema,
                                          retention_timestamp)
                if out.row_count:
                    chunk = out
                if invariants.enabled() and chunk is not None:
                    _invariant_check(
                        "versioned_rows",
                        (self.schema.key_column_names, chunk.to_rows()))
            else:
                rows: list[dict] = []
                value_names = [c.name for c in self.schema
                               if c.sort_order is None]
                for c in chunks:
                    for row in c.to_rows():
                        for name in value_names:
                            row[f"$w:{name}"] = _written(row, name)
                        rows.append(row)
                rows.sort(key=_versioned_sort_key(self.schema))
                rows = _drop_superseded(rows, self.schema,
                                        retention_timestamp)
                _invariant_check("versioned_rows",
                                 (self.schema.key_column_names, rows))
                if rows:
                    chunk = ColumnarChunk.from_rows(self._versioned_schema,
                                                    rows)
            old_ids = list(self.chunk_ids)
            if chunk is not None:
                new_id = self.chunk_store.write_chunk(chunk)
                self.chunk_ids = [new_id]
                if self.in_memory:
                    self.chunk_cache.pin(new_id)
            else:
                new_id = None
                self.chunk_ids = []
            for cid in old_ids:
                self.chunk_store.remove_chunk(cid)
                self.chunk_cache.invalidate(cid)
                self._host_planes.pop(cid, None)
            self.flush_generation += 1
            _invariant_check("tablet", self)
            return new_id

    # -- read path -------------------------------------------------------------

    def _decode(self, chunk_id: str) -> ColumnarChunk:
        return self.chunk_cache.get(chunk_id)

    def _chunk_host_planes_locked(self, chunk_id: str) -> dict:
        """numpy views of a chunk's planes (device->host once per chunk).
        LRU: hits promote (a hot chunk probed by every lookup batch must
        not be evicted because it was decoded first), capacity from
        TabletConfig.host_plane_cache_capacity."""
        planes = self._host_planes.get(chunk_id)
        if planes is None:
            chunk = self._decode(chunk_id)
            n = chunk.row_count
            planes = {name: (np.asarray(col.data[:n]), np.asarray(col.valid[:n]))
                      for name, col in chunk.columns.items()}
            self._host_planes[chunk_id] = planes
            capacity = tablet_config().host_plane_cache_capacity
            while len(self._host_planes) > capacity:
                self._host_planes.popitem(last=False)
        else:
            self._host_planes.move_to_end(chunk_id)
        return planes

    def _decoded_chunks(self) -> list[ColumnarChunk]:
        return [self._decode(cid) for cid in self.chunk_ids]

    def versioned_rows_snapshot(self) -> list[dict]:
        """All versions from every store (host rows; newest-first per key)."""
        with self._lock:
            rows: list[dict] = []
            for chunk in self._decoded_chunks():
                rows.extend(chunk.to_rows())
            for store in self.passive_stores + [self.active_store]:
                rows.extend(store.versioned_rows())
            rows.sort(key=_versioned_sort_key(self.schema))
            return rows

    def _generation(self) -> tuple:
        """Identity of the tablet's visible state: any write, rotation,
        flush or compaction changes it.  Keys the row cache AND the
        snapshot cache."""
        return (self.active_store.store_row_count,
                len(self.passive_stores), self.flush_generation)

    def _chunk_max_timestamp(self, chunk_id: str) -> int:
        """Newest version timestamp in a sealed chunk — from the chunk
        meta stats when present (one header parse), else from the host
        planes (pre-stats chunks)."""
        if hasattr(self.chunk_store, "read_stats"):
            try:
                stats = self.chunk_store.read_stats(chunk_id)
                entry = (stats or {}).get("$timestamp") or {}
                if entry.get("max") is not None:
                    return int(entry["max"])
            except (YtError, OSError):
                pass
        data, valid = self._chunk_host_planes_locked(chunk_id)["$timestamp"]
        return int(data[valid].max()) if valid.any() else 0

    def _latest_ts_floor(self) -> int:
        """Smallest timestamp that reads "latest": any read at/above the
        newest committed version sees the same visible state, so it can
        share the cached snapshot (the timestamp-class in the cache
        key)."""
        if self._chunk_max_ts_gen != self.flush_generation:
            best = 0
            for cid in self.chunk_ids:
                best = max(best, self._chunk_max_timestamp(cid))
            self._chunk_max_ts = best
            self._chunk_max_ts_gen = self.flush_generation
        floor = self._chunk_max_ts
        for store in [self.active_store] + self.passive_stores:
            floor = max(floor, store.max_timestamp)
        return floor

    def _normalize_versioned(self, chunk: ColumnarChunk) -> ColumnarChunk:
        """Adapt a persisted versioned chunk to THE versioned schema so
        chunk planes concatenate: chunks from before the per-column $w:
        layout gain explicit written=True planes (whole-row semantics,
        matching `_written`), missing value columns read as stated
        nulls."""
        vschema = self._versioned_schema
        if chunk.schema == vschema:
            return chunk
        import jax.numpy as jnp

        from ytsaurus_tpu.chunks.columnar import Column, _plane_dtype
        cap = chunk.capacity
        n = chunk.row_count
        row_valid = jnp.arange(cap) < n
        columns: dict[str, Column] = {}
        for c in vschema:
            col = chunk.columns.get(c.name)
            if col is not None:
                columns[c.name] = col
            elif c.name.startswith("$w:"):
                columns[c.name] = Column(
                    type=c.type, data=jnp.ones(cap, dtype=bool),
                    valid=row_valid)
            else:
                columns[c.name] = Column(
                    type=c.type,
                    data=jnp.zeros(cap, dtype=_plane_dtype(c.type)),
                    valid=jnp.zeros(cap, dtype=bool))
        return ColumnarChunk(schema=vschema, row_count=n, columns=columns)

    def read_snapshot(self, timestamp: int = MAX_TIMESTAMP) -> ColumnarChunk:
        """Materialize the tablet contents as of `timestamp` into a plain
        columnar chunk (the select_rows input).

        Columnar MVCC pipeline (tablet/mvcc.py): versioned chunk planes
        and store-ingested planes concatenate on device, one packed
        (key, -ts) sort, visibility as segmented scans — no to_rows().
        Latest-class reads (timestamp at/above the newest committed
        version) memoize the materialized chunk per generation, so
        repeated selects skip the merge entirely until the next
        write/flush/compact."""
        with child_span("tablet.read_snapshot") as span, self._lock:
            generation = self._generation()
            latest = timestamp >= self._latest_ts_floor()
            if latest:
                cached = self._snapshot_cache
                if cached is not None and cached[0] == generation:
                    _SNAP_HITS.increment()
                    span.add_tag("snapshot_cache", "hit")
                    span.add_tag("rows", cached[1].row_count)
                    return cached[1]
                _SNAP_MISSES.increment()
            span.add_tag("snapshot_cache",
                         "miss" if latest else "bypass")
            chunk = self._read_snapshot_uncached(timestamp)
            span.add_tag("rows", chunk.row_count)
            if latest and tablet_config().snapshot_cache_enabled:
                if self._snapshot_cache is not None:
                    _SNAP_EVICTIONS.increment()
                    _snap_bytes_add(-_chunk_nbytes(self._snapshot_cache[1]))
                self._snapshot_cache = (generation, chunk,
                                        time.monotonic())
                _snap_bytes_add(_chunk_nbytes(chunk))
            return chunk

    def read_snapshot_bounded(self, timestamp: int = MAX_TIMESTAMP,
                              max_staleness: float = 0.0) \
            -> "tuple[ColumnarChunk, float]":
        """Bounded-staleness read (serving brown-out rung 1, ISSUE 17):
        serve the cached snapshot EVEN IF writes advanced the generation,
        as long as it was built within `max_staleness` seconds — the
        explicit degradation that keeps an overloaded replica answering
        without paying the MVCC merge.  Returns (chunk, staleness
        seconds actually served); falls back to a full `read_snapshot`
        (staleness 0) when the cache is cold, too old, or the caller
        asked for a historical timestamp the cache cannot answer."""
        if max_staleness and max_staleness > 0:
            with self._lock:
                cached = self._snapshot_cache
                if cached is not None and \
                        timestamp >= self._latest_ts_floor():
                    age = time.monotonic() - cached[2]
                    if age <= max_staleness:
                        _SNAP_HITS.increment()
                        return cached[1], age
        return self.read_snapshot(timestamp), 0.0

    def _read_snapshot_uncached(self, timestamp: int) -> ColumnarChunk:
        total = sum(s.store_row_count for s in
                    [self.active_store] + self.passive_stores)
        for cid in self.chunk_ids:
            total += self._decode(cid).row_count
        if not self._vectorize(total):
            with child_span("tablet.mvcc_merge", vectorized=False,
                            versions=total):
                return self.read_snapshot_reference(timestamp)
        with child_span("tablet.mvcc_merge", vectorized=True,
                        versions=total):
            sources = [self._normalize_versioned(self._decode(cid))
                       for cid in self.chunk_ids]
            sources += [s.to_versioned_chunk(self._versioned_schema)
                        for s in self.passive_stores + [self.active_store]
                        if s.store_row_count]
            if not sources:
                return dataclasses.replace(
                    ColumnarChunk.from_rows(self.schema.to_unsorted(), []),
                    sorted_by=tuple(self.schema.key_column_names))
            return mvcc.visible_chunk(concat_chunks(sources), self.schema,
                                      timestamp)

    def read_snapshot_reference(self,
                                timestamp: int = MAX_TIMESTAMP
                                ) -> ColumnarChunk:
        """The retained Python MVCC merge (pre-columnar read path):
        the property-test oracle and the small-store fast path."""
        with self._lock:
            rows = self.versioned_rows_snapshot()
            visible = _mvcc_select(rows, self.schema, timestamp)
            chunk = ColumnarChunk.from_rows(self.schema.to_unsorted(), visible)
            # Same key-order seal as the vectorized merge: both snapshot
            # paths must produce the same sorted_by (and therefore the
            # same compiled program) for a given tablet.
            return dataclasses.replace(
                chunk, sorted_by=tuple(self.schema.key_column_names))

    def lookup_rows(self, keys: Sequence[tuple],
                    timestamp: int = MAX_TIMESTAMP,
                    column_names: Optional[Sequence[str]] = None,
                    normalized: bool = False,
                    pool: Optional[str] = None) -> list[Optional[dict]]:
        """Point reads at a timestamp (ref tablet_node/lookup.cpp).

        normalized=True: the caller already holds canonical keys
        (normalize_key output) — the serving-plane batcher normalizes
        once per request and must not pay it again per batch.

        `pool` is the admitted cohort's identity (serving plane): reads
        tick per-pool tablet sensors (`tablet_lookup_reads{pool=}`) so
        accounting attributes tablet consumption to tenants.

        Batched chunk probe: keys missing the row cache are matched
        against each versioned chunk in ONE vectorized pass (np.isin
        over the key planes) instead of one full-plane mask per key —
        the per-chunk cost drops from O(rows x keys) to O(rows +
        matches), which is what makes the serving plane's micro-batches
        pay off (ref tablet_node/lookup.cpp batched lookup sessions)."""
        counters = _lookup_counters.counters(pool)
        counters["reads"].increment()
        counters["keys"].increment(len(keys))
        with child_span("tablet.lookup", keys=len(keys),
                        chunks=len(self.chunk_ids)), self._lock:
            key_names = self.schema.key_column_names
            out: list[Optional[dict]] = []
            if not normalized:
                keys = [self.normalize_key(tuple(k)) for k in keys]
            # The cache only serves latest-timestamp reads and resets when
            # any store or chunk set changes.
            generation = self._generation()
            cacheable = timestamp == MAX_TIMESTAMP
            if self._row_cache_gen != generation:
                self._row_cache.clear()
                self._row_cache_gen = generation
            misses = dict.fromkeys(
                k for k in keys
                if not (cacheable and k in self._row_cache))
            chunk_rows: "Optional[dict[tuple, list[dict]]]" = None
            if len(misses) >= 4 and self.chunk_ids:
                chunk_rows = {}
                miss_list = list(misses)
                for cid in self.chunk_ids:
                    for key, rows in _chunk_batch_key_rows(
                            self._decode(cid), self.schema, miss_list,
                            self._chunk_host_planes_locked(cid),
                            bucket_min=self.probe_bucket_min).items():
                        chunk_rows.setdefault(key, []).extend(rows)
            for key in keys:
                if cacheable and key in self._row_cache:
                    self.row_cache_hits += 1
                    self._row_cache.move_to_end(key)
                    cached = self._row_cache[key]
                    row = dict(cached) if cached is not None else None
                else:
                    if cacheable:       # bypassing reads skew no metric
                        self.row_cache_misses += 1
                    versions: list[tuple[int, Optional[dict]]] = []
                    for store in [self.active_store] + self.passive_stores:
                        versions.extend(store.lookup_versions(key))
                    if chunk_rows is not None and key in misses:
                        # The batch probe is authoritative ONLY for the
                        # keys it covered: a key that was a cache HIT at
                        # call start can be evicted by THIS loop's own
                        # insertions and reach here unprobed — treating
                        # its absence from chunk_rows as "no versions"
                        # would return (and cache) a wrong None.
                        versions.extend(_versions_from_chunk_rows(
                            chunk_rows.get(key, ()), self.schema))
                    else:
                        for cid in self.chunk_ids:
                            versions.extend(_chunk_lookup_versions(
                                self._decode(cid), self.schema, key,
                                self._chunk_host_planes_locked(cid)))
                    merged = _merge_versions(versions, timestamp)
                    if merged is None:
                        row = None
                    else:
                        row = dict(zip(key_names, key))
                        # Columns no surviving version wrote read as null.
                        for c in self.schema:
                            if c.sort_order is None:
                                row[c.name] = None
                        row.update(merged)
                    if cacheable:
                        self._row_cache[key] =                             dict(row) if row is not None else None
                        while len(self._row_cache) > self.row_cache_capacity:
                            self._row_cache.popitem(last=False)
                if row is not None and column_names is not None:
                    row = {name: row.get(name) for name in column_names}
                out.append(row)
            return out


def _normalize_value(value, ty: EValueType):
    if value is None:
        return None
    if ty is EValueType.string:
        return value.encode("utf-8") if isinstance(value, str) else bytes(value)
    if ty is EValueType.boolean:
        return bool(value)
    if ty is EValueType.double:
        return float(value)
    if ty in (EValueType.int64, EValueType.uint64):
        return int(value)
    return value


# -- versioned row helpers -----------------------------------------------------

def _written(row: dict, name: str) -> bool:
    """Did this version state column `name`?  Chunks persisted before the
    per-column layout carry no $w: planes — or carry them as nulls after a
    re-encode — and mean whole-row writes, so ABSENT and None both read as
    written (only an explicit False means unwritten)."""
    flag = row.get(f"$w:{name}")
    return True if flag is None else bool(flag)



def _versioned_sort_key(schema: TableSchema):
    key_names = schema.key_column_names

    def sort_key(row: dict):
        key_part = tuple((row[name] is not None,
                          row[name] if row[name] is not None else 0)
                         for name in key_names)
        return key_part + (-row["$timestamp"],)
    return sort_key


def _mvcc_select(versioned_rows: list[dict], schema: TableSchema,
                 timestamp: int) -> list[dict]:
    """Per-column MVCC merge at `timestamp` (versioned_row_merger.h
    semantics): the newest delete <= ts bounds the merge; each column takes
    its newest write after that bound that STATES the column.  Input must
    be sorted by (key, -ts)."""
    key_names = schema.key_column_names
    value_names = [c.name for c in schema if c.sort_order is None]
    out = []
    prev_key: object = object()
    visible: Optional[dict] = None
    filled: set = set()
    deleted = False

    def emit():
        if visible is not None:
            for name in value_names:
                visible.setdefault(name, None)
            out.append(visible)

    for row in versioned_rows:
        key = tuple(row[name] for name in key_names)
        if key != prev_key:
            emit()
            prev_key = key
            visible = None
            filled = set()
            deleted = False
        if deleted or row["$timestamp"] > timestamp:
            continue
        if row["$tombstone"]:
            deleted = True          # older versions are invisible
            continue
        if visible is None:
            visible = {name: row[name] for name in key_names}
        for name in value_names:
            if name not in filled and _written(row, name):
                visible[name] = row.get(name)
                filled.add(name)
    emit()
    return out


def _drop_superseded(versioned_rows: list[dict], schema: TableSchema,
                     retention_timestamp: int) -> list[dict]:
    """Major-compaction retention: keep every version newer than
    `retention_timestamp`; versions at/below it collapse into ONE
    consolidated base version holding the per-column merged visible state
    at the retention timestamp (the merger's "merge partial writes"
    compaction mode) — or nothing if that state is a delete.  Input sorted
    by (key, -ts); output preserves that order."""
    key_names = schema.key_column_names
    value_names = [c.name for c in schema if c.sort_order is None]
    out = []
    i = 0
    n = len(versioned_rows)
    while i < n:
        key = tuple(versioned_rows[i][name] for name in key_names)
        group = []
        while i < n and tuple(versioned_rows[i][name]
                              for name in key_names) == key:
            group.append(versioned_rows[i])
            i += 1
        base_rows = []
        for row in group:
            if row["$timestamp"] > retention_timestamp:
                out.append(row)
            else:
                base_rows.append(row)
        if not base_rows:
            continue
        # Per-column merge of the <= retention versions.
        merged: Optional[dict] = None
        filled: set = set()
        base_ts = None
        for row in base_rows:           # newest first
            if row["$tombstone"]:
                break                   # older versions invisible
            if merged is None:
                merged = {name: row[name] for name in key_names}
                base_ts = row["$timestamp"]
            for name in value_names:
                if name not in filled and _written(row, name):
                    merged[name] = row.get(name)
                    filled.add(name)
        if merged is not None:
            merged["$timestamp"] = base_ts
            merged["$tombstone"] = False
            for name in value_names:
                merged.setdefault(name, None)
                merged[f"$w:{name}"] = True     # consolidated: states all
            out.append(merged)
    return out


def _merge_versions(versions: list[tuple[int, Optional[dict]]],
                    timestamp: int) -> Optional[dict]:
    """Per-column merge from (ts, written-columns-dict-or-None) pairs:
    the newest delete <= ts bounds the merge; each column takes its newest
    stated value after the bound (TVersionedRow lookup merge)."""
    live = sorted((v for v in versions if v[0] <= timestamp),
                  key=lambda v: -v[0])
    merged: Optional[dict] = None
    filled: set = set()
    for ts, state in live:
        if state is None:
            break                       # delete: older versions invisible
        if merged is None:
            merged = {}
        for name, value in state.items():
            if name not in filled:
                merged[name] = value
                filled.add(name)
    return merged


def _versions_from_chunk_rows(rows, schema: TableSchema
                              ) -> list[tuple[int, Optional[dict]]]:
    """Versioned chunk rows of one key → (timestamp, state) pairs."""
    out = []
    value_names = [c.name for c in schema if c.sort_order is None]
    for row in rows:
        if row["$tombstone"]:
            out.append((row["$timestamp"], None))
        else:
            # Only columns the version wrote ($w: flags; chunks from before
            # the per-column layout carry none → whole-row semantics).
            out.append((row["$timestamp"],
                        {name: row.get(name) for name in value_names
                         if _written(row, name)}))
    return out


def _chunk_lookup_versions(chunk: ColumnarChunk, schema: TableSchema,
                           key: tuple, host_planes: dict
                           ) -> list[tuple[int, Optional[dict]]]:
    return _versions_from_chunk_rows(
        _chunk_key_rows(chunk, schema, key, host_planes), schema)


def _chunk_last_timestamp(chunk: ColumnarChunk, schema: TableSchema,
                          key: tuple, host_planes: dict) -> Optional[int]:
    rows = _chunk_key_rows(chunk, schema, key, host_planes)
    if not rows:
        return None
    return max(r["$timestamp"] for r in rows)


def _chunk_key_rows(chunk: ColumnarChunk, schema: TableSchema,
                    key: tuple, host_planes: dict) -> list[dict]:
    """Rows matching `key` in a versioned chunk: vectorized mask over the
    cached host planes, then decode ONLY the matched rows."""
    n = chunk.row_count
    if n == 0:
        return []
    mask = np.ones(n, dtype=bool)
    for name, value in zip(schema.key_column_names, key):
        col = chunk.columns[name]
        data, valid = host_planes[name]
        if value is None:
            mask &= ~valid
        elif col.type is EValueType.string:
            code = None
            if col.dictionary is not None and len(col.dictionary):
                target = value if isinstance(value, bytes) else \
                    str(value).encode()
                idx = np.searchsorted(col.dictionary, target)
                if idx < len(col.dictionary) and col.dictionary[idx] == target:
                    code = idx
            if code is None:
                return []
            mask &= valid & (data == code)
        else:
            mask &= valid & (data == value)
        if not mask.any():
            return []
    idx = np.nonzero(mask)[0]
    return _decode_chunk_rows(chunk, host_planes, idx)


def _decode_chunk_rows(chunk: ColumnarChunk, host_planes: dict,
                       idx) -> list[dict]:
    """Decode only the rows at `idx` (usually tiny vs the chunk)."""
    n = chunk.row_count
    rows = []
    cols = {name: chunk.columns[name] for name in chunk.schema.column_names}
    host = host_planes
    for i in idx:
        row = {}
        for name, col in cols.items():
            data, valid = host[name]
            if not valid[i]:
                row[name] = None
            elif col.type is EValueType.string:
                row[name] = bytes(col.dictionary[int(data[i])])
            elif col.type is EValueType.any:
                row[name] = (col.host_values or [None] * n)[i]
            elif col.type is EValueType.boolean:
                row[name] = bool(data[i])
            elif col.type is EValueType.double:
                row[name] = float(data[i])
            else:
                row[name] = int(data[i])
        rows.append(row)
    return rows


def _pad_needles(values: list, bucket_min: int) -> list:
    """Pad a probe (needle) array to the next power-of-two bucket by
    repeating the last element (duplicate needles don't change an isin
    mask).  Bucketing bounds the SPECTRUM of probe shapes to O(log
    max_batch) variants — the discipline that keeps a shape-keyed
    compiled-gather cache bounded when this probe lowers to a device
    gather (and what the serving plane's micro-batches rely on).
    Buckets come from chunks.columnar.next_pow2 — the ONE pow2
    implementation chunk capacities and vocab paddings also use."""
    from ytsaurus_tpu.chunks.columnar import next_pow2
    n = len(values)
    cap = next_pow2(n, floor=bucket_min)
    if cap == n:
        return values
    return values + [values[-1]] * (cap - n)


def _chunk_batch_key_rows(chunk: ColumnarChunk, schema: TableSchema,
                          keys: "list[tuple]", host_planes: dict,
                          bucket_min: int = 8
                          ) -> "dict[tuple, list[dict]]":
    """Rows matching ANY of `keys`, grouped by exact key — ONE vectorized
    pass over the key planes for the whole batch (np.isin over a
    pow2-bucketed needle array), instead of one full-plane mask per key
    (`_chunk_key_rows`).  For multi-column keys the per-column
    membership intersection is a SUPERSET (cross products); the exact
    grouping below discards false positives after decoding only the
    candidate rows."""
    n = chunk.row_count
    if n == 0 or not keys:
        return {}
    key_names = schema.key_column_names
    mask = np.ones(n, dtype=bool)
    for ci, name in enumerate(key_names):
        col = chunk.columns[name]
        data, valid = host_planes[name]
        values = {k[ci] for k in keys}
        has_null = None in values
        values.discard(None)
        if col.type is EValueType.string:
            codes = []
            if col.dictionary is not None and len(col.dictionary) \
                    and values:
                targets = sorted(
                    v if isinstance(v, bytes) else str(v).encode()
                    for v in values)
                pos = np.searchsorted(col.dictionary, targets)
                for t, i in zip(targets, pos):
                    if i < len(col.dictionary) and \
                            col.dictionary[i] == t:
                        codes.append(i)
            col_mask = (valid & np.isin(data, np.asarray(
                _pad_needles(codes, bucket_min), dtype=data.dtype))) \
                if codes else np.zeros(n, dtype=bool)
        elif values:
            col_mask = valid & np.isin(
                data, np.asarray(_pad_needles(sorted(values),
                                              bucket_min),
                                 dtype=data.dtype))
        else:
            col_mask = np.zeros(n, dtype=bool)
        if has_null:
            col_mask = col_mask | ~valid
        mask &= col_mask
        if not mask.any():
            return {}
    idx = np.nonzero(mask)[0]
    out: "dict[tuple, list[dict]]" = {}
    for row in _decode_chunk_rows(chunk, host_planes, idx):
        key = tuple(row[name] for name in key_names)
        if key in out:
            out[key].append(row)
        else:
            out[key] = [row]
    return out
