"""Go SDK: build with the Go toolchain and drive a live HTTP proxy.

Ref model: yt/go/yt (the reference treats Go as a first-class SDK).
The test compiles sdk/go's demo binary and runs it against a
LocalCluster proxy end to end.  Skipped when no Go toolchain is
installed (this image ships none; the SDK is stdlib-only so any
go >= 1.20 builds it).
"""

import os
import shutil
import subprocess

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from ytsaurus_tpu.environment import LocalCluster  # noqa: E402

SDK_DIR = os.path.join(os.path.dirname(__file__), "..", "sdk", "go")


@pytest.fixture(scope="module")
def demo_binary(tmp_path_factory):
    if shutil.which("go") is None:
        pytest.skip("go toolchain not available")
    build = tmp_path_factory.mktemp("go_sdk")
    out = str(build / "demo")
    env = dict(os.environ, GOFLAGS="-mod=mod", GOCACHE=str(build / "cache"))
    subprocess.run(
        ["go", "build", "-o", out, "./cmd/demo"],
        cwd=SDK_DIR, env=env, check=True, capture_output=True)
    return out


def test_go_sdk_end_to_end(demo_binary, tmp_path):
    with LocalCluster(str(tmp_path), n_nodes=1, replication_factor=1,
                      http_proxy=True) as cluster:
        proc = subprocess.run([demo_binary, cluster.http_proxy_address],
                              capture_output=True, timeout=120)
        assert proc.returncode == 0, proc.stderr.decode()
        assert b"GO-SDK-DEMO PASS" in proc.stdout
        # Go-written data is visible through the Python client too.
        from ytsaurus_tpu.remote_client import connect_remote
        cl = connect_remote(cluster.primary_address)
        assert cl.lookup_rows("//go/dyn", [(2,)]) == [
            {"k": 2, "v": b"two"}]
