"""Sensor-catalog pass: PR 6's `tools/check_sensor_catalog.py` folded
into the analyzer framework (fifth pass), so `yt analyze` is the ONE
static-analysis entry point.  The standalone script keeps working — this
module adapts its `check()` output into the shared finding model."""

from __future__ import annotations

import os
import re
from typing import Optional

from tools.analyze.core import Finding, SourceFile

PASS_NAME = "sensors"

_LINE_RE = re.compile(r"^(?P<rel>[^:]+):(?P<line>\d+): (?P<msg>.*)$")


def _load_checker():
    import importlib.util
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "check_sensor_catalog.py")
    spec = importlib.util.spec_from_file_location("check_sensor_catalog",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run(files: "list[SourceFile]",
        root: Optional[str] = None) -> "list[Finding]":
    if root is None:
        return []     # fixture runs carry no catalog; repo runs pass root
    checker = _load_checker()
    findings: list[Finding] = []
    for error in checker.check(root):
        match = _LINE_RE.match(error)
        if match:
            findings.append(Finding(
                PASS_NAME, "sensor-catalog",
                "ytsaurus_tpu/" + match.group("rel").replace(os.sep, "/"),
                int(match.group("line")), match.group("msg")))
        else:
            findings.append(Finding(
                PASS_NAME, "sensor-catalog", "tools/sensor_catalog.json",
                1, error))
    return findings
