"""Continuous queries: incremental materialized views over ordered tablets
(ISSUE 13 tentpole — the Flow/YQL-streaming analog, PARITY §2.11).

A materialized view is a standing QL query over an ORDERED (queue) table
whose results live in a SORTED dynamic table readable by normal selects.
A refresher tails the source through a committed offset cursor (the same
consumer-table machinery `queue_agent` uses), runs the view's compiled
plan incrementally per micro-batch, and upserts results into the target —
with the offset commit and the target write in ONE 2PC transaction, so a
crash anywhere in the loop replays the batch instead of double-applying
it (exactly-once).

Incremental evaluation reuses the distributed GROUP BY machinery
verbatim: `coordinator.split_plan` already decomposes every aggregate
into a MERGEABLE partial state (avg → (sum, count), argmin/argmax →
(value, by) pairs, count merges by sum) so per-shard partials combine at
the front.  Here the "shards" are micro-batches separated in TIME rather
than space:

  batch_plan   the bottom query — group keys + partial aggregate states
               over one micro-batch chunk (fixed pow2 capacity, so the
               steady-state loop replays ONE compiled program forever);
  merge_plan   the front combine — re-groups (stored states ∪ batch
               states) with each aggregate's merge function;
  finalize     states → reader-facing columns (avg divides its sum by
               its count; argmin keeps its `__b` state column alongside
               the value so the NEXT merge still has it).

Non-aggregating selects skip the merge: filtered/projected rows upsert
directly, keyed by the source `$row_index` (idempotent by construction).

The steady state is the compile-once sweet spot (ISSUE 10): one
parameterized plan per view, pow2-bucketed batch capacity, all programs
riding the AOT disk tier — a view daemon restart resumes from committed
offsets with 0 fresh compiles.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Optional

from ytsaurus_tpu.chunks.columnar import (
    ColumnarChunk,
    concat_chunks,
    pad_capacity,
)
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import _MERGE_FN, split_plan
from ytsaurus_tpu.schema import EValueType, TableSchema
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils.tracing import child_span
from ytsaurus_tpu.utils import sanitizers

VIEWS_ROOT = "//sys/views"

# Failpoint sites (ISSUE 13 satellite): batch_execute covers the read +
# incremental-evaluation leg; commit sits BETWEEN the staged target write
# and the offset commit — the exact spot where a two-transaction protocol
# would double-apply.  The chaos soak proves the single-2PC protocol
# keeps the view bit-identical to a full recompute across crashes here.
_FP_BATCH = failpoints.register_site("views.batch_execute")
_FP_COMMIT = failpoints.register_site("views.commit")


# -- incremental plan preparation ----------------------------------------------


@dataclass(frozen=True)
class _Finalizer:
    """How one original aggregate's merged state becomes reader columns."""
    name: str          # reader-facing column (the aggregate's alias)
    kind: str          # scalar | avg | argfn
    state_names: tuple[str, ...]   # state columns persisted in the target


@dataclass(frozen=True)
class IncrementalPlan:
    """Everything the refresher needs, prepared ONCE per view."""
    plan: ir.Query                    # full plan (the recompute oracle)
    batch_plan: ir.Query              # per-micro-batch (bottom) program
    merge_plan: Optional[ir.Query]    # state combine (None: plain select)
    state_schema: TableSchema         # batch_plan output namespace
    target_schema: TableSchema        # sorted target table schema
    key_names: tuple[str, ...]        # target key columns
    finalizers: tuple[_Finalizer, ...] = ()

    @property
    def aggregating(self) -> bool:
        return self.merge_plan is not None

    # -- state <-> stored row conversion ---------------------------------------

    def stored_to_state(self, row: dict) -> dict:
        """A target row (as lookup returns it) → a state-schema row."""
        out = {k: row[k] for k in self.key_names}
        for fin in self.finalizers:
            if fin.kind == "argfn":
                out[fin.state_names[0]] = row[fin.name]
                out[fin.state_names[1]] = row[fin.state_names[1]]
            else:
                for state in fin.state_names:
                    out[state] = row[state]
        return out

    def finalize(self, state_row: dict) -> dict:
        """A merged state row → the target upsert row (finalized columns
        for readers + the state columns the NEXT merge needs)."""
        out = {k: state_row[k] for k in self.key_names}
        for fin in self.finalizers:
            if fin.kind == "avg":
                s_name, c_name = fin.state_names
                s, c = state_row[s_name], state_row[c_name]
                out[fin.name] = (s / c) if c else None
                out[s_name] = s
                out[c_name] = c
            elif fin.kind == "argfn":
                v_name, b_name = fin.state_names
                out[fin.name] = state_row[v_name]
                out[b_name] = state_row[b_name]
            else:
                out[fin.name] = state_row[fin.name]
        return out


def _reject(condition: bool, what: str) -> None:
    if condition:
        raise YtError(
            f"Materialized views do not support {what}: a continuous "
            f"view must be incrementally mergeable per micro-batch",
            code=EErrorCode.QueryUnsupported)


ROW_INDEX = "$row_index"


def prepare_incremental(plan: ir.Query) -> IncrementalPlan:
    """Validate a view plan and derive its incremental decomposition.

    Supported: WHERE + projection (plain views, keyed by source
    $row_index) and GROUP BY with mergeable aggregates (sum/min/max/
    count/avg/first/argmin/argmax).  Rejected: joins, window functions,
    ORDER BY/LIMIT/OFFSET/HAVING/WITH TOTALS, and cardinality() —
    none of them merge from per-batch partials.
    """
    _reject(bool(plan.joins), "JOIN")
    _reject(plan.window is not None, "window functions")
    _reject(plan.order is not None, "ORDER BY")
    _reject(plan.limit is not None or plan.offset != 0, "LIMIT/OFFSET")
    _reject(plan.having is not None, "HAVING")
    if plan.group is not None:
        _reject(plan.group.totals, "WITH TOTALS")
        _reject(any(a.function == "cardinality"
                    for a in plan.group.aggregate_items),
                "cardinality() (distinct counts need the full rowset)")
        return _prepare_aggregating(plan)
    return _prepare_plain(plan)


def _prepare_plain(plan: ir.Query) -> IncrementalPlan:
    """Non-aggregating view: rows upsert keyed by the source $row_index
    (carried through the projection if one is declared)."""
    batch_plan = plan
    if plan.project is not None and not any(
            item.name == ROW_INDEX for item in plan.project.items):
        row_ref = ir.NamedExpr(
            name=ROW_INDEX,
            expr=ir.TReference(type=EValueType.int64, name=ROW_INDEX))
        batch_plan = replace(plan, project=ir.ProjectClause(
            items=(row_ref,) + tuple(plan.project.items)))
    out_schema = batch_plan.output_schema()
    cols = [(ROW_INDEX, "int64", "ascending")]
    cols += [(c.name, c.type.value) for c in out_schema
             if c.name != ROW_INDEX]
    target_schema = TableSchema.make(cols, unique_keys=True)
    return IncrementalPlan(
        plan=plan, batch_plan=batch_plan, merge_plan=None,
        state_schema=out_schema, target_schema=target_schema,
        key_names=(ROW_INDEX,))


def _normalize_agg_projection(plan: ir.Query) -> ir.Query:
    """Fold the projection into the group clause.

    The builder names aggregate slots `_aggN` internally and maps
    `... AS alias` through PROJECT references; an incremental view
    persists the group output as the TARGET TABLE, so the aliases must
    become the group/aggregate slot names themselves.  Only plain
    reference projections are mergeable — a computed projection over
    aggregates (`sum(a)/sum(b) AS ratio`) would need re-finalizing from
    states on every read, which plain selects on the target cannot do.
    Unprojected aggregates are dropped (dead state); unprojected group
    keys are kept (they ARE the target key)."""
    if plan.project is None:
        return plan
    key_names = {i.name for i in plan.group.group_items}
    agg_names = {a.name for a in plan.group.aggregate_items}
    rename: dict[str, str] = {}
    for item in plan.project.items:
        _reject(not isinstance(item.expr, ir.TReference)
                or item.expr.name not in key_names | agg_names,
                "computed projections over aggregates (select group "
                "keys and aggregates directly, e.g. `g, sum(v) AS s`)")
        rename[item.expr.name] = item.name
    group = ir.GroupClause(
        group_items=tuple(
            ir.NamedExpr(name=rename.get(i.name, i.name), expr=i.expr)
            for i in plan.group.group_items),
        aggregate_items=tuple(
            replace(a, name=rename[a.name])
            for a in plan.group.aggregate_items if a.name in rename))
    return replace(plan, group=group, project=None)


def _prepare_aggregating(plan: ir.Query) -> IncrementalPlan:
    """GROUP BY view: split_plan's bottom runs per batch; the merge plan
    re-groups stored ∪ fresh partial states with each aggregate's merge
    function (states stay states so the NEXT batch can merge again)."""
    plan = _normalize_agg_projection(plan)
    bottom, _front = split_plan(plan)
    state_schema = bottom.output_schema()
    key_names = tuple(item.name for item in plan.group.group_items)

    group_refs = tuple(
        ir.NamedExpr(name=item.name,
                     expr=ir.TReference(type=item.expr.type,
                                        name=item.name))
        for item in plan.group.group_items)

    merge_aggs: list[ir.AggregateItem] = []
    finalizers: list[_Finalizer] = []
    for agg in plan.group.aggregate_items:
        if agg.function in ("argmin", "argmax"):
            v_name, b_name = f"{agg.name}__v", f"{agg.name}__b"
            by_type = agg.by_argument.type
            merge_aggs.append(ir.AggregateItem(
                name=v_name, function=agg.function,
                argument=ir.TReference(type=agg.type, name=v_name),
                type=agg.type, state_type=agg.state_type,
                by_argument=ir.TReference(type=by_type, name=b_name)))
            merge_aggs.append(ir.AggregateItem(
                name=b_name,
                function="min" if agg.function == "argmin" else "max",
                argument=ir.TReference(type=by_type, name=b_name),
                type=by_type, state_type=by_type))
            finalizers.append(_Finalizer(agg.name, "argfn",
                                         (v_name, b_name)))
        elif agg.function == "avg":
            s_name, c_name = f"{agg.name}__s", f"{agg.name}__c"
            merge_aggs.append(ir.AggregateItem(
                name=s_name, function="sum",
                argument=ir.TReference(type=EValueType.double,
                                       name=s_name),
                type=EValueType.double, state_type=EValueType.double))
            merge_aggs.append(ir.AggregateItem(
                name=c_name, function="sum",
                argument=ir.TReference(type=EValueType.int64,
                                       name=c_name),
                type=EValueType.int64, state_type=EValueType.int64))
            finalizers.append(_Finalizer(agg.name, "avg",
                                         (s_name, c_name)))
        else:
            merge_aggs.append(ir.AggregateItem(
                name=agg.name, function=_MERGE_FN[agg.function],
                argument=ir.TReference(type=agg.state_type,
                                       name=agg.name),
                type=agg.type, state_type=agg.state_type))
            finalizers.append(_Finalizer(agg.name, "scalar",
                                         (agg.name,)))

    merge_plan = ir.Query(
        schema=state_schema,
        group=ir.GroupClause(group_items=group_refs,
                             aggregate_items=tuple(merge_aggs)))

    cols: list[tuple] = [(item.name, item.expr.type.value, "ascending")
                         for item in plan.group.group_items]
    for agg, fin in zip(plan.group.aggregate_items, finalizers):
        cols.append((agg.name, agg.type.value))
        if fin.kind == "avg":
            cols.append((fin.state_names[0], "double"))
            cols.append((fin.state_names[1], "int64"))
        elif fin.kind == "argfn":
            cols.append((fin.state_names[1],
                         agg.by_argument.type.value))
    target_schema = TableSchema.make(cols, unique_keys=True)
    return IncrementalPlan(
        plan=plan, batch_plan=bottom, merge_plan=merge_plan,
        state_schema=state_schema, target_schema=target_schema,
        key_names=key_names, finalizers=tuple(finalizers))


# -- view registry (Cypress-backed) --------------------------------------------


@dataclass
class ViewSpec:
    name: str
    query: str
    source: str
    target: str
    consumer: str
    pool: str = "views"
    batch_rows: int = 1024
    state: str = "running"        # running | paused

    def to_dict(self) -> dict:
        return {"name": self.name, "query": self.query,
                "source": self.source, "target": self.target,
                "consumer": self.consumer, "pool": self.pool,
                "batch_rows": self.batch_rows, "state": self.state}

    @classmethod
    def from_dict(cls, d: dict) -> "ViewSpec":
        return cls(name=d["name"], query=d["query"], source=d["source"],
                   target=d["target"], consumer=d["consumer"],
                   pool=d.get("pool", "views"),
                   batch_rows=int(d.get("batch_rows", 1024)),
                   state=d.get("state", "running"))


def _spec_path(name: str) -> str:
    return f"{VIEWS_ROOT}/{name}"


def build_view_plan(client, query: str) -> ir.Query:
    from ytsaurus_tpu.client import _SchemaResolver
    from ytsaurus_tpu.query.builder import build_query
    return build_query(query, _SchemaResolver(client))


def create_materialized_view(client, name: str, query: str,
                             source: Optional[str] = None,
                             target: Optional[str] = None,
                             pool: str = "views",
                             batch_rows: Optional[int] = None) -> dict:
    """Register a continuous view: validate the plan, create + mount the
    sorted target table (schema derived from the plan's incremental
    decomposition), register a VITAL offset consumer on the source queue
    (auto-trim then never outruns the view), and persist the spec at
    //sys/views/<name>.  Returns the spec as a dict."""
    if not name or "/" in name:
        raise YtError(f"Bad view name {name!r}",
                      code=EErrorCode.QueryTypeError)
    # A view exists once its @view_spec landed (the LAST step below):
    # keying on the spec rather than the bare node keeps a half-created
    # registry entry (a failure mid-create) re-creatable instead of
    # permanently wedging the name.
    if client.exists(_spec_path(name) + "/@view_spec"):
        raise YtError(f"View {name!r} already exists",
                      code=EErrorCode.AlreadyExists)
    if batch_rows is None:
        from ytsaurus_tpu.config import views_config
        batch_rows = views_config().default_batch_rows
    if batch_rows <= 0:
        raise YtError("batch_rows must be positive",
                      code=EErrorCode.InvalidConfig)
    plan = build_view_plan(client, query)
    if source is not None and source != plan.source:
        raise YtError(
            f"View source {source!r} does not match the query's FROM "
            f"table {plan.source!r}", code=EErrorCode.QueryTypeError)
    source = plan.source
    from ytsaurus_tpu.tablet.ordered import OrderedTablet
    (tablet,) = client._mounted_tablets(source)
    if not isinstance(tablet, OrderedTablet):
        raise YtError(
            f"View source {source!r} must be an ordered (queue) table",
            code=EErrorCode.QueryUnsupported)
    inc = prepare_incremental(plan)
    target = target or f"{VIEWS_ROOT}/{name}/target"
    consumer = f"{VIEWS_ROOT}/{name}/consumer"
    if client.exists(target):
        raise YtError(f"View target {target!r} already exists",
                      code=EErrorCode.AlreadyExists)
    client.create("map_node", _spec_path(name), recursive=True,
                  ignore_existing=True)
    try:
        client.create("table", target, recursive=True,
                      attributes={"schema": inc.target_schema,
                                  "dynamic": True})
        client.mount_table(target)
        client.register_queue_consumer(source, consumer, vital=True)
        spec = ViewSpec(name=name, query=query, source=source,
                        target=target, consumer=consumer, pool=pool,
                        batch_rows=batch_rows)
        client.set(_spec_path(name) + "/@view_spec", spec.to_dict())
    except Exception:
        # Failure-atomic registration: a half-created view (target
        # mounted but no spec, consumer registered but no spec) would
        # be unlistable AND unremovable.  Best-effort rollback; the
        # name stays re-creatable either way (the exists-precheck keys
        # on @view_spec).
        for cleanup in (
                lambda: client.unregister_queue_consumer(source,
                                                         consumer),
                lambda: client.remove(_spec_path(name), recursive=True),
                # We created the target above (pre-existing ones error
                # out earlier); an external one needs its own removal.
                lambda: client.exists(target) and
                client.remove(target, recursive=True)):
            try:
                cleanup()
            except YtError:
                pass
        raise
    return spec.to_dict()


def list_views(client) -> list[str]:
    if not client.exists(VIEWS_ROOT):
        return []
    return sorted(n for n in client.list(VIEWS_ROOT)
                  if client.exists(_spec_path(n) + "/@view_spec"))


def load_view(client, name: str) -> ViewSpec:
    path = _spec_path(name) + "/@view_spec"
    if not client.exists(path):
        raise YtError(f"No such view {name!r}",
                      code=EErrorCode.NoSuchNode)
    data = client.get(path)
    try:
        return ViewSpec.from_dict(data)
    except (KeyError, TypeError, ValueError, AttributeError) as exc:
        # A hand-edited @view_spec must surface as a diagnosable
        # YtError, not pierce the daemon/CLI as a bare KeyError.
        raise YtError(f"View {name!r} has a corrupt @view_spec "
                      f"({exc!r}): {data!r}",
                      code=EErrorCode.InvalidConfig) from exc


def set_view_state(client, name: str, state: str) -> dict:
    if state not in ("running", "paused"):
        raise YtError(f"Bad view state {state!r}",
                      code=EErrorCode.InvalidConfig)
    spec = load_view(client, name)
    spec.state = state
    client.set(_spec_path(name) + "/@view_spec", spec.to_dict())
    return spec.to_dict()


def remove_view(client, name: str, drop_target: bool = False) -> None:
    """Drop the view: registry node, consumer table (it lives under the
    registry node), and the source registration.  Target-table fate:
    a DEFAULT-path target (//sys/views/<name>/target) is owned by the
    view and would die with the registry node anyway — it is removed
    unless the caller parks it first with a Cypress move; an EXTERNAL
    target survives unless drop_target=True.  A missing source table
    (already dropped by the operator) must not wedge removal — the
    unregister is best-effort."""
    spec = load_view(client, name)
    try:
        client.unregister_queue_consumer(spec.source, spec.consumer)
    except YtError:
        # Source gone (or not a queue anymore): nothing to unregister,
        # and an unremovable view would error on every daemon pass.
        pass
    internal_target = spec.target.startswith(_spec_path(name) + "/")
    client.remove(_spec_path(name), recursive=True)
    if drop_target and not internal_target and \
            client.exists(spec.target):
        client.remove(spec.target, recursive=True)


def view_status(client, name: str) -> dict:
    """Spec + live cursor/lag + last-commit freshness — the `yt view
    show` / monitoring payload."""
    spec = load_view(client, name)
    from ytsaurus_tpu.server.queue_agent import _consumer_offset
    offset = _consumer_offset(client, spec.consumer, spec.source)
    (tablet,) = client._mounted_tablets(spec.source)
    progress = {}
    progress_path = _spec_path(name) + "/@view_progress"
    if client.exists(progress_path):
        progress = client.get(progress_path)
    return {
        **spec.to_dict(),
        "offset": offset,
        "source_row_count": tablet.row_count,
        "source_trimmed_count": tablet.trimmed_count,
        "lag_rows": max(tablet.row_count - offset, 0),
        "progress": progress,
    }


# -- the incremental refresher -------------------------------------------------


@dataclass
class BatchResult:
    view: str
    rows_in: int = 0               # source rows consumed
    rows_out: int = 0              # target rows upserted
    offset: int = 0                # committed cursor after this batch
    lag_rows: int = 0
    commit_timestamp: Optional[int] = None
    empty: bool = False
    trim_skipped: int = 0
    batch_seconds: float = 0.0
    freshness_seconds: Optional[float] = None


class ViewRefresher:
    """One view's tail loop: pull a micro-batch at the committed offset,
    evaluate incrementally, upsert + advance the cursor in ONE 2PC
    transaction.  Thread-safe; a single instance serializes its own
    refreshes, and CONCURRENT writers (a second daemon, a manual
    `yt view refresh`) are safe too: a stale batch is rejected by the
    optimistic cursor check inside the commit window — or by the
    tablet's write-conflict check on the shared consumer row when the
    races overlap — so exactly one writer's batch lands and the loser
    replays from the committed cursor."""

    def __init__(self, client, spec: ViewSpec,
                 evaluator=None, accountant=None, config_provider=None):
        self.client = client
        self.spec = spec
        self.inc = prepare_incremental(build_view_plan(client, spec.query))
        self._evaluator = evaluator
        self._accountant = accountant
        # Where ViewsConfig knobs (lag_slo_rows) come from: the daemon
        # passes its own dynamically-configured view, standalone
        # refreshers fall back to the process-global config.
        self._config_provider = config_provider
        self._batch_capacity = pad_capacity(spec.batch_rows)
        # The refresher's single-writer discipline: one refresh (the
        # read-merge-write critical section) at a time.
        # hot=False: this mutex COVERS the read-merge-write refresh
        # critical section — query execution, 2PC commit, the works —
        # by design (single-writer per view); hold-budget and
        # blocking-op rules don't apply to a coarse section lock.
        # guards: _last_result
        self._lock = sanitizers.register_lock(
            "views.ViewRefresher._lock", hot=False)
        self._last_result: Optional[BatchResult] = None
        prof = Profiler("/views").with_tags(view=spec.name)
        self._s_batches = prof.counter("batches")
        self._s_rows_in = prof.counter("rows_in")
        self._s_rows_out = prof.counter("rows_out")
        self._s_empty = prof.counter("empty_batches")
        self._s_conflicts = prof.counter("conflicts")
        self._s_trim_skips = prof.counter("trim_skipped_rows")
        self._s_lag = prof.gauge("lag_rows")
        self._s_fresh = prof.gauge("freshness_seconds")
        self._s_batch_seconds = prof.summary("batch_seconds")
        self._s_lag_ok = prof.counter("lag_ok")
        self._s_lag_breach = prof.counter("lag_breach")

    @property
    def evaluator(self):
        return self._evaluator or self.client.cluster.evaluator

    # -- one micro-batch -------------------------------------------------------

    def refresh_once(self) -> BatchResult:
        with self._lock:
            with child_span("views.refresh", view=self.spec.name):
                result = self._refresh_locked()
                self._last_result = result
                return result

    @property
    def last_result(self) -> "Optional[BatchResult]":
        with self._lock:
            return self._last_result

    def _refresh_locked(self) -> BatchResult:
        from ytsaurus_tpu.server.queue_agent import (
            _consumer_offset,
            advance_consumer,
        )
        client, spec = self.client, self.spec
        t0 = time.perf_counter()
        result = BatchResult(view=spec.name)
        offset = _consumer_offset(client, spec.consumer, spec.source)
        (tablet,) = client._mounted_tablets(spec.source)
        trimmed = tablet.trimmed_count
        if offset < trimmed:
            # Rows were trimmed past the cursor (a non-vital operator
            # trim): they are unrecoverable, so skip the cursor to the
            # trim boundary — counted, never silent — instead of
            # spinning on an un-servable offset forever.
            result.trim_skipped = trimmed - offset
            self._s_trim_skips.increment(result.trim_skipped)
            advance_consumer(client, spec.consumer, spec.source, trimmed)
            offset = trimmed
        row_count = tablet.row_count
        if offset >= row_count:
            result.empty = True
            result.offset = offset
            self._s_empty.increment()
            self._observe_lag(result, row_count, offset, None)
            return result
        _FP_BATCH.hit()
        rows = client.pull_queue(spec.source, offset=offset,
                                 limit=spec.batch_rows)
        if not rows:                      # trimmed under us: retry next pass
            result.empty = True
            result.offset = offset
            self._s_empty.increment()
            self._observe_lag(result, row_count, offset, None)
            return result
        new_offset = rows[-1][ROW_INDEX] + 1
        max_source_ts = max((r.get("$timestamp") or 0) for r in rows)
        upserts = self._compute_upserts(rows)
        commit_ts = self._commit(upserts, new_offset,
                                 base_offset=offset)
        result.rows_in = len(rows)
        result.rows_out = len(upserts)
        result.offset = new_offset
        result.commit_timestamp = commit_ts
        result.batch_seconds = time.perf_counter() - t0
        self._s_batches.increment()
        self._s_rows_in.increment(len(rows))
        self._s_rows_out.increment(len(upserts))
        self._s_batch_seconds.record(result.batch_seconds)
        self._observe_lag(result, tablet.row_count, new_offset,
                          max_source_ts)
        self._record_progress(result)
        self._account(result)
        return result

    def _compute_upserts(self, rows: list[dict]) -> list[dict]:
        inc = self.inc
        chunk = ColumnarChunk.from_rows(
            inc.batch_plan.schema, rows, capacity=self._batch_capacity)
        states = self.evaluator.run_plan(inc.batch_plan, chunk)
        if not inc.aggregating:
            return states.to_rows()
        fresh = states.to_rows()
        if not fresh:
            return []
        # Delta-merge: lookup the touched groups' stored states, then
        # re-group (stored ∪ fresh) with the merge combine — the same
        # mergeable-state algebra the GROUP BY shuffle uses, pointed at
        # micro-batches in time instead of shards in space.
        seen: set = set()
        keys = []
        for row in fresh:
            key = tuple(row[k] for k in inc.key_names)
            if key not in seen:
                seen.add(key)
                keys.append(key)
        stored = self.client._lookup_rows_direct(self.spec.target, keys)
        prev_states = [inc.stored_to_state(r) for r in stored
                       if r is not None]
        merged_in = states
        if prev_states:
            prev = ColumnarChunk.from_rows(inc.state_schema, prev_states)
            merged_in = concat_chunks(
                [prev, states.slice_rows(0, states.row_count)])
        merged = self.evaluator.run_plan(inc.merge_plan, merged_in)
        return [inc.finalize(r) for r in merged.to_rows()]

    def _commit(self, upserts: list[dict], new_offset: int,
                base_offset: int) -> Optional[int]:
        """Target write + offset advance, atomically.  An all-filtered
        batch has nothing to upsert: the cursor still must advance or
        the loop re-reads the batch forever — a monotonic
        advance_consumer (with the optimistic old_offset check) is
        exactly-once by idempotence there."""
        from ytsaurus_tpu.server.queue_agent import (
            _consumer_offset,
            advance_consumer,
        )
        client, spec = self.client, self.spec
        if not upserts:
            _FP_COMMIT.hit()
            try:
                advance_consumer(client, spec.consumer, spec.source,
                                 new_offset, old_offset=base_offset)
            except YtError as err:
                if err.code == EErrorCode.TransactionLockConflict:
                    self._s_conflicts.increment()
                raise
            return None
        tx = client.start_transaction()
        try:
            # Optimistic cursor check INSIDE the transaction window: a
            # concurrent writer (second daemon / manual refresh) that
            # committed BEFORE our tx started moved the cursor — our
            # batch is stale and re-applying its delta would
            # double-count.  One that commits AFTER this read trips the
            # tablet's last-committed-timestamp conflict check on the
            # shared consumer row at 2PC prepare instead.  Either way
            # exactly one writer's batch lands.
            if _consumer_offset(client, spec.consumer,
                                spec.source) != base_offset:
                raise YtError(
                    f"View {self.spec.name!r} cursor moved past "
                    f"{base_offset} (concurrent refresher?); "
                    f"replaying the batch",
                    code=EErrorCode.TransactionLockConflict)
            client.insert_rows(spec.target, upserts, tx=tx)
            # The classic torn spot: target staged, offset not yet.  A
            # crash here must lose BOTH (the tx never commits) — never
            # one of them.
            _FP_COMMIT.hit()
            client.insert_rows(spec.consumer, [{
                "queue_path": spec.source, "partition_index": 0,
                "offset": new_offset}], tx=tx)
            return client.commit_transaction(tx)
        except YtError as err:
            if tx.state == "active":
                client.abort_transaction(tx)
            if err.code == EErrorCode.TransactionLockConflict:
                self._s_conflicts.increment()
            raise

    # -- bookkeeping -----------------------------------------------------------

    def _views_config(self):
        if self._config_provider is not None:
            return self._config_provider()
        from ytsaurus_tpu.config import views_config
        return views_config()

    def _observe_lag(self, result: BatchResult, row_count: int,
                     offset: int, max_source_ts: Optional[int]) -> None:
        result.lag_rows = max(row_count - offset, 0)
        self._s_lag.set(result.lag_rows)
        if max_source_ts:
            from ytsaurus_tpu.tablet.timestamp import COUNTER_BITS
            result.freshness_seconds = max(
                time.time() - (max_source_ts >> COUNTER_BITS), 0.0)
            self._s_fresh.set(result.freshness_seconds)
        # The view-lag SLO pair: every pass votes good/bad against the
        # configured freshness-lag objective; the burn-rate tracker
        # (utils/slo.py) alerts on the ratio over the history rings.
        if result.lag_rows > self._views_config().lag_slo_rows:
            self._s_lag_breach.increment()
        else:
            self._s_lag_ok.increment()

    def _record_progress(self, result: BatchResult) -> None:
        progress = {
            "offset": result.offset,
            "lag_rows": result.lag_rows,
            "last_commit_timestamp": result.commit_timestamp,
            "last_batch_rows": result.rows_in,
            "last_batch_seconds": round(result.batch_seconds, 6),
        }
        # Freshness rides the TARGET node so plain readers can check how
        # stale their select is without knowing the view registry.
        self.client.set(_spec_path(self.spec.name) + "/@view_progress",
                        progress)
        self.client.set(self.spec.target + "/@view_freshness", {
            "offset": result.offset,
            "commit_timestamp": result.commit_timestamp,
            "freshness_seconds": result.freshness_seconds,
        })

    def _account(self, result: BatchResult) -> None:
        """Refresh work folds into per-tenant accounting under the
        view's pool, so `yt top` attributes daemon load (ISSUE 13
        satellite)."""
        from ytsaurus_tpu.query.accounting import get_accountant
        accountant = self._accountant or get_accountant()
        accountant.observe_view_batch(
            self.spec.pool, rows_read=result.rows_in,
            rows_written=result.rows_out,
            wall_seconds=result.batch_seconds)

    # -- drain -----------------------------------------------------------------

    def refresh(self, max_batches: int = 0) -> dict:
        """Run micro-batches until the cursor catches the head (or
        max_batches > 0 caps the pass).  Returns a roll-up."""
        batches = rows_in = rows_out = trim_skipped = 0
        lag = 0
        while True:
            result = self.refresh_once()
            lag = result.lag_rows
            trim_skipped += result.trim_skipped
            if result.empty:
                break
            batches += 1
            rows_in += result.rows_in
            rows_out += result.rows_out
            if result.lag_rows <= 0:
                break
            if max_batches and batches >= max_batches:
                break
        return {"view": self.spec.name, "batches": batches,
                "rows_in": rows_in, "rows_out": rows_out,
                "lag_rows": lag, "trim_skipped": trim_skipped}
