"""YSON: YT's object notation (ref yt/yt/core/yson) — text + binary."""

from ytsaurus_tpu.yson.parser import loads
from ytsaurus_tpu.yson.types import (
    YsonBoolean,
    YsonDouble,
    YsonEntity,
    YsonInt64,
    YsonList,
    YsonMap,
    YsonString,
    YsonType,
    YsonUint64,
    YsonUnicode,
    get_attributes,
    to_yson_type,
)
from ytsaurus_tpu.yson.writer import dumps
