"""Distributed sort: range partition → ICI all-to-all → per-device sort.

TPU-native redesign of the reference MapReduce Sort pipeline
(server/controller_agent/controllers/sort_controller.cpp: TPartitionTask +
TSortTask; job side: job_proxy/partition_job.cpp routing rows by partitioner
and partition_sort_job.cpp k-way merging):

  reference                               this framework
  ---------                               --------------
  samples_fetcher → partition key bounds  per-shard key samples → host pivots
  partition jobs route rows to chunks     searchsorted(pivots) on device
  shuffle = readers pull blocks over TCP  ONE jax.lax.all_to_all over ICI
  partition_sort heap merge per partition lexsort per device

Static shapes: a first (cheap) pass computes the exact (src, dst) transfer
matrix; the host sizes the exchange quota from its max and compiles the
exchange program for that bucket, so skewed data costs one recompile instead
of an overflow failure.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ytsaurus_tpu.parallel.compat import shard_map

from ytsaurus_tpu.chunks.columnar import Column, pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import packed_sort_indices
from ytsaurus_tpu.parallel.distributed import ShardedTable
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.schema import SortOrder, TableSchema


def _encode_key_plane(data: jax.Array, valid: jax.Array):
    """(null_rank, value) encoding: null sorts before any value."""
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    return valid.astype(jnp.int8), jnp.where(valid, data, jnp.zeros_like(data))


def _lex_less_const(row_planes, pivot_planes, pivot_idx, or_equal: bool):
    """Lexicographic row < pivots[pivot_idx] over encoded planes.

    row_planes: [(v, d)] each (cap,); pivot_planes: [(v, d)] each (n_piv,).
    """
    shape = row_planes[0][0].shape
    result = jnp.full(shape, or_equal, dtype=bool)
    for (rv, rd), (pv, pd) in reversed(list(zip(row_planes, pivot_planes))):
        p_v, p_d = pv[pivot_idx], pd[pivot_idx]
        lt = (rv < p_v) | ((rv == p_v) & (rd < p_d))
        eq = (rv == p_v) & (rd == p_d)
        result = lt | (eq & result)
    return result


def _partition_ids(row_planes, pivot_planes, n_pivots: int) -> jax.Array:
    """For each row, the number of pivots ≤ row (lexicographic) — i.e. its
    destination shard in [0, n_pivots]."""
    cap = row_planes[0][0].shape[0]
    pid = jnp.zeros(cap, dtype=jnp.int32)
    for i in range(n_pivots):
        # row >= pivots[i]  ⇔  not (row < pivots[i])
        ge = ~_lex_less_const(row_planes, pivot_planes, i, or_equal=False)
        pid = pid + ge.astype(jnp.int32)
    return pid


def quantile_pivots(sample_rows: "list[tuple]", n: int,
                    key_arity: int) -> "list[tuple]":
    """n-1 quantile pivots from sampled (valid, value) key tuples; the
    shared samples→bounds step of every range-partition path (ref
    partitioning_parameters_evaluator.cpp)."""
    sample_rows = sorted(sample_rows)
    pivots = []
    for j in range(1, n):
        pivots.append(sample_rows[(j * len(sample_rows)) // n]
                      if sample_rows
                      else tuple((False, 0) for _ in range(key_arity)))
    return pivots


def _sample_pivots(table: ShardedTable, key_names: list[str],
                   samples_per_shard: int = 256) -> list[tuple]:
    """Host-side: evenly sample keys from every shard, take quantile pivots.
    Ref: ytlib/table_client/samples_fetcher.h + partitioning_parameters_
    evaluator.cpp."""
    n = table.n_shards
    cap = table.capacity
    # Gather only the sample rows on device; transfer n*samples values, not
    # the whole plane.
    idx_parts = []
    for s in range(n):
        count = table.row_counts[s]
        if count == 0:
            continue
        idx_parts.append(np.linspace(0, count - 1,
                                     min(samples_per_shard, count),
                                     dtype=np.int64) + s * cap)
    if not idx_parts:
        return [tuple((False, 0) for _ in key_names) for _ in range(n - 1)]
    idx = jnp.asarray(np.concatenate(idx_parts))
    key_data = {}
    for name in key_names:
        col = table.columns[name]
        # analyze: allow(host-sync): pivot sampling reads O(shards*samples) gathered keys once per sort
        key_data[name] = (np.asarray(col.data[idx]), np.asarray(col.valid[idx]))
    sample_rows: list[tuple] = []
    for i in range(len(idx)):
        sample_rows.append(tuple(
            # analyze: allow(host-sync): key_data is host numpy (gathered above); .item() is a scalar read
            (bool(key_data[name][1][i]), key_data[name][0][i].item())
            for name in key_names))
    return quantile_pivots(sample_rows, n, len(key_names))


def route_rows(planes: dict, pid: jax.Array, n: int, quota: int,
               cap: int) -> tuple[dict, jax.Array]:
    """Inside shard_map: scatter local rows into per-destination blocks and
    all_to_all them.  `pid` in [0, n) for live rows, n for discards.
    Returns (received planes, received-row mask); receive capacity n*quota."""
    order = jnp.argsort(pid, stable=True)
    pid_sorted = pid[order]
    dest_counts = jax.vmap(lambda d: (pid_sorted == d).sum())(jnp.arange(n + 1))
    starts = jnp.concatenate([jnp.zeros(1, jnp.int64),
                              jnp.cumsum(dest_counts)[:-1]])
    pos = jnp.arange(cap)
    slot = pos - starts[jnp.clip(pid_sorted, 0, n)]
    send_index = jnp.clip(pid_sorted, 0, n - 1) * quota + slot
    in_quota = (slot < quota) & (pid_sorted < n)
    send_index = jnp.where(in_quota, send_index, n * quota)

    def route(plane):
        plane_sorted = plane[order]
        buf = jnp.zeros(n * quota + 1, dtype=plane.dtype)
        buf = buf.at[send_index].set(plane_sorted)
        return buf[: n * quota].reshape(n, quota)

    sent_mask = jnp.zeros(n * quota + 1, dtype=bool).at[send_index].set(
        in_quota)[: n * quota].reshape(n, quota)
    recv_mask = jax.lax.all_to_all(sent_mask, SHARD_AXIS, 0, 0,
                                   tiled=False).reshape(-1)
    recv: dict = {}
    for name, (data, valid) in planes.items():
        r_data = jax.lax.all_to_all(route(data), SHARD_AXIS, 0, 0,
                                    tiled=False).reshape(-1)
        r_valid = jax.lax.all_to_all(route(valid), SHARD_AXIS, 0, 0,
                                     tiled=False).reshape(-1)
        recv[name] = (r_data, r_valid & recv_mask)
    return recv, recv_mask


def transfer_counts(pid: jax.Array, row_valid: jax.Array, n: int) -> jax.Array:
    """Inside shard_map: (1, n) per-destination counts for quota sizing."""
    pid = jnp.where(row_valid, pid, n)
    counts = jax.vmap(lambda dest: (pid == dest).sum())(jnp.arange(n))
    return counts[None, :]


def sort_table(table: ShardedTable, key_columns: Sequence[str],
               descending: bool = False) -> ShardedTable:
    """Globally sort a ShardedTable by `key_columns` across the mesh.

    Result: shard i holds the i-th key range, sorted within the shard —
    i.e. globally sorted in shard-major order.
    """
    mesh = table.mesh
    n = table.n_shards
    key_names = list(key_columns)
    for name in key_names:
        if name not in table.columns:
            raise YtError(f"No such key column {name!r}",
                          code=EErrorCode.QueryExecutionError)
    if n == 1:
        return _sort_single(table, key_names, descending)

    return _sort_table_sharded(table, key_names, descending)


def _sort_table_sharded(table: ShardedTable, key_names: "list[str]",
                        descending: bool) -> ShardedTable:
    from ytsaurus_tpu.utils.tracing import child_span
    mesh = table.mesh
    n = table.n_shards
    pivots = _sample_pivots(table, key_names)
    # Pivot planes as device constants: [(valid_rank, value)] per key.
    pivot_planes = []
    for ki, name in enumerate(key_names):
        col = table.columns[name]
        vals = np.array([p[ki][1] for p in pivots])
        ranks = np.array([1 if p[ki][0] else 0 for p in pivots], dtype=np.int8)
        pivot_planes.append((jnp.asarray(ranks),
                             jnp.asarray(vals.astype(col.data.dtype))))

    cap = table.capacity
    names = [c.name for c in table.schema]

    # --- pass 1: exact transfer matrix ---------------------------------------
    def count_pass(key_planes_in, row_valid):
        row_planes = [_encode_key_plane(d, v) for d, v in key_planes_in]
        pid = _partition_ids(row_planes, pivot_planes, n - 1)
        if descending:
            pid = (n - 1) - pid                 # shard 0 takes the top range
        pid = jnp.where(row_valid, pid, n)      # padding rows → discard slot
        counts = jax.vmap(
            lambda dest: (pid == dest).sum())(jnp.arange(n))
        return counts[None, :]                  # (1, n) per shard

    key_planes_global = [(table.columns[k].data, table.columns[k].valid)
                         for k in key_names]
    with child_span("sort.partition", shards=n):
        counts = shard_map(
            count_pass, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P(SHARD_AXIS), check_vma=False)(
                key_planes_global, table.row_valid)
        # analyze: allow(host-sync): receive quotas are a host decision — one transfer-matrix read per shuffle
        counts_np = np.asarray(counts)          # (n_src, n_dst)

    # Skew-robust sizing (ref: the partition tree's multi-level splitting,
    # controllers/sort_controller.cpp:459+, re-expressed for a fixed-shape
    # collective): receive capacity is the EXACT per-destination need
    # (max column sum), not n x the hottest (src,dst) cell; a hot cell is
    # drained over multiple all_to_all rounds with a constant block size
    # instead of inflating every device's buffers.
    max_cell = max(int(counts_np.max()), 1)
    recv_cap = pad_capacity(max(int(counts_np.sum(axis=0).max()), 1))
    quota = pad_capacity(
        max((recv_cap + n - 1) // n, (max_cell + 7) // 8, 1))
    rounds = (max_cell + quota - 1) // quota
    # Per-destination packing offsets: rows from src s land at
    # [prefix[s], prefix[s] + counts[s, d]) on destination d.
    prefix_np = np.zeros((n, n), dtype=np.int64)    # (dst, src)
    prefix_np[:, 1:] = np.cumsum(counts_np.T, axis=1)[:, :-1]
    prefix_sharded = jax.device_put(
        jnp.asarray(prefix_np),
        jax.sharding.NamedSharding(mesh, P(SHARD_AXIS)))

    # --- pass 2: multi-round route + all_to_all + local sort ------------------
    def exchange(columns_in, key_planes_in, row_valid, prefix_in):
        row_planes = [_encode_key_plane(d, v) for d, v in key_planes_in]
        pid = _partition_ids(row_planes, pivot_planes, n - 1)
        if descending:
            pid = (n - 1) - pid
        pid = jnp.where(row_valid, pid, n)
        prefix = prefix_in.reshape(n)               # my dst row: per-src base
        # Stable cell rank of each local row within its (src, dst) cell.
        order = jnp.argsort(pid, stable=True)
        pid_sorted = pid[order]
        dest_counts = jax.vmap(
            lambda d: (pid_sorted == d).sum())(jnp.arange(n + 1))
        starts = jnp.concatenate([jnp.zeros(1, jnp.int64),
                                  jnp.cumsum(dest_counts)[:-1]])
        pos = jnp.arange(cap)
        cell_rank = pos - starts[jnp.clip(pid_sorted, 0, n)]
        planes_sorted = {name: (columns_in[name][0][order],
                                columns_in[name][1][order])
                         for name in names}
        recv_planes = {name: (
            jnp.zeros(recv_cap, dtype=planes_sorted[name][0].dtype),
            jnp.zeros(recv_cap, dtype=bool)) for name in names}
        recv_mask = jnp.zeros(recv_cap, dtype=bool)
        for r in range(rounds):
            in_round = (pid_sorted < n) & (cell_rank >= r * quota) & \
                (cell_rank < (r + 1) * quota)
            slot = cell_rank - r * quota
            send_index = jnp.clip(pid_sorted, 0, n - 1) * quota + slot
            send_index = jnp.where(in_round, send_index, n * quota)

            sent_mask = jnp.zeros(n * quota + 1, dtype=bool).at[
                send_index].set(in_round)[: n * quota].reshape(n, quota)
            arrived = jax.lax.all_to_all(sent_mask, SHARD_AXIS, 0, 0,
                                         tiled=False)     # (n_src, quota)
            # Destination positions for this round's block from each src.
            dst_pos = prefix[:, None] + r * quota + jnp.arange(quota)[None, :]
            dst_pos = jnp.where(arrived, dst_pos, recv_cap)
            dst_flat = dst_pos.reshape(-1)
            recv_mask = jnp.concatenate(
                [recv_mask, jnp.zeros(1, dtype=bool)]).at[dst_flat].set(
                arrived.reshape(-1))[:recv_cap] | recv_mask
            for name in names:
                data_s, valid_s = planes_sorted[name]

                def send(plane):
                    buf = jnp.zeros(n * quota + 1, dtype=plane.dtype)
                    buf = buf.at[send_index].set(plane)
                    return buf[: n * quota].reshape(n, quota)

                rd = jax.lax.all_to_all(send(data_s), SHARD_AXIS, 0, 0,
                                        tiled=False).reshape(-1)
                rv = jax.lax.all_to_all(send(valid_s), SHARD_AXIS, 0, 0,
                                        tiled=False).reshape(-1)
                acc_d, acc_v = recv_planes[name]
                # Rounds write DISJOINT position ranges, so plain scatter
                # over the accumulated planes composes them.
                acc_d = jnp.concatenate(
                    [acc_d, jnp.zeros(1, dtype=acc_d.dtype)]).at[
                    dst_flat].set(rd)[:recv_cap]
                acc_v = jnp.concatenate(
                    [acc_v, jnp.zeros(1, dtype=bool)]).at[dst_flat].set(
                    rv & arrived.reshape(-1))[:recv_cap]
                recv_planes[name] = (acc_d, acc_v)
        # Rebuild validity strictly from arrivals (the accumulator ORs).
        recv_planes = {name: (d, v & recv_mask)
                       for name, (d, v) in recv_planes.items()}
        # Local sort of received rows by key (absent rows sink last).
        items = [((~recv_mask), jnp.ones_like(recv_mask), False, 1)]
        for name in key_names:
            d, v = recv_planes[name]
            items.append((d, v & recv_mask, descending, 64))
        order2 = packed_sort_indices(items)
        out = {name: (d[order2], v[order2])
               for name, (d, v) in recv_planes.items()}
        out_count = recv_mask.sum()
        return out, out_count[None]

    columns_global = {name: (table.columns[name].data,
                             table.columns[name].valid) for name in names}
    # all_to_all payload: routed rows x per-row plane bytes (+1 for each
    # validity bit plane) — the wire cost tag on the shuffle span.
    bytes_per_row = sum(
        np.dtype(table.columns[name].data.dtype).itemsize + 1
        for name in names)
    with child_span("sort.shuffle", shards=n, rounds=rounds,
                    all_to_all_bytes=int(counts_np.sum()) * bytes_per_row):
        mapped = shard_map(
            exchange, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)), check_vma=False)
        out_columns_planes, out_counts = jax.jit(mapped)(
            columns_global, key_planes_global, table.row_valid,
            prefix_sharded)

    # analyze: allow(host-sync): conservation check — one stacked counts transfer per shuffle
    out_counts_np = [int(c) for c in np.asarray(out_counts)]
    lost = table.total_rows - sum(out_counts_np)
    if lost != 0:
        raise YtError(f"Shuffle lost {lost} rows (quota={quota})",
                      code=EErrorCode.QueryExecutionError)
    out_columns: dict[str, Column] = {}
    for col_schema in table.schema:
        data, valid = out_columns_planes[col_schema.name]
        src = table.columns[col_schema.name]
        out_columns[col_schema.name] = Column(
            type=col_schema.type, data=data, valid=valid,
            dictionary=src.dictionary)
    sorted_schema = _sorted_schema(table.schema, key_names, descending)
    # Row-presence mask per shard from the received counts.
    rv = shard_map(
        lambda c: (jnp.arange(recv_cap) < c[0])[None, :],
        mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS),
        check_vma=False)(out_counts).reshape(-1)
    return ShardedTable(schema=sorted_schema, mesh=mesh, capacity=recv_cap,
                        columns=out_columns, row_counts=out_counts_np,
                        row_valid=rv)


def _sort_single(table: ShardedTable, key_names: list[str],
                 descending: bool = False) -> ShardedTable:
    """One-device mesh: plain packed-key sort, same result contract."""
    mask = table.row_valid
    items = [((~mask), jnp.ones_like(mask), False, 1)]
    for name in key_names:
        col = table.columns[name]
        items.append((col.data, col.valid & mask, descending, 64))
    order = packed_sort_indices(items)
    out_columns = {
        name: Column(type=col.type, data=col.data[order],
                     valid=col.valid[order], dictionary=col.dictionary)
        for name, col in table.columns.items()}
    return ShardedTable(
        schema=_sorted_schema(table.schema, key_names, descending),
        mesh=table.mesh, capacity=table.capacity, columns=out_columns,
        row_counts=list(table.row_counts), row_valid=mask[order])


def _sorted_schema(schema: TableSchema, key_names: list[str],
                   descending: bool) -> TableSchema:
    order = SortOrder.descending if descending else SortOrder.ascending
    cols = []
    reordered = [schema.get(k) for k in key_names] + \
        [c for c in schema if c.name not in key_names]
    for i, col in enumerate(reordered):
        cols.append(col.with_sort_order(order if i < len(key_names) else None))
    return TableSchema(columns=tuple(cols))
