"""Declarative validated configs + dynamic config delivery.

Ref shape: core/ytree/yson_struct.h (TYsonStruct: registered parameters with
defaults, validators, postprocessors, recursive merge) and
library/dynamic_config/dynamic_config_manager.h:23 (polls a Cypress path,
diffs, applies, keeps the last good config on validation failure).

Redesign: instead of C++ macro registration, a `YsonStruct` base class scans
class-level `param(...)` declarations at subclass creation.  Values load
from YSON-shaped dicts (bytes keys tolerated), merge recursively, and
round-trip through `to_dict` for persistence in Cypress documents.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("Config")


class _Param:
    """One declared parameter: default, type, constraints."""

    __slots__ = ("name", "default", "default_factory", "type", "ge", "le",
                 "choices", "validator")

    def __init__(self, default=None, *, default_factory=None, type=None,
                 ge=None, le=None, choices=None, validator=None):
        self.name: str = ""            # filled by __set_name__
        self.default = default
        self.default_factory = default_factory
        self.type = type
        self.ge = ge
        self.le = le
        self.choices = choices
        self.validator = validator

    def __set_name__(self, owner, name):
        self.name = name

    def make_default(self):
        if self.default_factory is not None:
            return self.default_factory()
        if isinstance(self.type, type) and issubclass(self.type, YsonStruct) \
                and self.default is None:
            return self.type()
        return self.default

    def check(self, value, path: str) -> Any:
        if value is None:
            # Explicit null resets to the default (it must NOT bypass
            # validation and poison consumers with unexpected Nones).
            return self.make_default()
        if self.type is not None:
            if isinstance(self.type, type) and issubclass(self.type,
                                                          YsonStruct):
                if isinstance(value, dict):
                    value = self.type.from_dict(value, path=path)
                elif not isinstance(value, self.type):
                    raise YtError(f"Config {path}: expected map for "
                                  f"{self.type.__name__}, got {value!r}",
                                  code=EErrorCode.InvalidConfig)
            elif self.type is float and isinstance(value, int) \
                    and not isinstance(value, bool):
                value = float(value)
            elif self.type is str and isinstance(value, bytes):
                value = value.decode("utf-8")
            elif not isinstance(value, self.type) \
                    or (self.type is int and isinstance(value, bool)):
                raise YtError(f"Config {path}: expected "
                              f"{self.type.__name__}, got {value!r}",
                              code=EErrorCode.InvalidConfig)
        if self.ge is not None and value < self.ge:
            raise YtError(f"Config {path}: {value!r} < minimum {self.ge!r}",
                          code=EErrorCode.InvalidConfig)
        if self.le is not None and value > self.le:
            raise YtError(f"Config {path}: {value!r} > maximum {self.le!r}",
                          code=EErrorCode.InvalidConfig)
        if self.choices is not None and value not in self.choices:
            raise YtError(f"Config {path}: {value!r} not one of "
                          f"{sorted(self.choices)!r}",
                          code=EErrorCode.InvalidConfig)
        if self.validator is not None:
            self.validator(value)
        return value


def param(default=None, **kwargs) -> Any:
    """Declare a config parameter on a YsonStruct subclass."""
    return _Param(default, **kwargs)


class YsonStruct:
    """Base for declarative configs; see module docstring.

    Subclasses declare parameters:

        class StoreConfig(YsonStruct):
            capacity_bytes = param(1 << 30, type=int, ge=0)
            codec = param("lz4", type=str, choices={"none", "lz4", "zstd"})

    Unknown keys raise by default; set `keep_unrecognized = True` to retain
    them (exposed via `.unrecognized`).
    """

    keep_unrecognized = False
    _params: dict[str, _Param] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        merged: dict[str, _Param] = dict(cls.__mro__[1]._params) \
            if hasattr(cls.__mro__[1], "_params") else {}
        for name, value in list(vars(cls).items()):
            if isinstance(value, _Param):
                merged[name] = value
        cls._params = merged

    def __init__(self, **overrides):
        self.unrecognized: dict[str, Any] = {}
        for name, p in self._params.items():
            setattr(self, name, p.make_default())
        for name, value in overrides.items():
            if name not in self._params:
                raise YtError(f"Unknown config parameter {name!r}",
                              code=EErrorCode.InvalidConfig)
            setattr(self, name, self._params[name].check(value, name))
        self.postprocess()

    # -- hooks -----------------------------------------------------------------

    def postprocess(self) -> None:
        """Cross-field validation; override in subclasses."""

    # -- load / dump -----------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict, path: str = "") -> "YsonStruct":
        self = cls.__new__(cls)
        self.unrecognized = {}
        data = {(k.decode("utf-8") if isinstance(k, bytes) else k): v
                for k, v in (data or {}).items()}
        for name, p in cls._params.items():
            here = f"{path}/{name}" if path else name
            if name in data:
                setattr(self, name, p.check(data.pop(name), here))
            else:
                setattr(self, name, p.make_default())
        if data:
            if cls.keep_unrecognized:
                self.unrecognized = data
            else:
                raise YtError(
                    f"Unrecognized config keys at {path or '/'}: "
                    f"{sorted(data)!r}", code=EErrorCode.InvalidConfig)
        self.postprocess()
        return self

    def to_dict(self) -> dict:
        out = {}
        for name in self._params:
            value = getattr(self, name)
            out[name] = value.to_dict() if isinstance(value, YsonStruct) \
                else value
        out.update(self.unrecognized)
        return out

    # -- merge -----------------------------------------------------------------

    def merge(self, patch: Optional[dict]) -> "YsonStruct":
        """Recursive merge: returns a NEW validated instance; `self` is
        untouched (the dynamic-config manager keeps the old config when the
        merged one fails validation)."""
        merged = _deep_merge(self.to_dict(), patch or {})
        return type(self).from_dict(merged)

    def __eq__(self, other):
        return type(other) is type(self) and other.to_dict() == self.to_dict()

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._params)
        return f"{type(self).__name__}({inner})"


def _deep_merge(base: dict, patch: dict) -> dict:
    out = dict(base)
    for key, value in patch.items():
        if isinstance(key, bytes):
            key = key.decode("utf-8")
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


# ---------------------------------------------------------------------------
# Daemon configs (static YSON file; every server role loads one of these).
# ---------------------------------------------------------------------------

class RetryPolicyConfig(YsonStruct):
    """Jittered-exponential-backoff retry knobs shared by every recovery
    ladder (RPC channels, replicated chunk reads, per-shard query
    retries).  Delay for attempt i is
    `min(backoff * 2^i, backoff_cap) * (1 - jitter * U[0,1))` — the
    jitter decorrelates retry storms after a common-cause failure."""

    attempts = param(5, type=int, ge=1)
    backoff = param(0.2, type=float, ge=0.0)
    backoff_cap = param(3.0, type=float, ge=0.0)
    jitter = param(0.2, type=float, ge=0.0, le=1.0)
    # Token-bucket retry budget (ISSUE 17): each retry spends one token,
    # each SUCCESSFUL call deposits `retry_budget_refill` tokens (capped
    # at `retry_budget`), and a throttled outcome deposits NOTHING — an
    # overloaded cluster sees its retry traffic decay instead of a
    # retry storm.  0 disables the budget (unbounded retries, the
    # pre-ISSUE-17 behavior).
    retry_budget = param(0, type=int, ge=0)
    retry_budget_refill = param(0.1, type=float, ge=0.0)

    def delay(self, attempt: int, rng=None) -> float:
        base = min(self.backoff * (2 ** attempt), self.backoff_cap)
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        import random as _random
        u = (rng or _random).random()
        return base * (1.0 - self.jitter * u)


# Process-wide retry policies, keyed by ladder.  Call sites read these
# instead of hardcoding attempts/backoff (ISSUE 2 satellite); tests and
# daemons override via set_retry_policy.
_RETRY_POLICIES: dict[str, RetryPolicyConfig] = {}
_RETRY_DEFAULTS: dict[str, dict] = {
    # General RPC transport retries (RetryingChannel's historical 5/0.2).
    "rpc": {},
    # Remote job start/poll: fail fast so the job revives on another node.
    "job_rpc": dict(attempts=2, backoff=0.1, backoff_cap=1.0),
    # Replicated chunk read ladder: rotate fast, short waits.
    "chunk_read": dict(attempts=3, backoff=0.05, backoff_cap=1.0,
                       jitter=0.5),
    # Per-shard retry inside coordinate_and_execute.
    "query_shard": dict(attempts=3, backoff=0.05, backoff_cap=0.5,
                        jitter=0.5),
}


def retry_policy(name: str) -> RetryPolicyConfig:
    policy = _RETRY_POLICIES.get(name)
    if policy is None:
        defaults = _RETRY_DEFAULTS.get(name)
        if defaults is None:
            raise YtError(f"Unknown retry policy {name!r}",
                          code=EErrorCode.InvalidConfig)
        policy = _RETRY_POLICIES[name] = RetryPolicyConfig(**defaults)
    return policy


def set_retry_policy(name: str, policy: RetryPolicyConfig) -> None:
    if name not in _RETRY_DEFAULTS:
        raise YtError(f"Unknown retry policy {name!r}",
                      code=EErrorCode.InvalidConfig)
    _RETRY_POLICIES[name] = policy


class TabletConfig(YsonStruct):
    """Tablet read-path knobs (tablet/tablet.py):

    - `host_plane_cache_capacity`: entries in the per-tablet LRU of
      host-side numpy plane views (promote-on-hit; the lookup probe's
      device→host staging cache).
    - `snapshot_cache_enabled`: memoize the materialized visible chunk
      per (flush generation, store mutation count) for latest-timestamp
      reads; invalidated by any write/flush/compact.
    - `vectorized_scan_min_rows`: version count at/above which the MVCC
      merge (read_snapshot/flush/compact) runs as the columnar XLA
      pipeline; below it the Python reference merge wins (per-program
      dispatch overhead dominates tiny stores — the same dispatch
      economics as coordinator shard coalescing).  0 forces the
      vectorized path always (parity tests use this)."""

    host_plane_cache_capacity = param(64, type=int, ge=1)
    snapshot_cache_enabled = param(True, type=bool)
    vectorized_scan_min_rows = param(1024, type=int, ge=0)


_TABLET_CONFIG: "Optional[TabletConfig]" = None


def tablet_config() -> TabletConfig:
    global _TABLET_CONFIG
    if _TABLET_CONFIG is None:
        _TABLET_CONFIG = TabletConfig()
    return _TABLET_CONFIG


def set_tablet_config(config: "Optional[TabletConfig]") -> None:
    """Install a process-wide tablet config (None restores defaults)."""
    global _TABLET_CONFIG
    _TABLET_CONFIG = config


class TracingConfig(YsonStruct):
    """Query flight recorder knobs (utils/tracing.py + query/profile.py):

    - `enabled`: master switch; False turns every span site into the
      NULL fast path (one contextvar read, ≲1µs — asserted by
      `bench.py --config trace_overhead`).
    - `sample_rate`: probability a new ROOT trace records its spans
      (entry points: gateway select/lookup, scheduler operations, HTTP
      proxy).  explain_analyze and X-YT-Trace-Id requests always sample.
    - `slow_query_threshold`: queries at/above this wall time (seconds)
      are ALWAYS retained in the flight recorder's slow-query log;
      faster queries are retained at `sample_rate`.
    - `slow_log_capacity` / `recent_log_capacity`: bounded profile logs.
    - `ring_capacity`: finished-span ring buffer size (bounded memory).
    """

    enabled = param(True, type=bool)
    sample_rate = param(1.0, type=float, ge=0.0, le=1.0)
    slow_query_threshold = param(0.5, type=float, ge=0.0)
    slow_log_capacity = param(128, type=int, ge=1)
    recent_log_capacity = param(128, type=int, ge=1)
    ring_capacity = param(4096, type=int, ge=1)


_TRACING_CONFIG: "Optional[TracingConfig]" = None


def tracing_config() -> TracingConfig:
    global _TRACING_CONFIG
    if _TRACING_CONFIG is None:
        _TRACING_CONFIG = TracingConfig()
    return _TRACING_CONFIG


def set_tracing_config(config: "Optional[TracingConfig]") -> None:
    """Install a process-wide tracing config (None restores defaults);
    pushes the fast-path mirrors into utils/tracing."""
    global _TRACING_CONFIG
    _TRACING_CONFIG = config
    from ytsaurus_tpu.utils import tracing
    tracing.configure(config)


class SloConfig(YsonStruct):
    """One service-level objective, evaluated over the metrics-history
    rings (utils/profiling.MetricsHistory) with multi-window burn-rate
    alerting (utils/slo.SloTracker).

    Two SLI shapes cover the fleet's objectives:

    - `availability`/`ratio`: good/bad event counters.  The SLI over a
      window is bad/(good+bad) from the counters' history deltas —
      e.g. admission rejects vs admits, or compile-cache misses vs hits
      (`compile_cache_hit_rate`, the ROADMAP item 1 acceptance gate).
    - `latency`: a histogram sensor plus `bound_ms`.  Error events are
      observations above the bound (from bucket-count deltas), so
      "`objective` of requests finish within `bound_ms`" — the p99-style
      objective — needs no per-request log, just the bucket rings.
      `bound_ms` should align with a bucket bound; the evaluator uses
      the tightest bucket that contains it (errors only over-count).

    Burn rate = error_rate / (1 - objective): 1.0 burns the whole error
    budget exactly over the SLO period.  The alert FIRES when both the
    fast and the slow window exceed `burn_threshold` (the classic
    multi-window rule: fast catches the regression quickly, slow keeps
    one blip from paging) and RESOLVES once the fast window recovers."""

    kind = param("availability", type=str,
                 choices={"availability", "ratio", "latency"})
    # latency: the histogram series name (registry path, e.g.
    # "/serving/select_latency_seconds").
    sensor = param("", type=str)
    # availability/ratio: counter series names.
    good_sensor = param("", type=str)
    bad_sensor = param("", type=str)
    # Tag filter (subset match): {"pool": "prod"} evaluates one pool's
    # series; empty sums every tagged series of the sensor.
    tags = param(default_factory=dict, type=dict)
    objective = param(0.99, type=float, ge=0.0, le=1.0)
    bound_ms = param(0.0, type=float, ge=0.0)
    fast_window = param(300.0, type=float, ge=0.0)
    slow_window = param(3600.0, type=float, ge=0.0)
    burn_threshold = param(10.0, type=float, ge=0.0)

    def postprocess(self):
        if self.kind == "latency":
            if not self.sensor or self.bound_ms <= 0:
                raise YtError(
                    "latency SLO requires `sensor` (a histogram) and a "
                    "positive `bound_ms`", code=EErrorCode.InvalidConfig)
        elif not self.good_sensor or not self.bad_sensor:
            raise YtError(
                f"{self.kind} SLO requires `good_sensor` and "
                f"`bad_sensor` counters", code=EErrorCode.InvalidConfig)


class TelemetryConfig(YsonStruct):
    """Cluster telemetry plane knobs (utils/profiling.MetricsHistory +
    utils/slo.SloTracker + query/accounting.ResourceAccountant):

    - `sample_period`: the sampler thread snapshots every registered
      sensor this often into the history rings (0 disables sampling;
      tests drive `sample_once()` manually with synthetic timestamps).
    - `fine_capacity`/`coarse_every`/`coarse_capacity`: ring tiers.
      Defaults hold 1h at 10s resolution plus 24h at 5min resolution
      (10s x 360 + 5min x 288) in bounded memory per sensor.
    - `slos`: name -> SloConfig, evaluated after every sample.
    - `mesh_telemetry`: arm the in-program mesh telemetry block (ISSUE
      20) — per-shard row counts, transfer matrices, quota headroom —
      stacked onto the whole-plan final transfer (same single host
      sync).  The flag folds into every SPMD cache key.
    - `mesh_max_imbalance`: max-shard/mean-shard output-row ratio above
      which an execution counts as SKEWED for the `/query/mesh/*`
      balanced-vs-skewed counters (the MESH_SKEW_SLO denominator)."""

    enabled = param(True, type=bool)
    sample_period = param(10.0, type=float, ge=0.0)
    fine_capacity = param(360, type=int, ge=1)
    # Every Nth fine sample is folded into the coarse ring.
    coarse_every = param(30, type=int, ge=1)
    coarse_capacity = param(288, type=int, ge=1)
    slos = param(default_factory=dict, type=dict)
    mesh_telemetry = param(True, type=bool)
    mesh_max_imbalance = param(4.0, type=float, ge=1.0)

    def postprocess(self):
        parsed = {}
        for name, spec in (self.slos or {}).items():
            if isinstance(name, bytes):
                name = name.decode("utf-8")
            if isinstance(spec, SloConfig):
                parsed[name] = spec
            elif isinstance(spec, dict):
                parsed[name] = SloConfig.from_dict(spec,
                                                   path=f"slos/{name}")
            else:
                raise YtError(f"SLO {name!r}: expected map, got {spec!r}",
                              code=EErrorCode.InvalidConfig)
        self.slos = parsed

    def to_dict(self) -> dict:
        out = super().to_dict()
        out["slos"] = {name: slo.to_dict()
                       for name, slo in self.slos.items()}
        return out


_TELEMETRY_CONFIG: "Optional[TelemetryConfig]" = None


def telemetry_config() -> TelemetryConfig:
    global _TELEMETRY_CONFIG
    if _TELEMETRY_CONFIG is None:
        _TELEMETRY_CONFIG = TelemetryConfig()
    return _TELEMETRY_CONFIG


def set_telemetry_config(config: "Optional[TelemetryConfig]") -> None:
    """Install a process-wide telemetry config (None restores defaults);
    rebuilds the global history rings + SLO tracker to the new shape."""
    global _TELEMETRY_CONFIG
    _TELEMETRY_CONFIG = config
    from ytsaurus_tpu.utils import profiling, slo
    # Tracker first: configure_telemetry restarts a running sampler,
    # and the restarted thread must hook the NEW tracker's evaluate.
    slo.configure(config)
    profiling.configure_telemetry(config)


class WorkloadConfig(YsonStruct):
    """Workload recorder + compilation observatory knobs (ISSUE 8,
    query/workload.py + query/engine/evaluator.py):

    - `enabled`: master switch for the workload recorder; False turns
      every observe site into one config read.
    - `sample_rate`: probability an admitted query folds a record into
      the workload log (1.0 = record everything; high-rate fleets dial
      this down — the log is a statistical capture, not an audit log).
    - `capacity`: bounded in-memory record ring (what `/workload` and
      `yt workload capture` serve).
    - `fingerprint_capacity`: bounded per-fingerprint roll-up map; new
      fingerprints past the cap count as dropped instead of growing it.
    - `log_dir`: when set, sampled records ALSO append to a rotated
      on-disk JSONL log (`workload.jsonl`, header line carries the
      schema version) bounded by `rotate_bytes` x `max_files`.
    - `lookup_keys_per_record`: lookup records retain at most this many
      key tuples (enough to replay; bounds record size).
    - `capture_artifacts`: the compilation observatory captures each
      compiled executable's HLO text + XLA `cost_analysis()`
      FLOPs/bytes (bounded by `artifact_capacity`, HLO truncated to
      `hlo_max_chars`).  Off by default: artifacts are debugging
      payloads, not steady-state telemetry.
    - `compile_cache_capacity`: LRU bound on the evaluator's compiled
      program cache (0 = unbounded, the historical behavior).  With a
      bound, evictions are counted per fingerprint and a re-miss on an
      evicted key is tagged cause=eviction."""

    enabled = param(True, type=bool)
    sample_rate = param(1.0, type=float, ge=0.0, le=1.0)
    capacity = param(4096, type=int, ge=1)
    fingerprint_capacity = param(1024, type=int, ge=1)
    log_dir = param(None, type=str)
    rotate_bytes = param(4 << 20, type=int, ge=4096)
    max_files = param(4, type=int, ge=1)
    lookup_keys_per_record = param(16, type=int, ge=0)
    capture_artifacts = param(False, type=bool)
    artifact_capacity = param(64, type=int, ge=1)
    hlo_max_chars = param(20_000, type=int, ge=0)
    compile_cache_capacity = param(0, type=int, ge=0)


_WORKLOAD_CONFIG: "Optional[WorkloadConfig]" = None


def workload_config() -> WorkloadConfig:
    global _WORKLOAD_CONFIG
    if _WORKLOAD_CONFIG is None:
        _WORKLOAD_CONFIG = WorkloadConfig()
    return _WORKLOAD_CONFIG


def set_workload_config(config: "Optional[WorkloadConfig]") -> None:
    """Install a process-wide workload config (None restores defaults);
    rebinds the global workload log to the new shape."""
    global _WORKLOAD_CONFIG
    _WORKLOAD_CONFIG = config
    from ytsaurus_tpu.query import workload
    workload.configure(config)


class CompileConfig(YsonStruct):
    """Compile-once serving knobs (ISSUE 10, query/parameterize.py +
    query/engine/evaluator.py + query/engine/aot_cache.py):

    - `parameterize`: auto-parameterize plans — the evaluator (and the
      distributed SPMD evaluator) key their compiled-program caches on
      the SHAPE fingerprint (hoistable literal values and bucketed
      LIMIT/OFFSET collapsed; see ir.fingerprint(omit_values=True)),
      and the lowering feeds literals/limits to the program as runtime
      bindings, so `WHERE user_id = ?` traffic compiles ONCE per shape
      instead of once per constant.  Off restores the historical
      per-constant fingerprints (bench A/B leg).
    - `disk_cache_dir`: when set, AOT-compiled executables ALSO persist
      to this directory (jax serialize_executable of lower().compile()
      products), keyed (fingerprint, capacity bucket, binding shapes,
      backend, jax version).  A fresh process warm-starts from disk
      instead of cold-compiling the fleet after a rolling restart.
      None (default) disables the disk tier.
    - `disk_cache_capacity_bytes`: size cap on the artifact directory;
      the writer evicts oldest-mtime files past it (loads touch mtime,
      so eviction is LRU-ish).
    - `disk_cache_min_compile_seconds`: programs that compiled faster
      than this are not worth a disk round-trip; 0 persists everything
      (tests).
    - `whole_plan`: lower fusable distributed plans as ONE
      jit(shard_map) program (parallel/whole_plan.py, ISSUE 12) — the
      top rung of the degradation ladder.  Off forces the stitched
      rungs (bench A/B leg, escape hatch).
    - `whole_plan_headroom`: multiplier applied when an OVERFLOW
      escalates a fused program's static exchange/expansion quota (the
      estimate has proven short, so the re-run takes extra slack).
      First guesses and settled steady-state quotas round the
      estimate/measured demand to pow2 WITHOUT it — the rounding is
      the slack, and doubling accurate capacities taxes every
      downstream stage."""

    parameterize = param(True, type=bool)
    disk_cache_dir = param(None, type=str)
    disk_cache_capacity_bytes = param(256 << 20, type=int, ge=0)
    disk_cache_min_compile_seconds = param(0.0, type=float, ge=0.0)
    whole_plan = param(True, type=bool)
    whole_plan_headroom = param(1.5, type=float, ge=1.0)
    # Cost-based join planning (query/planner.py, ISSUE 14): reorder
    # multiway equi-joins by estimated cardinality (chunk-stats NDV
    # sketches), choose broadcast-vs-partition per side, and push
    # semi-join key ranges from selective sides into the scan stage.
    # Off restores the declared left-to-right cascade (bench A/B leg,
    # escape hatch).  `broadcast_join_rows`: foreign sides at or below
    # this row count replicate to every device instead of riding the
    # co-partition exchange (they must also prove unique join keys).
    cost_join_planner = param(True, type=bool)
    broadcast_join_rows = param(65536, type=int, ge=0)
    # Encoded-plane kernel execution (ISSUE 19, query/engine/expr.py +
    # interp.py): string predicates against literals compare the column's
    # dict CODES with a host-bound code — no merged-vocab remap tables,
    # no per-row gathers.  Off restores the decoded remap-table path
    # (the bit-identity oracle the dual-check corpus runs both ways).
    encoded_predicates = param(True, type=bool)
    # Buffer donation (ISSUE 19, evaluator/joins/distributed dispatch):
    # OWNED chunk-sized temporaries (join-cascade intermediates, phase-1
    # join products) are donated to their consuming program so XLA can
    # reuse the buffers in place.  Persistent table chunks are NEVER
    # donated.  Off = copying fallback (escape hatch + A/B leg).
    donate_buffers = param(True, type=bool)


_COMPILE_CONFIG: "Optional[CompileConfig]" = None


def compile_config() -> CompileConfig:
    global _COMPILE_CONFIG
    if _COMPILE_CONFIG is None:
        _COMPILE_CONFIG = CompileConfig()
    return _COMPILE_CONFIG


def set_compile_config(config: "Optional[CompileConfig]") -> None:
    """Install a process-wide compile config (None restores defaults);
    rebinds the global disk compile-artifact cache to the new shape."""
    global _COMPILE_CONFIG
    _COMPILE_CONFIG = config
    from ytsaurus_tpu.query.engine import aot_cache
    aot_cache.configure(config)


class TieringConfig(YsonStruct):
    """Adaptive tiered execution knobs (ISSUE 18, query/engine/interp.py +
    query/engine/evaluator.py + query/engine/prewarm.py):

    - `enabled`: master switch for the interpreter tier.  Off (the
      default — rollout gate, same convention as `disk_cache_dir`)
      restores the pre-tiering behavior exactly: every cold fingerprint
      compiles inline.  On, a fingerprint that misses ALL THREE AOT
      rungs (memory LRU, disk, cluster artifact store) is served by the
      no-compile numpy interpreter immediately when its plan shape is
      inside the interpreter's declared coverage, while the background
      compiler promotes it off-thread.
    - `hot_threshold`: interpreted executions of one fingerprint before
      the background compiler is asked to promote it.  1 promotes on
      first sight (bench/prewarm-adjacent workloads); higher values
      keep one-shot ad-hoc shapes from burning compile capacity.
    - `queue_depth`: bound on the background-compiler work queue.
      Enqueues past it are dropped (the fingerprint re-arms on a later
      interpreted run) — promotion is an optimization, never backlog.
    - `prewarm_capture`: path to an exported workload capture (JSONL,
      `yt workload capture` shape); daemon startup replays it through
      compile-only prewarm so a restarted daemon joins hot.  None skips
      the startup prewarm."""

    enabled = param(False, type=bool)
    hot_threshold = param(2, type=int, ge=1)
    queue_depth = param(64, type=int, ge=1)
    prewarm_capture = param(None, type=str)


_TIERING_CONFIG: "Optional[TieringConfig]" = None


def tiering_config() -> TieringConfig:
    global _TIERING_CONFIG
    if _TIERING_CONFIG is None:
        _TIERING_CONFIG = TieringConfig()
    return _TIERING_CONFIG


def set_tiering_config(config: "Optional[TieringConfig]") -> None:
    """Install a process-wide tiering config (None restores defaults)."""
    global _TIERING_CONFIG
    _TIERING_CONFIG = config


class ViewsConfig(YsonStruct):
    """Continuous-query (materialized view) plane knobs (ISSUE 13,
    query/views.py + server/view_daemon.py):

    - `enable`: master switch for the view daemon's refresh loop — off
      pauses EVERY view (dynamic-config brown-out lever; the committed
      offset cursors make resume lossless).
    - `poll_interval`: daemon sleep between passes over the registry
      when every view is drained.
    - `default_batch_rows`: micro-batch size for views created without
      an explicit one.  Batches pad to the pow2 capacity bucket, so the
      steady-state loop replays one compiled program per view.
    - `max_batches_per_pass`: per-view cap on batches drained in one
      daemon pass (fairness across views; 0 = drain to the head).
    - `lag_slo_rows`: the freshness-lag objective — each refresh pass
      votes the per-view `/views/lag_ok` vs `/views/lag_breach`
      counters against it, the SLI pair the view-lag burn-rate SLO
      (`view_lag_slo()`) evaluates over the history rings.
    - `paused`: view names force-paused by dynamic config (additive to
      per-view `yt view pause` registry state)."""

    enable = param(True, type=bool)
    poll_interval = param(0.05, type=float, ge=0.0)
    default_batch_rows = param(1024, type=int, ge=1)
    max_batches_per_pass = param(64, type=int, ge=0)
    lag_slo_rows = param(65536, type=int, ge=0)
    paused = param(default_factory=list, type=list)


_VIEWS_CONFIG: "Optional[ViewsConfig]" = None


def views_config() -> ViewsConfig:
    global _VIEWS_CONFIG
    if _VIEWS_CONFIG is None:
        _VIEWS_CONFIG = ViewsConfig()
    return _VIEWS_CONFIG


def set_views_config(config: "Optional[ViewsConfig]") -> None:
    """Install a process-wide views config (None restores defaults)."""
    global _VIEWS_CONFIG
    _VIEWS_CONFIG = config


def view_lag_slo(view: "Optional[str]" = None,
                 objective: float = 0.99,
                 burn_threshold: float = 10.0,
                 fast_window: float = 300.0,
                 slow_window: float = 3600.0) -> SloConfig:
    """The view-freshness SLO spec (ISSUE 13 satellite): a ratio SLI
    over the per-view lag vote counters — `objective` of refresh passes
    must meet the configured `lag_slo_rows` freshness bound.  Evaluated
    by utils/slo.SloTracker over the telemetry history rings with the
    standard fast+slow burn-rate windows; `view=None` sums every view's
    series (the fleet-wide objective)."""
    return SloConfig(
        kind="ratio", good_sensor="/views/lag_ok",
        bad_sensor="/views/lag_breach",
        tags={"view": view} if view else {},
        objective=objective, burn_threshold=burn_threshold,
        fast_window=fast_window, slow_window=slow_window)


class FailpointsConfig(YsonStruct):
    """Deterministic fault-injection schedule (utils/failpoints.py):
    `spec` uses the YT_FAILPOINTS syntax, `seed` fixes p-based rolls.
    Applied with `failpoints.configure(cfg)`; spawned daemons arm from
    the YT_FAILPOINTS / YT_FAILPOINTS_SEED environment instead."""

    spec = param("", type=str)
    seed = param(0, type=int)


class SanitizerConfig(YsonStruct):
    """Runtime concurrency sanitizer (utils/sanitizers.py): the
    instrumented-lock layer recording held-lock sets, acquisition-order
    edges, lock-order inversions, hold-budget violations, and blocking
    operations under hot-path locks.  Disabled by default — the
    registration helper then hands out PLAIN `threading.Lock`s (zero
    wrappers, zero per-acquire cost; `bench.py --config
    sanitizer_overhead` asserts it).  Enablement applies to locks
    created AFTER `sanitizers.configure(cfg)` runs (or set
    YT_TPU_SANITIZE=1 before the process constructs its daemons, the
    tests/conftest pattern)."""

    enabled = param(False, type=bool)
    # A registered hot lock held longer than this is a violation
    # (counted + bounded-reported, never fatal: the serving plane keeps
    # serving while operators read /sanitizer).
    hold_budget_seconds = param(0.25, type=float, ge=0.0)


def sanitizer_config() -> SanitizerConfig:
    return _sanitizer_config if _sanitizer_config is not None \
        else SanitizerConfig()


def set_sanitizer_config(config: "Optional[SanitizerConfig]") -> None:
    """Install + APPLY a sanitizer config (None restores the defaults —
    disabled — matching the other setters' convention; the env gate
    YT_TPU_SANITIZE is independent and wins when set)."""
    global _sanitizer_config
    _sanitizer_config = config
    from ytsaurus_tpu.utils import sanitizers
    sanitizers.configure(config if config is not None
                         else SanitizerConfig())


_sanitizer_config: "Optional[SanitizerConfig]" = None


class RpcConfig(YsonStruct):
    bind_host = param("127.0.0.1", type=str)
    port = param(0, type=int, ge=0, le=65535)
    max_workers = param(16, type=int, ge=1)
    call_timeout = param(30.0, type=float, ge=0.0)
    retry_attempts = param(2, type=int, ge=1)
    retry_backoff = param(0.1, type=float, ge=0.0)


class ChunkStoreConfig(YsonStruct):
    cache_capacity_bytes = param(1 << 30, type=int, ge=0)
    replication_factor = param(2, type=int, ge=1)
    erasure_codec = param("none", type=str,
                          choices={"none", "rs_6_3", "rs_3_2"})


class MasterConfig(YsonStruct):
    snapshot_every = param(1024, type=int, ge=1)
    journal_nodes = param(2, type=int, ge=0)
    bootstrap_timeout = param(60.0, type=float, ge=0.0)


class SchedulerConfig(YsonStruct):
    fair_share_update_period = param(0.1, type=float, ge=0.0)
    max_running_jobs = param(8, type=int, ge=1)
    speculative_after = param(5.0, type=float, ge=0.0)


class ServingConfig(YsonStruct):
    """Query serving plane knobs (query/serving.py QueryGateway):
    admission control (weighted per-pool concurrency slots over a bounded
    wait queue), deadline propagation, and continuous micro-batching of
    lookups.  Ref shape: the reference query service's in-flight window
    + lookup sessions (query_agent/query_service.cpp)."""

    enabled = param(True, type=bool)
    # Total concurrent query slots, shared by every pool under fair-share
    # admission (ISSUE 17): min-share guarantees first, then weight-
    # proportional water filling capped by live demand — the scalar
    # collapse of vector HDRF (operations/fair_share.py).
    slots = param(16, type=int, ge=1)
    # pool name -> weight; pools not listed here use default_pool's slots.
    pools = param(default_factory=lambda: {"default": 1.0}, type=dict)
    default_pool = param("default", type=str)
    # pool name -> guaranteed share of `slots` in [0, 1] (vector-HDRF
    # min_share_ratio): honored before weight-proportional filling, so
    # an idle pool's guarantee survives a neighbor's storm.
    min_shares = param(default_factory=dict, type=dict)
    # pool name -> hard cap on concurrently running queries (fair share
    # never raises a pool past its cap).
    pool_limits = param(default_factory=dict, type=dict)
    # Brown-out ladder (ISSUE 17): under sustained overload reads degrade
    # explicitly — rung 0 full execution, rung 1 bounded-staleness
    # snapshot-cache reads, rung 2 reject-with-retry_after.  The signal
    # is estimated queue drain time: total_waiting * hold_ewma / slots
    # (queue depth AND observed drain rate in one number).  Rungs step
    # UP immediately and step DOWN one at a time, only after
    # `brownout_min_dwell_seconds` in the rung with the signal below
    # `threshold * brownout_hysteresis` — no flapping at the boundary.
    brownout_enabled = param(True, type=bool)
    brownout_rung1_seconds = param(0.5, type=float, ge=0.0)
    brownout_rung2_seconds = param(2.0, type=float, ge=0.0)
    brownout_hysteresis = param(0.5, type=float, ge=0.0, le=1.0)
    brownout_min_dwell_seconds = param(1.0, type=float, ge=0.0)
    # pool name -> max staleness (seconds) a rung-1 degraded read may
    # serve from the tablet snapshot cache; pools absent here use
    # `default_staleness_seconds`.  0 opts the pool out of degradation
    # (its reads stay full-execution until rung 2 sheds them).
    staleness_bounds = param(default_factory=dict, type=dict)
    default_staleness_seconds = param(5.0, type=float, ge=0.0)
    # Admitted-but-waiting requests per pool; overflow => ThrottledError.
    max_queue = param(128, type=int, ge=0)
    # Deadline applied when the caller passes none (0 = no deadline).
    default_timeout = param(30.0, type=float, ge=0.0)
    # Lookup micro-batching: requests against one (table, timestamp)
    # coalesce inside this window, up to max_batch_size keys.
    flush_window_ms = param(2.0, type=float, ge=0.0)
    max_batch_size = param(1024, type=int, ge=1)
    # Pow2 floor for the batched chunk probe's key (needle) arrays
    # (tablet._pad_needles): bounds the spectrum of gather shapes so a
    # shape-keyed compiled-gather cache stays bounded.
    min_bucket = param(8, type=int, ge=1)
    # Parallel per-tablet fan-out width for one batched read.
    max_tablet_fanout = param(8, type=int, ge=1)

    def postprocess(self):
        # YSON-loaded maps may carry bytes keys; pool names are strings.
        self.pools = {
            (k.decode("utf-8") if isinstance(k, bytes) else k): v
            for k, v in (self.pools or {}).items()}
        for name, weight in self.pools.items():
            if isinstance(weight, bool) or \
                    not isinstance(weight, (int, float)) or weight < 0:
                raise YtError(
                    f"Serving pool {name!r}: weight must be a "
                    f"non-negative number, got {weight!r}",
                    code=EErrorCode.InvalidConfig)
        if self.default_pool not in self.pools:
            raise YtError(
                f"Serving default_pool {self.default_pool!r} is not in "
                f"pools {sorted(self.pools)!r}",
                code=EErrorCode.InvalidConfig)
        self.min_shares = {
            (k.decode("utf-8") if isinstance(k, bytes) else k): v
            for k, v in (self.min_shares or {}).items()}
        for name, ratio in self.min_shares.items():
            if isinstance(ratio, bool) or \
                    not isinstance(ratio, (int, float)) or \
                    not 0.0 <= ratio <= 1.0:
                raise YtError(
                    f"Serving pool {name!r}: min_share must be in "
                    f"[0, 1], got {ratio!r}", code=EErrorCode.InvalidConfig)
        if sum(self.min_shares.values()) > 1.0 + 1e-9:
            raise YtError(
                f"Serving min_shares sum to "
                f"{sum(self.min_shares.values()):.3f} > 1.0 — the "
                f"guarantees are not satisfiable",
                code=EErrorCode.InvalidConfig)
        self.pool_limits = {
            (k.decode("utf-8") if isinstance(k, bytes) else k): v
            for k, v in (self.pool_limits or {}).items()}
        for name, limit in self.pool_limits.items():
            if isinstance(limit, bool) or not isinstance(limit, int) \
                    or limit < 1:
                raise YtError(
                    f"Serving pool {name!r}: pool_limit must be a "
                    f"positive int, got {limit!r}",
                    code=EErrorCode.InvalidConfig)
        self.staleness_bounds = {
            (k.decode("utf-8") if isinstance(k, bytes) else k): v
            for k, v in (self.staleness_bounds or {}).items()}
        for name, bound in self.staleness_bounds.items():
            if isinstance(bound, bool) or \
                    not isinstance(bound, (int, float)) or bound < 0:
                raise YtError(
                    f"Serving pool {name!r}: staleness bound must be a "
                    f"non-negative number, got {bound!r}",
                    code=EErrorCode.InvalidConfig)
        if self.brownout_rung2_seconds < self.brownout_rung1_seconds:
            raise YtError(
                "Serving brownout_rung2_seconds must be >= "
                "brownout_rung1_seconds",
                code=EErrorCode.InvalidConfig)


class DaemonConfig(YsonStruct):
    """Top-level daemon config (`--config file.yson`)."""

    role = param("primary", type=str, choices={"primary", "node", "proxy"})
    root = param(None, type=str)
    rpc = param(type=RpcConfig)
    chunk_store = param(type=ChunkStoreConfig)
    master = param(type=MasterConfig)
    scheduler = param(type=SchedulerConfig)
    serving = param(type=ServingConfig)
    tablet = param(type=TabletConfig)
    tracing = param(type=TracingConfig)
    telemetry = param(type=TelemetryConfig)
    workload = param(type=WorkloadConfig)
    compile = param(type=CompileConfig)
    tiering = param(type=TieringConfig)
    sanitizer = param(type=SanitizerConfig)

    def postprocess(self):
        if self.role == "node" and self.chunk_store.replication_factor < 1:
            raise YtError("node role requires replication_factor >= 1",
                          code=EErrorCode.InvalidConfig)

    @classmethod
    def load(cls, path: str) -> "DaemonConfig":
        from ytsaurus_tpu import yson
        with open(path, "rb") as f:
            return cls.from_dict(yson.loads(f.read()))


# ---------------------------------------------------------------------------
# Dynamic config manager
# ---------------------------------------------------------------------------

class DynamicConfigManager:
    """Polls a Cypress document for config patches and applies them.

    Ref: library/dynamic_config/dynamic_config_manager.h:23 — the manager
    periodically fetches `//sys/<component>/@config`-style state, validates
    the merged config, fires subscriber callbacks on change, and keeps
    serving the last good config when a bad patch lands (the error is
    logged + exported via `last_error`).
    """

    def __init__(self, fetch: Callable[[], Optional[dict]],
                 base_config: YsonStruct, period: float = 1.0):
        self._fetch = fetch
        self._base = base_config
        self._period = period
        self._lock = threading.Lock()
        self._current = base_config
        self._last_patch: Optional[dict] = None
        self.last_error: Optional[YtError] = None
        self.update_count = 0
        self._subscribers: list[Callable[[YsonStruct], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def config(self) -> YsonStruct:
        with self._lock:
            return self._current

    def subscribe(self, callback: Callable[[YsonStruct], None]) -> None:
        self._subscribers.append(callback)

    def poll_once(self) -> bool:
        """One fetch+merge+apply cycle; True if the config changed."""
        try:
            patch = self._fetch()
        except Exception as exc:   # noqa: BLE001 — fetch is an RPC boundary;
            # the poll loop must survive transport/teardown errors.
            self.last_error = exc if isinstance(exc, YtError) else \
                YtError(f"dynamic config fetch failed: {exc!r}")
            return False
        if patch == self._last_patch:
            return False
        try:
            new_config = self._base.merge(patch)
        except YtError as exc:
            # Keep the last good config; surface the failure.
            self.last_error = exc
            logger.warning("rejecting dynamic config patch: %s", exc)
            return False
        self._last_patch = patch
        self.last_error = None
        with self._lock:
            if new_config == self._current:
                return False
            self._current = new_config
        self.update_count += 1
        for callback in self._subscribers:
            try:
                callback(new_config)
            except Exception as exc:   # noqa: BLE001 — subscriber boundary
                logger.error("dynamic config subscriber failed: %r", exc)
        return True

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dynamic-config")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._period):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
