"""Backend selection helper for driver entry points.

A dead TPU tunnel HANGS backend initialization (it does not raise), so the
health probe runs `jax.devices()` in a subprocess with a timeout before this
process touches backends; on failure the process falls back to CPU with a
stderr notice so results are never silently mislabeled.

The probe RETRIES with escalating per-attempt timeouts across a window
(round-2 lesson: one 180s shot gives a flaky tunnel a single chance to ruin
the round's artifact — a tunnel that flaps for 60s and recovers should
still land on the accelerator).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_PROBED = False

# Early-attempt timeouts; short first so a healthy tunnel answers in
# seconds and a flapping one gets quick retries.  The FINAL attempt uses
# the whole remaining window, so a slow-but-alive tunnel (answers in,
# say, 130s) still lands on the accelerator instead of being cut off by
# escalation steps.
_ATTEMPT_TIMEOUTS = (30.0, 60.0)


def _probe_once(timeout: float) -> "tuple[bool, str]":
    """(ok, reason). Runs `jax.devices()` in a throwaway subprocess."""
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, check=True, capture_output=True,
            env=dict(os.environ))
        return True, ""
    except subprocess.TimeoutExpired:
        return False, f"HUNG (> {timeout:.0f}s; dead tunnel?)"
    except subprocess.CalledProcessError as exc:
        tail = (exc.stderr or b"")[-800:].decode("utf-8", "replace")
        return False, f"FAILED; probe stderr tail:\n{tail}"
    except Exception as exc:  # pragma: no cover - defensive
        return False, f"errored ({exc!r})"


def ensure_backend(timeout: float = 120.0, window: float | None = None):
    """Returns the jax module with a usable backend selected.

    `timeout` caps a single probe attempt; `window` (default
    BENCH_PROBE_WINDOW env or 120s) caps the total time spent retrying
    before falling back to CPU.  The default stays at the round-2 probe
    budget so non-bench callers (e.g. the driver's compile-check entry)
    don't blow their own deadlines; bench.py opts into a longer window
    explicitly.
    """
    global _PROBED
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Even an explicit-CPU env can hang if an accelerator plugin was
        # pre-registered at interpreter start; pinning via jax.config takes
        # effect immediately in this process.
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax
    # A parent bench process already probed this tunnel and exported its
    # verdict: honor it instead of re-probing — a dead tunnel then costs
    # ONE fallback window for the whole bench invocation, not one per
    # spawned config child (BENCH_r05 probe-hang lesson).
    verdict = os.environ.get("YT_TPU_PROBE_VERDICT", "")
    if verdict == "cpu":
        print("# accelerator probe verdict inherited from parent: cpu",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax
    if verdict == "accel":
        _PROBED = True
    if not _PROBED:
        _PROBED = True
        if window is None:
            window = float(os.environ.get("BENCH_PROBE_WINDOW", 120.0))
        # A caller asking for a long single-probe timeout must get at
        # least that much total grace (the final attempt runs to the
        # window's end).
        window = max(window, timeout)
        deadline = time.monotonic() + window
        ok = False
        attempt = 0
        while True:
            remaining = max(deadline - time.monotonic(), 5.0)
            if attempt < len(_ATTEMPT_TIMEOUTS):
                per_attempt = min(_ATTEMPT_TIMEOUTS[attempt], timeout,
                                  remaining)
            else:
                per_attempt = remaining       # final attempt: all of it
            ok, reason = _probe_once(per_attempt)
            attempt += 1
            if ok:
                if attempt > 1:
                    print(f"# accelerator probe recovered on attempt "
                          f"{attempt}", file=sys.stderr)
                break
            print(f"# accelerator backend probe attempt {attempt} "
                  f"{reason}", file=sys.stderr)
            if time.monotonic() + 10.0 >= deadline:
                break
            time.sleep(min(5.0 * attempt, 20.0))
        if not ok:
            print(f"# accelerator backend unusable after {attempt} probe "
                  f"attempts in {window:.0f}s; falling back to CPU",
                  file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
    jax.devices()
    return jax
