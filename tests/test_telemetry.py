"""Cluster telemetry plane tests (ISSUE 6): metrics-history rings
(bounded memory, downsample tiers, window deltas), SLO burn-rate
alerting (fires on a synthetic SLI step, resolves on recovery),
per-tenant resource accounting (conservation under concurrent
mixed-pool traffic, exact reconciliation with gateway counters),
monitoring endpoints (/metrics/history /accounting /slo /telemetry
/cluster), the /cluster roll-up over a real 3-daemon LocalCluster,
the Summary bounded reservoir, the serving routing-signal gauges,
and the sensor-catalog lint."""

import json
import os
import threading
import time
import urllib.request

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.config import (
    ServingConfig,
    SloConfig,
    TelemetryConfig,
)
from ytsaurus_tpu.errors import ThrottledError, YtError
from ytsaurus_tpu.query.accounting import (
    USAGE_FIELDS,
    ResourceAccountant,
    get_accountant,
)
from ytsaurus_tpu.query.serving import CancellationToken, QueryGateway
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.server.monitoring import MonitoringServer
from ytsaurus_tpu.utils.profiling import (
    MetricsHistory,
    Profiler,
    ProfilerRegistry,
    Summary,
    TelemetrySampler,
    get_registry,
)
from ytsaurus_tpu.utils.slo import SloTracker

from tests.test_observability import parse_prometheus_exposition


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


# --- summary bounded reservoir ------------------------------------------------


def test_summary_reservoir_is_bounded():
    s = Summary()
    for i in range(50_000):
        s.record(float(i))
    assert s.count == 50_000 and s.max == 49_999.0
    # The reservoir — the only per-observation storage — stays capped.
    assert len(s._reservoir) == Summary.RESERVOIR_CAPACITY
    # Uniform sample of a uniform ramp: the median estimate must land
    # well inside the middle half.
    assert 12_500 < s.quantile(0.5) < 37_500
    assert s.quantile(0.0) < s.quantile(0.99)


# --- history rings ------------------------------------------------------------


def _make_history(registry, **kw):
    defaults = dict(fine_capacity=16, coarse_every=4, coarse_capacity=8,
                    sample_period=10.0)
    defaults.update(kw)
    return MetricsHistory(registry=registry, **defaults)


def test_history_ring_bounded_and_downsampled():
    reg = ProfilerRegistry()
    counter = Profiler("/t", registry=reg).counter("c")
    hist = _make_history(reg)
    t0 = 1_000.0
    for i in range(100):
        counter.increment()
        hist.sample_once(t0 + 10.0 * i)
    (series,) = hist.query(name="/t/c")
    # Fine tier: exactly fine_capacity newest points survive.
    assert len(series["points"]) == 16
    assert series["points"][-1] == [t0 + 990.0, 100.0]
    assert series["points"][0] == [t0 + 840.0, 85.0]
    # Coarse tier: every coarse_every-th sample, capacity-bounded.
    (coarse,) = hist.query(name="/t/c", tier="coarse")
    assert len(coarse["points"]) == 8
    stamps = [p[0] for p in coarse["points"]]
    assert stamps == [t0 + 10.0 * (4 * k - 1) for k in range(18, 26)]


def test_history_query_filters_and_since():
    reg = ProfilerRegistry()
    prof = Profiler("/q", registry=reg)
    prof.with_tags(pool="a").counter("n").increment(1)
    prof.with_tags(pool="b").counter("n").increment(2)
    prof.gauge("g").set(7.0)
    hist = _make_history(reg)
    hist.sample_once(100.0)
    hist.sample_once(110.0)
    assert {s["name"] for s in hist.query()} == {"/q/n", "/q/g"}
    (only_b,) = hist.query(name="/q/n", tags={"pool": "b"})
    assert only_b["tags"] == {"pool": "b"}
    assert [p[1] for p in only_b["points"]] == [2.0, 2.0]
    (late,) = hist.query(name="/q/g", since=100.0)
    assert [p[0] for p in late["points"]] == [110.0]
    assert hist.series_names() == ["/q/g", "/q/n"]


def test_window_delta_per_kind():
    reg = ProfilerRegistry()
    prof = Profiler("/w", registry=reg)
    counter = prof.counter("c")
    gauge = prof.gauge("g")
    summary = prof.summary("s")
    histo = prof.histogram("h", bounds=(0.1, 1.0))
    hist = _make_history(reg, fine_capacity=64)
    for i in range(10):
        counter.increment(5)
        gauge.set(float(i))
        summary.record(2.0)
        histo.record(0.05 if i < 5 else 5.0)
        hist.sample_once(100.0 + 10.0 * i)
    now = 190.0
    assert hist.window_delta("/w/c", window=50.0, now=now) == 25.0
    assert hist.window_delta("/w/g", window=50.0, now=now) == 9.0
    d_count, d_sum = hist.window_delta("/w/s", window=50.0, now=now)
    assert (d_count, d_sum) == (5, 10.0)
    d_count, d_sum, d_buckets, bounds = hist.window_delta(
        "/w/h", window=50.0, now=now)
    assert d_count == 5 and bounds == (0.1, 1.0)
    assert d_buckets == [0, 0, 5]          # all five landed above 1.0
    # Counter deltas SUM over matching tagged series.
    tagged = Profiler("/w2", registry=reg)
    tagged.with_tags(pool="a").counter("n").increment(3)
    tagged.with_tags(pool="b").counter("n").increment(4)
    hist2 = _make_history(reg)
    hist2.sample_once(10.0)
    tagged.with_tags(pool="a").counter("n").increment(3)
    hist2.sample_once(20.0)
    assert hist2.window_delta("/w2/n", window=15.0, now=20.0) == 3.0
    # No matching series / single point -> None.
    assert hist2.window_delta("/nope", window=15.0, now=20.0) is None


def test_sampler_thread_ticks_and_stops():
    reg = ProfilerRegistry()
    Profiler("/bg", registry=reg).counter("c").increment()
    hist = _make_history(reg, sample_period=0.02)
    ticks = []
    sampler = TelemetrySampler(hist, period=0.02,
                               hooks=[ticks.append]).start()
    deadline = time.monotonic() + 5.0
    while hist.samples_taken < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    sampler.stop()
    assert hist.samples_taken >= 3 and len(ticks) >= 3
    taken = hist.samples_taken
    time.sleep(0.08)
    assert hist.samples_taken == taken     # stopped means stopped


# --- slo burn-rate alerting ---------------------------------------------------


def _slo_config(**slos):
    return TelemetryConfig.from_dict({"slos": slos})


def test_burn_rate_alert_fires_and_resolves_on_step():
    reg = ProfilerRegistry()
    prof = Profiler("/svc", registry=reg)
    good, bad = prof.counter("ok"), prof.counter("err")
    hist = _make_history(reg, fine_capacity=720)
    cfg = _slo_config(availability={
        "kind": "availability", "good_sensor": "/svc/ok",
        "bad_sensor": "/svc/err", "objective": 0.99,
        "fast_window": 300.0, "slow_window": 3600.0,
        "burn_threshold": 2.0})
    tracker = SloTracker(cfg, history=hist)
    t = 0.0
    for _ in range(60):                     # healthy baseline
        good.increment(100)
        t = hist.sample_once(t + 10.0)
    snap = tracker.evaluate(now=t)
    assert snap["slos"]["availability"]["firing"] is False
    assert snap["active_alerts"] == []

    for _ in range(30):                     # SLI step: 1/3 errors
        good.increment(100)
        bad.increment(50)
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    snap = tracker.evaluate(now=t)
    state = snap["slos"]["availability"]
    assert state["firing"] is True
    assert state["burn_fast"] > 2.0 and state["burn_slow"] > 2.0
    (alert,) = snap["active_alerts"]
    assert alert["slo"] == "availability" and alert["state"] == "firing"
    since = alert["since"]

    for _ in range(31):                     # recovery: fast window heals
        good.increment(100)
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    snap = tracker.evaluate(now=t)
    assert snap["active_alerts"] == []
    assert any(a["slo"] == "availability" and a["state"] == "resolved"
               and a["since"] == since and "resolved_at" in a
               for a in snap["resolved_alerts"])


def test_latency_slo_over_histogram_buckets():
    reg = ProfilerRegistry()
    lat = Profiler("/svc", registry=reg).histogram(
        "latency_seconds", bounds=(0.01, 0.05, 0.5))
    hist = _make_history(reg, fine_capacity=720)
    cfg = _slo_config(p99={
        "kind": "latency", "sensor": "/svc/latency_seconds",
        "objective": 0.9, "bound_ms": 50.0,
        "fast_window": 300.0, "slow_window": 600.0,
        "burn_threshold": 2.0})
    tracker = SloTracker(cfg, history=hist)
    t = 0.0
    for _ in range(60):
        for _ in range(10):
            lat.record(0.005)               # all under the 50ms bound
        t = hist.sample_once(t + 10.0)
    assert tracker.evaluate(now=t)["slos"]["p99"]["firing"] is False
    for _ in range(30):                     # regression: half over bound
        for _ in range(5):
            lat.record(0.005)
        for _ in range(5):
            lat.record(2.0)
        t = hist.sample_once(t + 10.0)
    state = tracker.evaluate(now=t)["slos"]["p99"]
    assert state["firing"] is True
    assert state["error_rate_fast"] == pytest.approx(0.5)


def test_slo_config_validation():
    with pytest.raises(YtError):
        SloConfig.from_dict({"kind": "latency", "bound_ms": 0.0})
    with pytest.raises(YtError):
        SloConfig.from_dict({"kind": "availability",
                             "good_sensor": "/a"})
    with pytest.raises(YtError):
        TelemetryConfig.from_dict({"slos": {"x": 3}})
    cfg = _slo_config(ok={"kind": "ratio", "good_sensor": "/g",
                          "bad_sensor": "/b", "objective": 0.999})
    assert cfg.slos["ok"].objective == 0.999
    assert cfg.to_dict()["slos"]["ok"]["good_sensor"] == "/g"


# --- per-tenant accounting ----------------------------------------------------


def test_accounting_conservation_under_concurrent_folds():
    reg = ProfilerRegistry()
    acct = ResourceAccountant(registry=reg)
    pools = ["p0", "p1", "p2", "p3"]
    users = ["u0", "u1", "u2"]
    n_threads, folds_each = 8, 200

    def worker(seed):
        for i in range(folds_each):
            acct.fold(pools[(seed + i) % 4], users[i % 3],
                      queries=1, rows_read=i, bytes_read=2 * i,
                      wall_seconds=0.001)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = acct.snapshot()
    n_folds = n_threads * folds_each
    per_fold_rows = sum(range(folds_each)) * n_threads
    assert snap["totals"]["queries"] == n_folds
    assert snap["totals"]["rows_read"] == per_fold_rows
    assert snap["totals"]["bytes_read"] == 2 * per_fold_rows
    # Conservation: per-pool and per-user roll-ups both sum to totals.
    for roll in ("by_pool", "by_user"):
        for field in USAGE_FIELDS:
            assert sum(r[field] for r in snap[roll].values()) == \
                pytest.approx(snap["totals"][field]), (roll, field)
    # The per-pool sensor mirrors agree exactly with the roll-up.
    for pool, agg in snap["by_pool"].items():
        for field in ("queries", "rows_read", "bytes_read"):
            sensor = Profiler("/accounting/usage",
                              registry=reg).with_tags(
                pool=pool).counter(field)
            assert sensor.get() == pytest.approx(agg[field])


def test_admission_throttle_folds_into_accounting():
    acct = get_accountant()
    before = (acct.snapshot()["by_pool"].get("default") or
              {"throttled": 0.0})["throttled"]
    gateway = QueryGateway(ServingConfig(slots=1, max_queue=0,
                                         default_timeout=5.0))
    release = threading.Event()
    started = threading.Event()

    def hold(_token):
        started.set()
        release.wait(10.0)
        return "held"

    holder = threading.Thread(
        target=lambda: gateway.run_select(hold, timeout=10.0))
    holder.start()
    try:
        assert started.wait(5.0)
        with pytest.raises(ThrottledError):
            gateway.run_select(lambda _t: "nope", timeout=1.0)
    finally:
        release.set()
        holder.join()
    after = acct.snapshot()["by_pool"]["default"]["throttled"]
    assert after == before + 1


N_ROWS = 120


@pytest.fixture(scope="module")
def client(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("telemetry")
    c = connect(str(tmp_path / "cluster"))
    c.cluster.serving_config = ServingConfig(
        slots=8, pools={"default": 1.0, "gold": 1.0, "silver": 1.0})
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    c.create("table", "//acct",
             attributes={"schema": schema, "dynamic": True},
             recursive=True)
    c.mount_table("//acct")
    c.insert_rows("//acct", [{"k": i, "v": i * 3}
                             for i in range(N_ROWS)])
    return c


def _pool_usage(pool):
    return (get_accountant().snapshot()["by_pool"].get(pool) or
            {field: 0.0 for field in USAGE_FIELDS})


def test_accounting_reconciles_with_gateway_counters(client):
    """The acceptance invariant: per-pool accounting totals reconcile
    EXACTLY with the gateway's own admission counters and with the
    per-query profiles, under concurrent mixed-pool traffic."""
    gateway = client.cluster.gateway
    pools = gateway.admission._pools
    before = {
        "gold": _pool_usage("gold"), "silver": _pool_usage("silver"),
        "gold_admitted": pools["gold"].admitted_n,
        "silver_admitted": pools["silver"].admitted_n,
    }
    profiles = {"gold": [], "silver": []}
    lock = threading.Lock()

    def select_worker(pool, n):
        for i in range(n):
            p = client.select_rows(
                f"select k, v from [//acct] where k < {20 + i}",
                pool=pool, explain_analyze=True)
            with lock:
                profiles[pool].append(p)

    def lookup_worker(pool, n):
        for i in range(n):
            rows = client.lookup_rows("//acct", [(i,), (i + 1,)],
                                      pool=pool)
            assert rows[0]["v"] == i * 3

    threads = [
        threading.Thread(target=select_worker, args=("gold", 4)),
        threading.Thread(target=select_worker, args=("silver", 3)),
        threading.Thread(target=lookup_worker, args=("gold", 5)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    gold, silver = _pool_usage("gold"), _pool_usage("silver")
    assert gold["queries"] - before["gold"]["queries"] == 4
    assert silver["queries"] - before["silver"]["queries"] == 3
    # Every member REQUEST folds (5 calls), however they coalesced.
    assert gold["lookups"] - before["gold"]["lookups"] == 5
    assert gold["lookup_batches"] - before["gold"]["lookup_batches"] >= 1
    assert gold["lookup_keys"] - before["gold"]["lookup_keys"] == 10
    assert gold["lookup_rows_found"] - \
        before["gold"]["lookup_rows_found"] == 10
    # Exact per-pool reconciliation against the per-query profiles.
    for pool, n_queries in (("gold", 4), ("silver", 3)):
        usage, base = _pool_usage(pool), before[pool]
        for field, attr in (("rows_read", "rows_read"),
                            ("rows_written", "rows_written")):
            assert usage[field] - base[field] == sum(
                p.statistics.get(attr, 0) for p in profiles[pool])
        assert usage["wall_seconds"] - base["wall_seconds"] == \
            pytest.approx(sum(p.wall_time for p in profiles[pool]))
        assert usage["compile_seconds"] - base["compile_seconds"] == \
            pytest.approx(sum(p.compile_time for p in profiles[pool]))
        assert all(p.pool == pool for p in profiles[pool])
    # Gateway-counter reconciliation: every admission in a pool is one
    # accounted query or one accounted lookup BATCH (the flush holds
    # the slot; member requests fold as `lookups` under their users).
    gold_admitted = pools["gold"].admitted_n - before["gold_admitted"]
    assert gold_admitted == \
        (gold["queries"] - before["gold"]["queries"]) + \
        (gold["lookup_batches"] - before["gold"]["lookup_batches"])
    silver_admitted = pools["silver"].admitted_n - \
        before["silver_admitted"]
    assert silver_admitted == silver["queries"] - \
        before["silver"]["queries"]


def test_unknown_pool_resolves_to_default_everywhere(client):
    """An unconfigured pool name lands on the default pool's slots —
    accounting, the profile, and the admission counters must all agree
    on that RESOLVED identity instead of inventing a phantom pool."""
    pools = client.cluster.gateway.admission._pools
    usage0 = _pool_usage("default")
    admitted0 = pools["default"].admitted_n
    profile = client.select_rows("select k from [//acct] where k < 4",
                                 pool="no_such_pool",
                                 explain_analyze=True)
    assert profile.pool == "default"
    assert _pool_usage("no_such_pool")["queries"] == 0
    assert _pool_usage("default")["queries"] - usage0["queries"] == 1
    assert pools["default"].admitted_n - admitted0 == 1


def test_profile_carries_user_and_pool(client):
    profile = client.select_rows("select k from [//acct] where k < 3",
                                 pool="gold", explain_analyze=True)
    assert profile.pool == "gold"
    assert profile.user == "root"
    assert profile.to_dict()["user"] == "root"
    from ytsaurus_tpu.query.profile import format_profile_dict
    assert "user: root" in format_profile_dict(profile.to_dict())


def test_evaluator_pool_tagged_compile_cache_counters(client):
    hits = Profiler("/query/compile_cache").with_tags(
        pool="gold").counter("hits")
    misses = Profiler("/query/compile_cache").with_tags(
        pool="gold").counter("misses")
    h0, m0 = hits.get(), misses.get()
    query = "select k, v from [//acct] where k < 77 order by k limit 5"
    client.select_rows(query, pool="gold")
    client.select_rows(query, pool="gold")
    assert misses.get() > m0                # first run compiled
    assert hits.get() > h0                  # second run hit the cache


def test_serving_routing_signal_gauges(client):
    client.select_rows("select k from [//acct] where k < 2",
                       pool="gold")
    series = parse_prometheus_exposition(
        get_registry().render_prometheus())
    by_name = {}
    for name, labels, value in series:
        by_name.setdefault(name, []).append((labels, value))
    # The hold EWMA is a real exported gauge now, seeded > 0.
    ((labels, value),) = by_name["serving_hold_ewma_seconds"]
    assert labels == {} and value > 0.0
    # Per-pool backlog gauges exist for every pool that admitted work.
    depth_pools = {l["pool"] for l, _v in
                   by_name.get("serving_queue_depth", [])}
    assert "gold" in depth_pools


def test_lookup_pool_tagged_tablet_counters(client):
    reads = Profiler("tablet/lookup").with_tags(
        pool="silver").counter("reads")
    keys = Profiler("tablet/lookup").with_tags(
        pool="silver").counter("keys")
    r0, k0 = reads.get(), keys.get()
    client.lookup_rows("//acct", [(5,), (6,), (7,)], pool="silver")
    assert reads.get() > r0
    assert keys.get() - k0 >= 3


# --- prometheus exposition satellites -----------------------------------------


def test_histogram_exposition_both_tag_arms():
    """+Inf bucket, _count and _sum render under the strict grammar for
    BOTH the tagged and the untagged sensor arm."""
    reg = ProfilerRegistry()
    prof = Profiler("/h", registry=reg)
    prof.histogram("plain", bounds=(0.1, 1.0)).record(0.5)
    prof.with_tags(pool="p").histogram(
        "tagged", bounds=(0.1, 1.0)).record(5.0)
    series = parse_prometheus_exposition(reg.render_prometheus())
    plain_buckets = {l["le"]: v for n, l, v in series
                     if n == "h_plain_bucket"}
    assert plain_buckets == {"0.1": 0, "1.0": 1, "+Inf": 1}
    tagged_buckets = {l["le"]: v for n, l, v in series
                      if n == "h_tagged_bucket"}
    assert tagged_buckets == {"0.1": 0, "1.0": 0, "+Inf": 1}
    assert all(l["pool"] == "p" for n, l, v in series
               if n.startswith("h_tagged"))
    flat = {(n, tuple(sorted(l.items()))): v for n, l, v in series}
    assert flat[("h_plain_count", ())] == 1
    assert flat[("h_plain_sum", ())] == 0.5
    assert flat[("h_tagged_count", (("pool", "p"),))] == 1
    assert flat[("h_tagged_sum", (("pool", "p"),))] == 5.0


# --- monitoring endpoints -----------------------------------------------------


def test_monitoring_telemetry_endpoints_roundtrip():
    reg = ProfilerRegistry()
    prof = Profiler("/ep", registry=reg)
    counter = prof.with_tags(pool="a").counter("n")
    hist = _make_history(reg)
    cfg = _slo_config(avail={
        "kind": "availability", "good_sensor": "/ep/n",
        "bad_sensor": "/ep/err", "objective": 0.99})
    tracker = SloTracker(cfg, history=hist)
    acct = ResourceAccountant(registry=reg)
    acct.fold("a", "alice", queries=2, rows_read=10)
    for i in range(5):
        counter.increment()
        hist.sample_once(100.0 + 10.0 * i)
    server = MonitoringServer(registry=reg, history=hist,
                              slo_tracker=tracker, accountant=acct)
    server.start()
    try:
        base = f"http://{server.address}"
        body = _get_json(f"{base}/metrics/history"
                         f"?name=/ep/n&tags=pool=a&since=110")
        (series,) = body["series"]
        assert series["kind"] == "counter"
        assert [p[1] for p in series["points"]] == [3.0, 4.0, 5.0]
        assert body["samples_taken"] == 5
        coarse = _get_json(f"{base}/metrics/history?tier=coarse")
        assert all(s["tier"] == "coarse" for s in coarse["series"])

        acct_body = _get_json(f"{base}/accounting")
        assert acct_body["totals"]["queries"] == 2.0
        assert acct_body["by_user"]["alice"]["rows_read"] == 10.0

        slo_body = _get_json(f"{base}/slo")
        assert "avail" in slo_body["slos"]

        summary = _get_json(f"{base}/telemetry")
        assert summary["address"] == server.address
        # The accountant's per-pool mirrors share the registry, so the
        # series list holds /ep/n plus the usage counters.
        assert "/ep/n" in summary["history"]["series_names"]
        assert "/accounting/usage/queries" in \
            summary["history"]["series_names"]
        assert summary["accounting"]["totals"]["queries"] == 2.0
    finally:
        server.stop()


def test_cluster_rollup_merges_members_and_tolerates_dead():
    def make_member(pool, firing):
        reg = ProfilerRegistry()
        hist = _make_history(reg)
        good = Profiler("/m", registry=reg).counter("ok")
        bad = Profiler("/m", registry=reg).counter("err")
        cfg = _slo_config(avail={
            "kind": "availability", "good_sensor": "/m/ok",
            "bad_sensor": "/m/err", "objective": 0.99,
            "burn_threshold": 2.0})
        tracker = SloTracker(cfg, history=hist)
        t = 0.0
        for _ in range(40):
            good.increment(10)
            if firing:
                bad.increment(10)
            t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
        acct = ResourceAccountant(registry=reg)
        acct.fold(pool, "u", queries=3, rows_read=100)
        server = MonitoringServer(registry=reg, history=hist,
                                  slo_tracker=tracker, accountant=acct)
        server.start()
        return server

    healthy = make_member("pa", firing=False)
    burning = make_member("pb", firing=True)
    healthy.cluster_members = lambda: [
        {"id": "self", "address": healthy.address,
         "attributes": {"role": "primary"}},
        {"id": "peer", "address": burning.address,
         "attributes": {"role": "node"}},
        {"id": "ghost", "address": "127.0.0.1:1"},
    ]
    try:
        body = _get_json(f"http://{healthy.address}/cluster")
        assert body["members"]["self"]["reachable"] is True
        assert body["members"]["peer"]["reachable"] is True
        assert body["members"]["ghost"]["reachable"] is False
        assert "ghost" in body["errors"]
        # Accounting totals sum across reachable members.
        assert body["accounting_totals"]["queries"] == 6.0
        assert body["accounting_totals"]["rows_read"] == 200.0
        # The burning member's alert surfaces fleet-wide, tagged.
        (alert,) = body["active_alerts"]
        assert alert["member"] == "peer" and alert["slo"] == "avail"
    finally:
        healthy.stop()
        burning.stop()


# --- /cluster over a real LocalCluster ----------------------------------------


@pytest.mark.slow
def test_cluster_rollup_over_three_daemon_cluster(tmp_path):
    """Full-suite variant: the real 3-daemon fleet (1 primary + 2 data
    nodes, ~19s spin-up).  Quick-tier sibling:
    test_cluster_rollup_merges_members_and_tolerates_dead covers the
    same aggregation logic over in-process members."""
    from ytsaurus_tpu.environment.local import LocalCluster

    with LocalCluster(str(tmp_path / "c"), n_nodes=2,
                      replication_factor=2) as cluster:
        root = os.path.join(str(tmp_path / "c"), "primary")
        with open(os.path.join(root, "primary.monitoring.port")) as f:
            base = f"http://127.0.0.1:{int(f.read())}"
        # Primary registers itself immediately; the two data nodes join
        # /daemons on their 2s heartbeat cadence.
        deadline = time.monotonic() + 30.0
        body = None
        while time.monotonic() < deadline:
            body = _get_json(f"{base}/cluster")
            reachable = [m for m in body["members"].values()
                         if m.get("reachable")]
            if len(reachable) >= 3:
                break
            time.sleep(0.5)
        assert body is not None
        reachable = {mid: m for mid, m in body["members"].items()
                     if m.get("reachable")}
        assert len(reachable) >= 3, body["members"].keys()
        roles = {m["attributes"].get("role")
                 for m in reachable.values() if m.get("attributes")}
        assert "primary" in roles and "node" in roles
        # Every member serves its own telemetry summary.
        for member in reachable.values():
            assert "slo" in member and "accounting" in member
        # The member monitoring endpoints serve history directly too.
        node = next(m for m in reachable.values()
                    if m["attributes"].get("role") == "node")
        hist = _get_json(f"http://{node['address']}/metrics/history")
        assert "series" in hist


# --- orchid + CLI surfaces ----------------------------------------------------


def test_orchid_telemetry_mounts():
    from ytsaurus_tpu.server.orchid import default_orchid
    get_accountant().fold("orchid_pool", "u", queries=1)
    from ytsaurus_tpu.utils.profiling import get_history
    get_history().sample_once()
    tree = default_orchid()
    dump = tree.get("/telemetry/history")
    assert dump["samples_taken"] >= 1 and dump["series"]
    snap = tree.get("/accounting")
    assert "orchid_pool" in snap["by_pool"]
    assert isinstance(tree.get("/telemetry/slo"), dict)


def test_yt_top_formatting():
    from ytsaurus_tpu.cli import _format_top
    acct = ResourceAccountant(registry=ProfilerRegistry())
    acct.fold("gold", "alice", queries=5, rows_read=1000,
              wall_seconds=2.5, bytes_read=5_000_000)
    acct.fold("silver", "bob", queries=1, rows_read=10,
              wall_seconds=9.0)
    text = _format_top(acct.snapshot(), by="pool",
                       sort_key="wall_seconds", limit=20)
    lines = text.splitlines()
    assert lines[0].split()[0] == "pool"
    # Sorted by wall seconds descending: silver first.
    assert lines[1].split()[0] == "silver"
    assert lines[2].split()[0] == "gold"
    assert lines[-1].split()[0] == "TOTAL"
    assert "5.0MB" in lines[2]              # bytes render human-readable
    by_user = _format_top(acct.snapshot(), by="user",
                          sort_key="queries", limit=1)
    assert by_user.splitlines()[1].split()[0] == "alice"
    assert len(by_user.splitlines()) == 3   # header + 1 row + TOTAL


def test_yt_top_fair_share_columns():
    """`yt top --by pool` overlays the admission controller's LIVE
    fair-share state (share/use/demand) on the usage history — a pool
    that is queued but has finished nothing still gets a row, and a
    pool the serving plane doesn't know renders '-' (ISSUE 17)."""
    from ytsaurus_tpu.cli import _format_top
    acct = ResourceAccountant(registry=ProfilerRegistry())
    acct.fold("prod", "alice", queries=5, wall_seconds=2.5)
    acct.fold("legacy", "bob", queries=1, wall_seconds=9.0)
    serving = {"gateways": [{"admission": {"pools": {
        "prod": {"fair_slots": 1.5, "in_flight": 1, "waiting": 0,
                 "demand": 1},
        "batch": {"fair_slots": 0.5, "in_flight": 1, "waiting": 40,
                  "demand": 41}}}}]}
    text = _format_top(acct.snapshot(), by="pool",
                       sort_key="wall_seconds", limit=0,
                       serving=serving)
    lines = text.splitlines()
    assert lines[0].split()[-3:] == ["share", "use", "demand"]
    rows = {line.split()[0]: line.split() for line in lines[1:]}
    assert rows["prod"][-3:] == ["1.50", "1", "1"]
    assert rows["batch"][-3:] == ["0.50", "1", "41"]   # queued-only pool
    assert rows["legacy"][-3:] == ["-", "-", "-"]      # no serving view
    assert rows["TOTAL"][-3:] == ["2.00", "2", "42"]
    # Without a serving snapshot the columns drop entirely.
    plain = _format_top(acct.snapshot(), by="pool",
                        sort_key="wall_seconds", limit=0)
    assert "share" not in plain.splitlines()[0]


# --- global wiring ------------------------------------------------------------


def test_set_telemetry_config_rebinds_running_sampler():
    """Reconfiguring a LIVE daemon must not orphan the sampler thread:
    the restarted sampler follows the NEW history rings."""
    from ytsaurus_tpu.config import set_telemetry_config
    from ytsaurus_tpu.utils import profiling

    def wait_samples(hist, n):
        deadline = time.monotonic() + 5.0
        while hist.samples_taken < n and time.monotonic() < deadline:
            time.sleep(0.01)
        return hist.samples_taken >= n

    try:
        set_telemetry_config(TelemetryConfig.from_dict(
            {"sample_period": 0.02}))
        assert profiling.start_telemetry() is not None
        old_hist = profiling.get_history()
        assert wait_samples(old_hist, 2)
        set_telemetry_config(TelemetryConfig.from_dict(
            {"sample_period": 0.02, "fine_capacity": 5}))
        new_hist = profiling.get_history()
        assert new_hist is not old_hist
        assert new_hist.fine_capacity == 5
        assert wait_samples(new_hist, 2)    # the restarted thread
    finally:
        set_telemetry_config(None)
        sampler = profiling._global_sampler
        if sampler is not None:
            sampler.stop()
            with profiling._history_lock:
                profiling._global_sampler = None


def test_set_telemetry_config_rebuilds_history():
    from ytsaurus_tpu.config import set_telemetry_config
    from ytsaurus_tpu.utils.profiling import get_history
    from ytsaurus_tpu.utils.slo import get_slo_tracker
    try:
        cfg = TelemetryConfig.from_dict({
            "fine_capacity": 7, "coarse_every": 2,
            "coarse_capacity": 3, "sample_period": 1.0,
            "slos": {"a": {"kind": "ratio", "good_sensor": "/g",
                           "bad_sensor": "/b"}}})
        set_telemetry_config(cfg)
        hist = get_history()
        assert hist.fine_capacity == 7 and hist.sample_period == 1.0
        assert "a" in get_slo_tracker().config.slos
    finally:
        set_telemetry_config(None)
        assert get_history().fine_capacity == 360


# --- sensor catalog lint ------------------------------------------------------


def _tools_check():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_sensor_catalog",
        os.path.join(repo, "tools", "check_sensor_catalog.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_sensor_catalog_is_clean():
    mod, repo = _tools_check()
    assert mod.check(repo) == []


def test_sensor_catalog_catches_renames(tmp_path):
    """Dropping a sensor from the catalog (≈ renaming it in code
    without updating the catalog) must fail the lint, as must leaving a
    stale entry behind."""
    mod, repo = _tools_check()
    with open(mod.CATALOG_PATH) as f:
        catalog = json.load(f)
    broken = {**catalog, "sensors": dict(catalog["sensors"])}
    del broken["sensors"]["/serving/hold_ewma_seconds"]
    broken["sensors"]["/serving/stale_gauge_nobody_creates"] = {
        "kind": "gauge", "tags": []}
    path = tmp_path / "catalog.json"
    path.write_text(json.dumps(broken))
    errors = mod.check(repo, str(path))
    assert any("hold_ewma_seconds" in e and "missing" in e
               for e in errors)
    assert any("stale" in e and "stale_gauge_nobody_creates" in e
               for e in errors)
