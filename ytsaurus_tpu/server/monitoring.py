"""Monitoring HTTP endpoint: /metrics (Prometheus), /orchid/...,
/healthz, /traces (query flight recorder).

Ref shape: library/profiling/solomon/exporter.h:25 — every daemon hosts a
pull endpoint the monitoring system scrapes; Orchid doubles as the
human-readable live-state browser.  stdlib http.server on a daemon thread
is plenty: scrape traffic is tiny and the handlers only read in-process
state.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.server.orchid import OrchidTree
from ytsaurus_tpu.utils.profiling import ProfilerRegistry, get_registry


class MonitoringServer:
    def __init__(self, orchid: Optional[OrchidTree] = None,
                 registry: Optional[ProfilerRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.orchid = orchid or OrchidTree()
        self.registry = registry or get_registry()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):      # silence stderr chatter
                pass

            def do_GET(self):
                try:
                    outer._handle(self)
                except (ConnectionError, BrokenPipeError):
                    pass
                except Exception as exc:   # noqa: BLE001 — one bad orchid
                    # producer must yield a diagnosable 500, not a dropped
                    # connection.
                    try:
                        outer._reply(self, 500, repr(exc).encode(),
                                     "text/plain")
                    except (ConnectionError, BrokenPipeError):
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="monitoring-http")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- request handling ------------------------------------------------------

    def _handle(self, request) -> None:
        path = request.path.split("?", 1)[0]
        if path == "/healthz":
            self._reply(request, 200, b"ok", "text/plain")
        elif path == "/failpoints":
            # Fault-injection observability (utils/failpoints.py): the
            # active schedule + cumulative per-site hit/trigger counters
            # (triggers also mirror into /metrics as failpoints_*).
            from ytsaurus_tpu.utils import failpoints
            body = json.dumps({
                "active_spec": failpoints.active_spec(),
                "schedule": failpoints.schedule_snapshot(),
                "sites": failpoints.counters(),
            }, indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/serving":
            # Query serving plane (query/serving.py): per-pool admission
            # state + lookup batching counters of every live gateway in
            # this process (histograms export via /metrics serving_*).
            from ytsaurus_tpu.query.serving import serving_snapshot
            body = json.dumps({"gateways": serving_snapshot()},
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/tablet":
            # Tablet read-path caches (tablet/tablet.py): process-wide
            # snapshot-cache hit/miss/evict counters + bytes pinned
            # (the raw sensors also render on /metrics as
            # tablet_snapshot_cache_*).
            from ytsaurus_tpu.tablet.tablet import snapshot_cache_stats
            body = json.dumps({"snapshot_cache": snapshot_cache_stats()},
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/traces" or path.startswith("/traces/"):
            # Query flight recorder (ISSUE 5): the listing serves recent
            # trace summaries + the bounded slow-query/recent profile
            # logs; /traces/<trace_id> renders that trace's span tree.
            from ytsaurus_tpu.query.profile import get_flight_recorder
            from ytsaurus_tpu.utils.tracing import span_tree, trace_summaries
            if path == "/traces":
                body = json.dumps({
                    "recent_traces": trace_summaries(),
                    **get_flight_recorder().snapshot(),
                }, indent=2, default=_json_default).encode()
                self._reply(request, 200, body, "application/json")
            else:
                trace_id = path[len("/traces/"):]
                tree = span_tree(trace_id)
                if not tree:
                    self._reply(request, 404, json.dumps(
                        {"error": f"no such trace {trace_id!r} "
                                  "(unsampled or evicted)"}).encode(),
                        "application/json")
                    return
                body = json.dumps({"trace_id": trace_id, "spans": tree},
                                  indent=2,
                                  default=_json_default).encode()
                self._reply(request, 200, body, "application/json")
        elif path in ("/metrics", "/solomon"):
            body = self.registry.render_prometheus().encode()
            self._reply(request, 200, body, "text/plain; version=0.0.4")
        elif path == "/orchid" or path.startswith("/orchid/"):
            sub = path[len("/orchid"):] or "/"
            try:
                value = self.orchid.get(sub)
            except YtError as err:
                self._reply(request, 404,
                            json.dumps(err.to_dict()).encode(),
                            "application/json")
                return
            body = json.dumps(value, default=_json_default,
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        else:
            self._reply(request, 404, b"not found", "text/plain")

    @staticmethod
    def _reply(request, status: int, body: bytes, ctype: str) -> None:
        request.send_response(status)
        request.send_header("Content-Type", ctype)
        request.send_header("Content-Length", str(len(body)))
        request.end_headers()
        request.wfile.write(body)


def _json_default(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)
