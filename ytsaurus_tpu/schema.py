"""Table schemas and the logical type system.

TPU-native analog of the reference's TTableSchema / TColumnSchema / logical types
(yt/yt/client/table_client/schema.h, logical_type.h).  Differences by design:

  * The physical representation is columnar-first: each logical type maps onto a
    fixed-width device plane dtype (see `device_dtype`) plus a validity mask.
    Strings are order-preserving dictionary-encoded (codes on device, vocabulary
    on host) so that comparisons / grouping / sorting run on the MXU/VPU over
    integer planes — the reference's pointer-rich TUnversionedValue row layout
    (unversioned_row.h:153) would defeat XLA's static-shape compilation model.
  * Schemas are immutable and hashable so they can key compilation caches.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Optional

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError


class EValueType(enum.Enum):
    """Logical value types (subset of ref logical_type.h ESimpleLogicalValueType).

    `null` is the type of the NULL literal; `any` holds arbitrary YSON values
    (kept host-side, excluded from device planes).
    """

    null = "null"
    int64 = "int64"
    uint64 = "uint64"
    double = "double"
    boolean = "boolean"
    string = "string"
    any = "any"

    @property
    def is_numeric(self) -> bool:
        return self in (EValueType.int64, EValueType.uint64, EValueType.double)

    @property
    def is_arithmetic(self) -> bool:
        return self.is_numeric

    @property
    def is_comparable(self) -> bool:
        return self is not EValueType.any


class VectorType:
    """Parametric fixed-width float vector type: `vector<float, N>`.

    Not an EValueType member (an enum cannot carry a per-column dim), but
    duck-types its API (`value`, `is_numeric`, `is_comparable`) so the flat
    name→type namespaces, `TableSchema.make((name, ty.value))` rebuilds and
    schema dict round-trips all preserve the dim without special-casing.
    Instances are INTERNED per dim so `a is b` works wherever code compares
    EValueType members by identity; the device plane is a contiguous
    `(capacity, dim)` float32 matrix plus the usual (capacity,) validity
    mask — the matmul-ready layout NEAREST distance passes scan.
    """

    __slots__ = ("dim",)
    _interned: "dict[int, VectorType]" = {}

    def __new__(cls, dim: int):
        dim = int(dim)
        if dim <= 0:
            raise YtError(f"Vector dim must be positive, got {dim}",
                          code=EErrorCode.QueryTypeError)
        cached = cls._interned.get(dim)
        if cached is None:
            cached = super().__new__(cls)
            object.__setattr__(cached, "dim", dim)
            cls._interned[dim] = cached
        return cached

    def __setattr__(self, name, value):
        raise AttributeError("VectorType is immutable")

    def __reduce__(self):
        return (VectorType, (self.dim,))

    @property
    def value(self) -> str:
        return f"vector<float,{self.dim}>"

    @property
    def name(self) -> str:
        return "vector"

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_arithmetic(self) -> bool:
        return False

    @property
    def is_comparable(self) -> bool:
        # No total order on vectors: ORDER BY / GROUP BY / key columns
        # reject them; NEAREST orders by a DISTANCE over them instead.
        return False

    def __repr__(self) -> str:
        return f"VectorType({self.dim})"

    def __hash__(self) -> int:
        return hash(("vector", self.dim))

    def __eq__(self, other) -> bool:
        return self is other


_VECTOR_TYPE_RE = re.compile(r"^vector\s*<\s*float\s*,\s*(\d+)\s*>$")


def parse_type(ty: "str | EValueType | VectorType") -> "EValueType | VectorType":
    """Parse a type spelling: EValueType values plus `vector<float,N>`."""
    if isinstance(ty, (EValueType, VectorType)):
        return ty
    m = _VECTOR_TYPE_RE.match(str(ty).strip())
    if m:
        return VectorType(int(m.group(1)))
    try:
        return EValueType(ty)
    except ValueError:
        raise YtError(f"Unknown column type {ty!r}",
                      code=EErrorCode.QueryTypeError)


_DEVICE_DTYPES = {
    EValueType.int64: np.int64,
    EValueType.uint64: np.uint64,
    EValueType.double: np.float64,
    EValueType.boolean: np.bool_,
    # Strings live on device as int32 order-preserving dictionary codes.
    EValueType.string: np.int32,
    # NULL literal columns carry no payload; use int8 zeros.
    EValueType.null: np.int8,
}


def device_dtype(ty: "EValueType | VectorType") -> np.dtype:
    """Physical dtype of the device plane backing a column of logical type `ty`."""
    if isinstance(ty, VectorType):
        # Fixed-width (capacity, dim) float32 matrix: the MXU-native
        # element type for the NEAREST distance matmul.
        return np.dtype(np.float32)
    if ty not in _DEVICE_DTYPES:
        raise YtError(f"Type {ty.value!r} has no device representation",
                      code=EErrorCode.QueryUnsupported)
    return np.dtype(_DEVICE_DTYPES[ty])


class SortOrder(enum.Enum):
    ascending = "ascending"
    descending = "descending"


@dataclass(frozen=True)
class ColumnSchema:
    """One column (ref: client/table_client/schema.h TColumnSchema)."""

    name: str
    type: "EValueType | VectorType"
    sort_order: Optional[SortOrder] = None
    required: bool = False
    expression: Optional[str] = None  # computed column (key evaluator)
    aggregate: Optional[str] = None   # aggregate column for dynamic tables
    lock: Optional[str] = None        # lock group for dynamic-table writes
    # Values >= this many bytes store out-of-row in hunk chunks
    # (ref TColumnSchema::MaxInlineHunkSize, client/table_client/schema.h).
    max_inline_hunk_size: Optional[int] = None

    def with_sort_order(self, order: Optional[SortOrder]) -> "ColumnSchema":
        return replace(self, sort_order=order)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name, "type": self.type.value}
        if self.sort_order is not None:
            d["sort_order"] = self.sort_order.value
        if self.required:
            d["required"] = True
        if self.expression is not None:
            d["expression"] = self.expression
        if self.aggregate is not None:
            d["aggregate"] = self.aggregate
        if self.lock is not None:
            d["lock"] = self.lock
        if self.max_inline_hunk_size is not None:
            d["max_inline_hunk_size"] = self.max_inline_hunk_size
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ColumnSchema":
        return cls(
            name=d["name"],
            type=parse_type(d["type"]),
            sort_order=SortOrder(d["sort_order"]) if d.get("sort_order") else None,
            required=bool(d.get("required", False)),
            expression=d.get("expression"),
            aggregate=d.get("aggregate"),
            lock=d.get("lock"),
            max_inline_hunk_size=d.get("max_inline_hunk_size"),
        )


@dataclass(frozen=True)
class TableSchema:
    """An ordered set of columns; key columns form a prefix with sort orders.

    Ref: client/table_client/schema.h TTableSchema.  `strict` means no columns
    outside the schema; `unique_keys` marks a sorted table whose key is unique
    (dynamic sorted tables require this).
    """

    columns: tuple[ColumnSchema, ...]
    strict: bool = True
    unique_keys: bool = False
    _by_name: dict[str, int] = field(default=None, repr=False, compare=False, hash=False)  # type: ignore

    def __post_init__(self):
        by_name: dict[str, int] = {}
        for i, col in enumerate(self.columns):
            if col.name in by_name:
                raise YtError(f"Duplicate column {col.name!r} in schema")
            by_name[col.name] = i
        # Key columns must form a prefix.
        seen_non_key = False
        for col in self.columns:
            if col.sort_order is None:
                seen_non_key = True
            elif seen_non_key:
                raise YtError(
                    f"Key column {col.name!r} appears after a non-key column")
            elif isinstance(col.type, VectorType):
                raise YtError(
                    f"Column {col.name!r} of type {col.type.value} cannot "
                    "be a key column (no total order on vectors)",
                    code=EErrorCode.QueryTypeError)
        object.__setattr__(self, "_by_name", by_name)

    # --- construction helpers -------------------------------------------------

    @classmethod
    def make(cls, columns: Iterable[ColumnSchema | tuple | dict],
             strict: bool = True, unique_keys: bool = False) -> "TableSchema":
        cols = []
        for c in columns:
            if isinstance(c, ColumnSchema):
                cols.append(c)
            elif isinstance(c, dict):
                cols.append(ColumnSchema.from_dict(c))
            else:  # ("name", type[, sort_order])
                name, ty = c[0], c[1]
                ty = parse_type(ty)
                so = None
                if len(c) > 2 and c[2] is not None:
                    so = SortOrder(c[2]) if not isinstance(c[2], SortOrder) else c[2]
                cols.append(ColumnSchema(name=name, type=ty, sort_order=so))
        return cls(columns=tuple(cols), strict=strict, unique_keys=unique_keys)

    # --- lookups --------------------------------------------------------------

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __iter__(self):
        return iter(self.columns)

    def __len__(self) -> int:
        return len(self.columns)

    def find(self, name: str) -> Optional[ColumnSchema]:
        idx = self._by_name.get(name)
        return None if idx is None else self.columns[idx]

    def get(self, name: str) -> ColumnSchema:
        col = self.find(name)
        if col is None:
            raise YtError(f"No such column {name!r}",
                          code=EErrorCode.QueryTypeError)
        return col

    def index_of(self, name: str) -> int:
        idx = self._by_name.get(name)
        if idx is None:
            raise YtError(f"No such column {name!r}")
        return idx

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    @property
    def key_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.sort_order is not None]

    @property
    def key_column_names(self) -> list[str]:
        return [c.name for c in self.key_columns]

    @property
    def is_sorted(self) -> bool:
        return bool(self.key_columns)

    # --- derived schemas ------------------------------------------------------

    def to_unsorted(self) -> "TableSchema":
        return TableSchema(
            columns=tuple(c.with_sort_order(None) for c in self.columns),
            strict=self.strict, unique_keys=False)

    def select(self, names: Iterable[str]) -> "TableSchema":
        """Project onto `names` in the given order.

        Sort orders survive only while the projection keeps key columns as a
        prefix in key order; the first break clears all remaining sort orders
        (mirrors ref schema projection semantics rather than raising).
        """
        names = list(names)
        cols = [self.get(n) for n in names]
        out: list[ColumnSchema] = []
        prefix_ok = True
        for i, col in enumerate(cols):
            if prefix_ok and col.sort_order is not None and \
                    i < len(self.columns) and self.columns[i].name == col.name:
                out.append(col)
            else:
                prefix_ok = False
                out.append(col.with_sort_order(None))
        return TableSchema(columns=tuple(out), strict=self.strict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "columns": [c.to_dict() for c in self.columns],
            "strict": self.strict,
            "unique_keys": self.unique_keys,
        }

    @classmethod
    def from_dict(cls, d: "dict[str, Any] | list") -> "TableSchema":
        # YT accepts a bare column list as @schema; honor that shape too.
        if isinstance(d, (list, tuple)):
            return cls.make(d)
        return cls.make(d["columns"], strict=d.get("strict", True),
                        unique_keys=d.get("unique_keys", False))
