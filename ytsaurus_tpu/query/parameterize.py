"""Plan auto-parameterization (ISSUE 10 tentpole, piece a).

The JIT pathology this kills: the evaluator's compiled-program cache
keys on the plan fingerprint, and the historical fingerprint included
literal VALUES — so a million-users traffic mix of `WHERE user_id = ?`
with different constants recompiled once per constant ("An Empirical
Analysis of Just-in-Time Compilation in Modern Databases", arxiv
2311.04692, measures exactly this; Flare, arxiv 1703.08219, builds the
compile-caching discipline to escape it).  The reference engine solves
it with InferName(omitValues) feeding the llvm::FoldingSet profiler
(folding_profiler.cpp) so one LLVM image serves every constant; this
module is the XLA analog.

Two cooperating passes share ONE definition of "a hoistable literal":

  text level   `hoist_literals(query_text)` — the lexer pass (THE
               implementation behind workload.normalize_query): every
               int/uint/double/string literal TOKEN becomes a `?`
               placeholder.  true/false/null are keywords, never
               hoisted.  Workload-log fingerprints hash this text.
  plan level   `plan_fingerprint(plan)` — ir.fingerprint with
               omit_values=True: TLiteral values of the same four types
               (ir.HOISTABLE_LITERAL_TYPES) collapse to `?`, IN-list
               values to their pow2-bucketed count, BETWEEN/TRANSFORM
               value lists to their lengths, string-predicate patterns
               to `?`.  The evaluator caches keyed on this.

Because both hoist the same literal classes, two query texts that
normalize identically always build plans with identical shape
fingerprints (test-enforced: the workload plane and the evaluator can
no longer silently disagree about what "the same query shape" means).

STATIC RESIDUE — values that stay in the shape fingerprint because they
shape the traced program:

  * boolean / null literals (keywords to the lexer; domains of size
    <= 2 cannot grow a spectrum);
  * OFFSET / LIMIT, which bucket pow2 instead of hoisting: the top-k
    candidate count must be a trace constant, so the lowering sizes it
    by the bucket and applies the exact offset/limit through runtime
    bindings (query/engine/lowering.py);
  * structural counts (IN-list bucket, BETWEEN range lengths,
    TRANSFORM table widths) — membership loops trace a fixed iteration
    count.

Correctness contract: the lowering is literal-value-INDEPENDENT — every
hoisted value reaches the program as a runtime binding (numeric
literals as 0-d binding slots, strings through bound vocabulary
tables), and any host constant a bind method does bake is noted into
the bind-phase structure notebook (expr.BindContext.note), which folds
into PreparedQuery.structure_key and hence the full cache key
(fingerprint, capacity bucket, binding shapes, structure).  Two plans
sharing a cache entry therefore compute the same function of their
bindings by construction.
"""

from __future__ import annotations

import re
from typing import Optional

from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.lexer import TokenKind, tokenize

_PLAIN_IDENT = re.compile(r"[A-Za-z_$][A-Za-z0-9_$]*")

_LITERAL_KINDS = {TokenKind.INT: "int64", TokenKind.UINT: "uint64",
                  TokenKind.DOUBLE: "double", TokenKind.STRING: "string"}

# No space BEFORE these rendered tokens / AFTER these suffixes: purely
# cosmetic (the token stream is identical either way), but it keeps
# normalized text readable and fingerprint-stable.
_NO_SPACE_BEFORE = {",", ")", ".", "]"}
_NO_SPACE_AFTER = ("(", ".", "[")


def hoist_literals(query: str) -> tuple[str, list]:
    """Hoist literals out of a query text: `(normalized_text, literals)`.

    Literal tokens (int/uint/double/string) become `?` placeholders and
    land in `literals` as (kind, value) in appearance order — the
    binding shapes/dtypes of the record.  Keywords upper-case and
    identifiers re-bracket when exotic, so two queries differing only
    in constants normalize to ONE text (= one workload fingerprint and,
    via the matching plan-level pass, one evaluator fingerprint)."""
    parts: list[str] = []
    literals: list[tuple[str, object]] = []
    for tok in tokenize(query):
        if tok.kind is TokenKind.EOF:
            break
        kind = _LITERAL_KINDS.get(tok.kind)
        if kind is not None:
            literals.append((kind, tok.value))
            parts.append("?")
        elif tok.kind is TokenKind.KEYWORD:
            parts.append(str(tok.value).upper())
        elif tok.kind is TokenKind.IDENT:
            name = str(tok.value)
            plain = all(_PLAIN_IDENT.fullmatch(seg)
                        for seg in name.split(".")) if name else False
            parts.append(name if plain else f"[{name}]")
        else:
            parts.append(str(tok.value))
    text = ""
    for part in parts:
        if text and part not in _NO_SPACE_BEFORE \
                and not text.endswith(_NO_SPACE_AFTER):
            text += " "
        text += part
    return text, literals


def plan_fingerprint(plan: "ir.Query | ir.FrontQuery") -> str:
    """THE compile-cache fingerprint: parameterized (shape) when
    CompileConfig.parameterize is on, the historical per-constant
    fingerprint otherwise.  Every compiled-program cache (local
    evaluator, distributed SPMD evaluator) keys through here so an
    operator toggling the config reasons about ONE discipline."""
    from ytsaurus_tpu.config import compile_config
    return ir.fingerprint(plan,
                          omit_values=compile_config().parameterize)


def hoisted_parameters(plan: "ir.Query | ir.FrontQuery") -> list:
    """The literal values the parameterized fingerprint hoisted out of
    `plan`, in deterministic walk order — the plan-level counterpart of
    hoist_literals()' `literals` (observability/tests; execution reads
    values straight from the original plan at bind time)."""
    params: list = []

    def visit(expr) -> None:
        if expr is None:
            return
        if isinstance(expr, ir.TLiteral):
            if not isinstance(expr.type, ir.EValueType):
                # Vector literal (parametric type): hoisted as a runtime
                # binding like the scalar classes.
                params.append(("vector", expr.value))
            elif expr.type in ir.HOISTABLE_LITERAL_TYPES:
                params.append((expr.type.value, expr.value))
            return
        if isinstance(expr, ir.TIn):
            for o in expr.operands:
                visit(o)
            for tup in expr.values:
                for v in tup:
                    params.append(("in", v))
            return
        if isinstance(expr, ir.TBetween):
            for o in expr.operands:
                visit(o)
            for lo, hi in expr.ranges:
                for v in (*lo, *hi):
                    params.append(("between", v))
            return
        if isinstance(expr, ir.TTransform):
            for o in expr.operands:
                visit(o)
            for tup in expr.from_values:
                for v in tup:
                    params.append(("transform", v))
            for v in expr.to_values:
                params.append(("transform_to", v))
            visit(expr.default)
            return
        if isinstance(expr, ir.TStringPredicate):
            visit(expr.operand)
            params.append(("pattern", expr.pattern))
            return
        import dataclasses as _dc
        if not isinstance(expr, ir.TExpr):
            return
        for f in _dc.fields(expr):
            value = getattr(expr, f.name)
            if isinstance(value, ir.TExpr):
                visit(value)
            elif isinstance(value, (tuple, list)):
                for item in value:
                    if isinstance(item, ir.TExpr):
                        visit(item)

    def visit_named(items) -> None:
        for item in items:
            visit(item.expr)

    if isinstance(plan, ir.Query):
        for j in plan.joins:
            for e in (*j.self_equations, *j.foreign_equations):
                visit(e)
        visit(plan.where)
    if plan.group is not None:
        visit_named(plan.group.group_items)
        for agg in plan.group.aggregate_items:
            visit(agg.argument)
            visit(agg.by_argument)
    if plan.window is not None:
        visit_named(plan.window.partition_items)
        for oi in plan.window.order_items:
            visit(oi.expr)
        for w in plan.window.items:
            visit(w.argument)
            visit(w.default)
    visit(plan.having)
    if plan.order is not None:
        for oi in plan.order.items:
            visit(oi.expr)
    if plan.project is not None:
        visit_named(plan.project.items)
    return params
