"""ExecNodeService: job slots hosted by data-node daemons.

Ref shape: exec_node slot manager + job controller
(yt/yt/server/node/exec_node/) and the per-job user process
(yt/yt/server/job_proxy/user_job.cpp).  The scheduler dispatches a
declarative JOB SPEC over RPC; the node materializes the input stripe
from chunks — LOCAL store first, peers by placement rank otherwise —
pipes formatted rows through the user command in its own process group,
and hands the stdout blob back to the controller on poll.

This moves the exec plane out of the primary: "distributed" means
distributed storage AND distributed compute (round-2 gap #4).
"""

from __future__ import annotations

import subprocess
import threading
import time
import uuid
import weakref
from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, ThrottledError, YtError
from ytsaurus_tpu.rpc import Service, rpc_method
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("exec_node")

STDERR_TAIL_BYTES = 16 << 10
# Jobs admitted but not yet holding a slot, per slot.  Past this the node
# answers RequestThrottled with a retry_after hint instead of queueing
# unboundedly — the scheduler's RetryingChannel honors the hint, and a
# persistent throttle surfaces as a dispatch failure the job quarantine
# can requeue elsewhere (serving-plane admission, ISSUE 3).
MAX_PENDING_PER_SLOT = 4
RESULT_TTL_SECONDS = 600.0
# Once the stdout blob has been handed to a poll, it is kept only this
# long (a lost poll RESPONSE can still be re-polled within the grace);
# the full TTL applies only to results nobody has fetched yet.  Must
# comfortably exceed the scheduler's poll RPC timeout + retry backoff
# (operations/jobs.py polls with a 30s channel timeout), or a timed-out
# delivery response could find the result swept on retry and double-run
# the job.
DELIVERED_GRACE_SECONDS = 120.0
SWEEP_INTERVAL_SECONDS = 60.0


def _sweep_loop(service_ref, stop: threading.Event) -> None:
    while not stop.wait(SWEEP_INTERVAL_SECONDS):
        service = service_ref()
        if service is None:
            return
        with service._lock:
            service._sweep_locked()
        del service


class ExecNodeService(Service):
    name = "exec_node"

    def __init__(self, store, slots: int = 4):
        self.store = store                    # local FsChunkStore
        self.slots = slots
        self._sem = threading.Semaphore(slots)
        self._jobs: dict[str, dict] = {}
        self._by_key: dict[str, str] = {}     # dedup: job_key -> job_id
        self._lock = threading.Lock()
        self._started_total = 0
        self._throttled_total = 0
        self._pending = 0          # admitted jobs not yet holding a slot
        # Timer-driven sweep: a burst of large-output jobs followed by
        # idle time must not pin the blobs until the next start_job.
        # The thread holds only a weakref (a dropped service instance
        # must not be pinned forever by its own sweeper) and exits on
        # close() or garbage collection.
        self._stop = threading.Event()
        self._sweeper = threading.Thread(
            target=_sweep_loop, args=(weakref.ref(self), self._stop),
            daemon=True, name="exec-job-sweeper")
        self._sweeper.start()

    def close(self) -> None:
        self._stop.set()

    # -- RPC surface -----------------------------------------------------------

    @rpc_method()
    def start_job(self, body, attachments):
        """spec: command, format, time_limit, env, and EITHER
        slices=[{chunk_id,start,end}] + peers=[addr...] (node-side
        materialization, local-first) OR an input blob attachment."""
        spec = {
            "command": _text(body["command"]),
            "format": _text(body.get("format") or "json"),
            "time_limit": body.get("time_limit"),
            "env": {_text(k): _text(v)
                    for k, v in (body.get("env") or {}).items()},
            "slices": [
                {"chunk_id": _text(s["chunk_id"]),
                 "start": int(s["start"]), "end": int(s["end"])}
                for s in (body.get("slices") or [])],
            "peers": [_text(p) for p in (body.get("peers") or [])],
            "job_id": _text(body.get("job_id") or ""),
            "op_id": _text(body.get("op_id") or ""),
            # Job environment enforcement (rlimits applied in the child;
            # operations/job_environment.py).
            "limits": {_text(k): int(v)
                       for k, v in (body.get("limits") or {}).items()}
            or None,
        }
        input_blob = attachments[0] if attachments else None
        job_key = _text(body.get("job_key") or "")
        job_id = uuid.uuid4().hex[:16]
        entry = {"state": "running", "stdout": None, "stderr": b"",
                 "error": None, "exit_code": None,
                 "proc": None, "aborted": False,
                 "created": time.monotonic()}
        with self._lock:
            self._sweep_locked()
            if job_key:
                # Transport-level retry of a delivered start_job: hand
                # back the ALREADY RUNNING job instead of a twin.
                existing = self._by_key.get(job_key)
                if existing is not None and existing in self._jobs:
                    return {"job_id": existing}
            if self._pending >= self.slots * MAX_PENDING_PER_SLOT:
                self._throttled_total += 1
                raise ThrottledError(
                    f"exec node job queue full ({self._pending} pending "
                    f"over {self.slots} slots)",
                    retry_after=round(min(max(
                        0.1 * self._pending / max(self.slots, 1), 0.05),
                        5.0), 3))
            if job_key:
                self._by_key[job_key] = job_id
            self._pending += 1
            self._jobs[job_id] = entry
            self._started_total += 1
        thread = threading.Thread(
            target=self._run, args=(job_id, entry, spec, input_blob),
            daemon=True, name=f"exec-job-{job_id}")
        thread.start()
        return {"job_id": job_id}

    @rpc_method()
    def poll_job(self, body, attachments):
        job_id = _text(body["job_id"])
        with self._lock:
            entry = self._jobs.get(job_id)
        if entry is None:
            raise YtError(f"No such job {job_id}",
                          code=EErrorCode.NoSuchOperation)
        out = {"state": entry["state"],
               "exit_code": entry["exit_code"],
               "stderr_tail": entry["stderr"].decode("utf-8", "replace")}
        if entry["error"] is not None:
            out["error"] = str(entry["error"])
        if entry["state"] == "completed":
            if entry.get("delivered") is None:
                entry["delivered"] = time.monotonic()
            return out, [entry["stdout"]]
        return out

    @rpc_method()
    def abort_job(self, body, attachments):
        job_id = _text(body["job_id"])
        with self._lock:
            entry = self._jobs.get(job_id)
        if entry is not None:
            entry["aborted"] = True
            self._kill(entry)
        return {}

    @rpc_method()
    def exec_stats(self, body, attachments):
        with self._lock:
            running = sum(1 for e in self._jobs.values()
                          if e["state"] == "running")
            return {"slots": self.slots, "running": running,
                    "pending": self._pending,
                    "started_total": self._started_total,
                    "throttled_total": self._throttled_total}

    # -- execution -------------------------------------------------------------

    def _sweep_locked(self) -> None:
        now = time.monotonic()
        for job_id in [j for j, e in self._jobs.items()
                       if e["state"] != "running"
                       and (now - e["created"] > RESULT_TTL_SECONDS
                            or (e.get("delivered") is not None and
                                now - e["delivered"] >
                                DELIVERED_GRACE_SECONDS))]:
            del self._jobs[job_id]
        self._by_key = {k: v for k, v in self._by_key.items()
                        if v in self._jobs}

    def _materialize(self, spec) -> bytes:
        """Stripe rows as a format blob: local chunks first, peers by
        placement rank for the rest (the local-first read the reference's
        exec nodes get from colocated data nodes)."""
        from ytsaurus_tpu.chunks.columnar import concat_chunks
        from ytsaurus_tpu.formats import dumps_rows
        from ytsaurus_tpu.server.remote_store import RpcChunkStore

        remote = RpcChunkStore(lambda: spec["peers"])
        try:
            parts = []
            for item in spec["slices"]:
                chunk = None
                try:
                    if self.store.exists(item["chunk_id"]):
                        chunk = self.store.read_chunk(item["chunk_id"])
                except Exception:   # noqa: BLE001 — fall back to peers
                    chunk = None
                if chunk is None:
                    chunk = remote.read_chunk(item["chunk_id"])
                if item["start"] != 0 or item["end"] != chunk.row_count:
                    chunk = chunk.slice_rows(item["start"], item["end"])
                parts.append(chunk)
            merged = concat_chunks(parts) if len(parts) > 1 else parts[0]
            return dumps_rows(merged.to_rows(), spec["format"])
        finally:
            remote.close()

    def _run(self, job_id: str, entry: dict, spec: dict,
             input_blob: Optional[bytes]) -> None:
        import os
        with self._sem:
            with self._lock:
                self._pending -= 1      # holding a slot now, not queued
            try:
                if entry["aborted"]:
                    raise YtError("job aborted before start",
                                  code=EErrorCode.Canceled)
                if input_blob is None:
                    input_blob = self._materialize(spec)
                from ytsaurus_tpu.operations.job_environment import (
                    make_preexec,
                )
                proc = subprocess.Popen(
                    ["/bin/sh", "-c", spec["command"]],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, start_new_session=True,
                    preexec_fn=make_preexec(spec.get("limits")),
                    env={**os.environ, **spec["env"],
                         "YT_JOB_ID": spec["job_id"] or job_id,
                         "YT_OPERATION_ID": spec["op_id"]})
                entry["proc"] = proc
                if entry["aborted"]:
                    self._kill(entry)
                try:
                    stdout, stderr = proc.communicate(
                        input_blob, timeout=spec["time_limit"])
                except subprocess.TimeoutExpired:
                    self._kill(entry)
                    proc.communicate()
                    raise YtError("user job timed out",
                                  code=EErrorCode.Timeout)
                entry["stderr"] = stderr[-STDERR_TAIL_BYTES:]
                entry["exit_code"] = proc.returncode
                if entry["aborted"]:
                    raise YtError("job aborted", code=EErrorCode.Canceled)
                if proc.returncode != 0:
                    from ytsaurus_tpu.operations.job_environment import (
                        classify_failure,
                    )
                    cause = classify_failure(
                        proc.returncode, entry["stderr"],
                        spec.get("limits"))
                    raise YtError(
                        f"user job exited {proc.returncode}",
                        code=EErrorCode.OperationFailed,
                        attributes={"probable_cause": cause}
                        if cause else {})
                entry["stdout"] = stdout
                entry["state"] = "completed"
            except YtError as err:
                entry["error"] = err
                entry["state"] = "aborted" if entry["aborted"] \
                    else "failed"
            except Exception as exc:    # noqa: BLE001 — job boundary
                entry["error"] = YtError(f"job crashed: {exc!r}")
                entry["state"] = "failed"
            finally:
                entry["proc"] = None

    @staticmethod
    def _kill(entry: dict) -> None:
        import os
        import signal
        proc = entry.get("proc")
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    proc.kill()
                except OSError:
                    pass
