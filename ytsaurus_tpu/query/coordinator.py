"""Distributed query coordination: bottom/front plan split + execution.

Analog of the reference's coordinator algebra (library/query/engine_api/
coordinator.h: GetDistributedQueryPattern, CoordinateAndExecute): a plan is
split into a `bottom` query that runs unchanged on every shard (tablet) and a
`front` query that merges the partial results — partial aggregate states are
re-aggregated with merge functions (count merges by SUM, avg decomposes into
sum+count state columns), ORDER BY re-sorts the per-shard top-K, and
offset/limit apply only at the front.
"""

from __future__ import annotations

import contextvars
import time
from dataclasses import replace
from typing import Mapping, Optional, Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk, concat_chunks
from ytsaurus_tpu.config import retry_policy
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.evaluator import Evaluator, finish_all
from ytsaurus_tpu.schema import EValueType
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.tracing import NULL_SPAN, child_span

# How each aggregate's partial state is merged at the front.
_MERGE_FN = {"sum": "sum", "count": "sum", "min": "min", "max": "max",
             "first": "first"}

# Per-shard fault sites: materialize covers staging (chunk fetch/decode,
# tablet snapshot), execute covers the shard's bottom-query program.
_FP_MATERIALIZE = failpoints.register_site(
    "query.shard_materialize",
    error=lambda s: YtError(f"injected shard staging failure at {s}",
                            code=EErrorCode.TransportError))
_FP_EXECUTE = failpoints.register_site(
    "query.shard_execute",
    error=lambda s: YtError(f"injected shard execution failure at {s}",
                            code=EErrorCode.TransportError))

# Errors worth a per-shard retry: transport-shaped (a remote read hiccup,
# a dying location).  Application errors (type/parse/execution bugs) are
# deterministic and must surface unchanged.
_TRANSIENT_CODES = frozenset({EErrorCode.TransportError,
                              EErrorCode.RpcTimeout,
                              EErrorCode.PeerUnavailable})


def _is_transient(err: Exception) -> bool:
    return isinstance(err, OSError) or (
        isinstance(err, YtError) and err.code in _TRANSIENT_CODES)


def _retry_transient(fn, site: "Optional[failpoints.FailpointSite]" = None,
                     token=None, span_name: Optional[str] = None,
                     stats=None, **span_tags):
    """Jittered-exponential-backoff retry of transient failures (policy
    `query_shard` in config.py) around one shard-granular step.  A token
    past its deadline stops the ladder — retries must not keep a dead
    query alive past its budget.  `span_name` opens one child span PER
    ATTEMPT (same trace, fresh span, tagged `attempt=`), so a retried
    shard shows every try in the flight recorder; `stats.retries` counts
    the extra attempts (per-tenant accounting charges them)."""
    policy = retry_policy("query_shard")
    for attempt in range(policy.attempts):
        try:
            with child_span(span_name, attempt=attempt, **span_tags) \
                    if span_name is not None else NULL_SPAN:
                if token is not None:
                    token.check()
                if site is not None:
                    site.hit()
                return fn()
        except (OSError, YtError) as err:
            if not _is_transient(err) or attempt + 1 >= policy.attempts:
                raise
            if stats is not None:
                stats.retries += 1
            time.sleep(policy.delay(attempt))


def _wrap_lazy_shard(shard, token=None, index: Optional[int] = None,
                     stats=None):
    """Lazy shards retry their own staging so one transient chunk-read
    failure doesn't sink the whole scan.  The CALLER's trace context is
    captured explicitly: staging runs on prefetch-executor threads whose
    contextvars would otherwise be empty, unlinking the stage spans."""
    if not callable(shard):
        return shard
    captured = contextvars.copy_context()

    def staged():
        return _retry_transient(shard, site=_FP_MATERIALIZE, token=token,
                                span_name="coordinator.shard_stage",
                                stats=stats, shard=index)

    return lambda: captured.run(staged)


def split_plan(plan: ir.Query) -> tuple[ir.Query, ir.FrontQuery]:
    """Split into (bottom, front) — ref GetDistributedQueryPattern."""
    limit_for_bottom = None
    if plan.limit is not None:
        limit_for_bottom = plan.offset + plan.limit

    if plan.window is not None:
        # Window functions need COMPLETE partitions: per-shard windows
        # over arbitrary row placement would be wrong, so the bottom
        # only filters and the window stage runs at the front over the
        # merged rowset (the shuffled SPMD path instead co-partitions by
        # the PARTITION BY key — parallel/distributed.py).
        bottom = replace(plan, window=None, having=None, order=None,
                         project=None, offset=0, limit=None)
        front = ir.FrontQuery(
            schema=bottom.output_schema(), window=plan.window,
            order=plan.order, project=plan.project,
            offset=plan.offset, limit=plan.limit)
        return bottom, front

    if plan.group is not None and any(
            a.function == "cardinality" for a in plan.group.aggregate_items):
        # Distinct counts cannot merge from per-shard counts; ship the
        # filtered rows and run the whole group stage at the front.
        bottom = replace(plan, group=None, having=None, order=None,
                         project=None, offset=0, limit=None)
        front = ir.FrontQuery(
            schema=bottom.output_schema(), group=plan.group,
            having=plan.having, order=plan.order, project=plan.project,
            offset=plan.offset, limit=plan.limit)
        return bottom, front

    if plan.group is not None:
        bottom_aggs: list[ir.AggregateItem] = []
        avg_map: dict[str, tuple[str, str]] = {}
        argfn_front: dict[str, tuple[str, str]] = {}
        for agg in plan.group.aggregate_items:
            if agg.function in ("argmin", "argmax"):
                v_name, b_name = f"{agg.name}__v", f"{agg.name}__b"
                bottom_aggs.append(ir.AggregateItem(
                    name=v_name, function=agg.function,
                    argument=agg.argument, type=agg.type,
                    state_type=agg.state_type,
                    by_argument=agg.by_argument))
                bottom_aggs.append(ir.AggregateItem(
                    name=b_name,
                    function="min" if agg.function == "argmin" else "max",
                    argument=agg.by_argument, type=agg.by_argument.type,
                    state_type=agg.by_argument.type))
                argfn_front[agg.name] = (v_name, b_name)
                continue
            if agg.function == "avg":
                s_name, c_name = f"{agg.name}__s", f"{agg.name}__c"
                arg = agg.argument
                bottom_aggs.append(ir.AggregateItem(
                    name=s_name, function="sum",
                    argument=_to_double(arg), type=EValueType.double,
                    state_type=EValueType.double))
                bottom_aggs.append(ir.AggregateItem(
                    name=c_name, function="count", argument=arg,
                    type=EValueType.int64, state_type=EValueType.int64))
                avg_map[agg.name] = (s_name, c_name)
            else:
                bottom_aggs.append(agg)
        bottom = replace(plan, group=ir.GroupClause(
            group_items=plan.group.group_items,
            aggregate_items=tuple(bottom_aggs), totals=False),
            having=None, order=None, project=None, offset=0, limit=None)
        inter_schema = bottom.output_schema()

        front_group_items = tuple(
            ir.NamedExpr(name=item.name,
                         expr=ir.TReference(type=item.expr.type, name=item.name))
            for item in plan.group.group_items)
        # Keep the ORIGINAL declaration order: output schemas must match the
        # single-node plan regardless of how states were decomposed.
        by_name = {a.name: a for a in plan.group.aggregate_items}
        front_agg_list = []
        for agg in plan.group.aggregate_items:
            if agg.name in argfn_front:
                v_name, b_name = argfn_front[agg.name]
                front_agg_list.append(ir.AggregateItem(
                    name=agg.name, function=agg.function,
                    argument=ir.TReference(type=agg.type, name=v_name),
                    type=agg.type, state_type=agg.state_type,
                    by_argument=ir.TReference(
                        type=agg.by_argument.type, name=b_name)))
            elif agg.function == "avg":
                s_name, c_name = avg_map[agg.name]
                for state_name, state_fn, ty in (
                        (s_name, "sum", EValueType.double),
                        (c_name, "sum", EValueType.int64)):
                    front_agg_list.append(ir.AggregateItem(
                        name=state_name, function=state_fn,
                        argument=ir.TReference(type=ty, name=state_name),
                        type=ty, state_type=ty))
            else:
                front_agg_list.append(ir.AggregateItem(
                    name=agg.name, function=_MERGE_FN[agg.function],
                    argument=ir.TReference(type=agg.state_type, name=agg.name),
                    type=agg.type, state_type=agg.state_type))
        front_aggs = tuple(front_agg_list)

        subst = _AvgSubstituter(avg_map)
        front = ir.FrontQuery(
            schema=inter_schema,
            group=ir.GroupClause(group_items=front_group_items,
                                 aggregate_items=front_aggs,
                                 totals=plan.group.totals),
            having=subst(plan.having),
            order=_subst_order(plan.order, subst),
            project=_subst_project(plan.project, subst,
                                   plan) if plan.project else _default_project(plan, subst),
            offset=plan.offset, limit=plan.limit)
        return bottom, front

    if plan.order is not None:
        # Bottom keeps the full row set (identity projection) but can cut to
        # the per-shard top-(offset+limit); the front re-sorts and projects.
        bottom = replace(plan, having=None, project=None, offset=0,
                         limit=limit_for_bottom)
        front = ir.FrontQuery(
            schema=plan.schema, order=plan.order, project=plan.project,
            offset=plan.offset, limit=plan.limit)
        return bottom, front

    bottom = replace(plan, offset=0, limit=limit_for_bottom)
    front = ir.FrontQuery(schema=bottom.output_schema(), offset=plan.offset,
                          limit=plan.limit)
    return bottom, front


def _to_double(expr: ir.TExpr) -> ir.TExpr:
    if expr.type is EValueType.double:
        return expr
    return ir.TFunction(type=EValueType.double, name="double", args=(expr,))


class _AvgSubstituter:
    """Rewrites references to an avg slot into state_sum / state_count."""

    def __init__(self, avg_map: dict[str, tuple[str, str]]):
        self.avg_map = avg_map

    def __call__(self, expr: Optional[ir.TExpr]) -> Optional[ir.TExpr]:
        if expr is None or not self.avg_map:
            return expr
        return ir.map_expr(expr, self._leaf)

    def _leaf(self, e: ir.TExpr) -> ir.TExpr:
        if isinstance(e, ir.TReference) and e.name in self.avg_map:
            s_name, c_name = self.avg_map[e.name]
            s_ref = ir.TReference(type=EValueType.double, name=s_name)
            c_ref = ir.TReference(type=EValueType.int64, name=c_name)
            return ir.TBinary(type=EValueType.double, op="/", lhs=s_ref,
                              rhs=_to_double(c_ref))
        return e


def _subst_order(order: Optional[ir.OrderClause],
                 subst: _AvgSubstituter) -> Optional[ir.OrderClause]:
    if order is None:
        return None
    return ir.OrderClause(items=tuple(
        ir.OrderItem(expr=subst(i.expr), descending=i.descending)
        for i in order.items))


def _subst_project(project: ir.ProjectClause, subst: _AvgSubstituter,
                   plan: ir.Query) -> ir.ProjectClause:
    return ir.ProjectClause(items=tuple(
        ir.NamedExpr(name=i.name, expr=subst(i.expr)) for i in project.items))


def _default_project(plan: ir.Query, subst: _AvgSubstituter
                     ) -> Optional[ir.ProjectClause]:
    """SELECT * with GROUP BY: reconstruct keys + original aggregate values
    (avg must be divided back out of its state columns)."""
    if not subst.avg_map:
        return None
    items = []
    for item in plan.group.group_items:
        items.append(ir.NamedExpr(
            name=item.name,
            expr=ir.TReference(type=item.expr.type, name=item.name)))
    for agg in plan.group.aggregate_items:
        items.append(ir.NamedExpr(
            name=agg.name,
            expr=subst(ir.TReference(type=agg.type, name=agg.name))))
    return ir.ProjectClause(items=tuple(items))


def _ordered_scan_direction(plan: ir.Query,
                            range_ordered_by) -> Optional[str]:
    """'asc'/'desc' when ORDER BY + LIMIT can stop scanning range-ordered
    shards early: every order item is a bare reference, the referenced
    names form a prefix of the shard-range key, and the direction is
    uniform.  None otherwise."""
    if not range_ordered_by or plan.order is None or \
            plan.limit is None or plan.group is not None:
        return None
    items = plan.order.items
    if not items or not all(isinstance(it.expr, ir.TReference)
                            for it in items):
        return None
    if len({it.descending for it in items}) != 1:
        return None
    names = [it.expr.name for it in items]
    if names != list(range_ordered_by)[: len(names)]:
        return None
    return "desc" if items[0].descending else "asc"


class _PrefetchScanner:
    """Adaptive ordered prefetch (ref engine_api/coordinator.h:81-90 —
    scanOrder + prefetch): while shard i evaluates on device, shards
    i+1..i+window stage on background threads.  The window is
    FEEDBACK-BOUNDED: an early-exit scan starts at 1 (it expects to
    stop; staging ahead would touch chunks the exit saves), and doubles
    each time the scan actually continues, up to max_window — a scan
    that keeps going converges to full pipelining."""

    def __init__(self, shards, window: int = 1, max_window: int = 4,
                 stats=None, count_rows: bool = False):
        from concurrent.futures import ThreadPoolExecutor
        self.shards = list(shards)
        self.window = max(window, 1)
        self.max_window = max_window
        self.stats = stats
        self.count_rows = count_rows
        self._futures: dict = {}
        self._executor = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="shard-prefetch")

    def _submit(self, i: int) -> None:
        if 0 <= i < len(self.shards) and i not in self._futures:
            shard = self.shards[i]
            if callable(shard):
                # Count at SUBMIT: a window-prefetched shard the exit
                # then skips was still fetched/decoded, and the staged
                # counter must say so.  (Eager inputs were fetched
                # before the coordinator ran — not counted here.)
                if self.stats is not None and self.count_rows:
                    self.stats.shards_staged += 1
                self._futures[i] = self._executor.submit(shard)
            else:
                from concurrent.futures import Future
                fut: Future = Future()
                fut.set_result(shard)
                self._futures[i] = fut

    def get(self, i: int) -> ColumnarChunk:
        self._submit(i)
        for j in range(i + 1, i + 1 + self.window):
            self._submit(j)
        chunk = self._futures.pop(i).result()
        if self.stats is not None and self.count_rows:
            self.stats.rows_read += chunk.row_count
            self.stats.bytes_read += chunk.nbytes
        return chunk

    def feedback(self) -> None:
        """The scan continued past a shard: stage further ahead."""
        self.window = min(self.window * 2, self.max_window)

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)


def _materialize(shard) -> ColumnarChunk:
    return shard() if callable(shard) else shard


def coordinate_and_execute(
        plan: ir.Query,
        chunks: Sequence,
        foreign_chunks: Optional[Mapping[str, ColumnarChunk]] = None,
        evaluator: Optional[Evaluator] = None,
        merge_shards_below: int = 0,
        range_ordered_by: Optional[Sequence[str]] = None,
        stats=None, token=None) -> ColumnarChunk:
    """Host-coordinated fan-out: run the bottom query per shard (tablet),
    concatenate partial results, run the front merge.

    Ref: CoordinateAndExecute (engine_api/coordinator.cpp) — here shard
    results stay on device; only the final row count syncs to host.

    `chunks` entries may be ColumnarChunks OR zero-arg callables
    producing them (LAZY shards): staging then happens inside the scan
    through the adaptive prefetcher, so an ordered LIMIT touches only
    the shards it actually reads, and a full scan overlaps shard i+1's
    staging with shard i's evaluation.

    `merge_shards_below`: when > 0, shards are coalesced so no device
    program runs over fewer than this many rows — per-program dispatch
    overhead dominates small shards (ref analog: chunk slice grouping in
    chunk pools).  0 preserves one program per shard.

    `range_ordered_by`: key column names by which the SHARDS are range-
    ordered (tablet pivot order for sorted dynamic tables).  Lets ORDER
    BY <key prefix> LIMIT scan shards from the matching end and stop
    once offset+limit rows passed the filter — the reference's ordered
    scan with scanOrder (engine_api/coordinator.h:81-90).

    `token` (query/serving.CancellationToken): checked before each
    shard's staging and execution, so a query past its deadline aborts
    mid-plan — remaining shards never stage and never launch device
    programs — instead of running to completion.
    """
    evaluator = evaluator or Evaluator()
    if not chunks:
        raise YtError("coordinate_and_execute: no input shards",
                      code=EErrorCode.QueryExecutionError)
    if token is not None:
        token.check()
    lazy = any(callable(c) for c in chunks)
    if lazy:
        chunks = [_wrap_lazy_shard(c, token=token, index=i, stats=stats)
                  for i, c in enumerate(chunks)]
    # Early-exit budget, decided BEFORE any shard coalescing: when a
    # LIMIT scan can stop after the first shard or two, merging every
    # shard into one big program would do strictly more work than the
    # exit saves.
    needed = None
    scan_direction = None
    # No early exit for window plans: every row of a partition (on any
    # shard) feeds the front's window stage, so a partial scan would
    # change window values, not just row selection.
    if plan.limit is not None and plan.group is None and \
            plan.window is None:
        if plan.order is None:
            needed = plan.offset + plan.limit
        else:
            scan_direction = _ordered_scan_direction(plan,
                                                     range_ordered_by)
            if scan_direction is not None:
                needed = plan.offset + plan.limit
    if merge_shards_below > 0 and len(chunks) > 1 and not lazy:
        if scan_direction is None:
            # Bare LIMIT (or no early exit): full coalescing — a
            # selective WHERE may scan everything, so dispatch overhead
            # dominates and the early exit still skips whole groups.
            chunks = _coalesce_shards(chunks, merge_shards_below)
        else:
            # Ordered exit: the scan is expected to stop after ~needed
            # rows, so a group only needs to hold the scan budget —
            # merging further would drag unwanted rows into the first
            # program and forfeit the skip.  (A selective WHERE on an
            # ordered scan pays per-shard dispatch; that is the price
            # of being able to stop at all.)
            chunks = _coalesce_shards(chunks, max(needed, 1))
    if stats is not None:
        stats.shards_total += len(chunks)
        if not lazy:
            stats.rows_read += sum(c.row_count for c in chunks)
            stats.bytes_read += sum(c.nbytes for c in chunks)
    if len(chunks) == 1:
        chunk = _materialize(chunks[0])
        if lazy and stats is not None:
            stats.shards_staged += 1
            stats.rows_read += chunk.row_count
            stats.bytes_read += chunk.nbytes
        result = _retry_transient(
            lambda: evaluator.run_plan(plan, chunk, foreign_chunks,
                                       stats=stats, token=token),
            site=_FP_EXECUTE, token=token,
            span_name="coordinator.shard", stats=stats, shard=0)
    else:
        bottom, front = split_plan(plan)
        # LIMIT early-exit (ref: pull-model readers stop at the limit,
        # CoordinateAndExecute ordered scans, coordinator.h:81-90): with
        # no ORDER BY and no aggregation, any offset+limit rows satisfy
        # the query — stop launching shard programs once the partials
        # hold enough.  The per-shard row-count read is the bounded-batch
        # "device predicate feedback" loop from SURVEY §7.
        # Ordered scan: shards range-ordered by the ORDER BY prefix are
        # walked from the matching end; once offset+limit rows passed
        # the filter, no unscanned shard can hold a better-ranked row
        # (its whole key range sorts after).  Ties at the boundary pick
        # among equal keys, which ORDER BY leaves unspecified anyway.
        scan_chunks = list(chunks)
        if scan_direction == "desc":
            scan_chunks.reverse()
        # Lazy shards could not be pre-coalesced (row counts unknown
        # before staging): group AFTER materialization.  ANY early exit
        # (ordered or bare LIMIT) caps the group at the scan budget —
        # staging past `needed` rows before the first program would
        # fetch exactly the chunks the exit exists to save.  (The eager
        # path coalesces bare LIMITs fully only because its chunks were
        # already staged — a sunk cost lazy scans don't have.)
        group_threshold = 0
        if lazy and merge_shards_below > 0:
            group_threshold = max(needed, 1) if needed is not None \
                else merge_shards_below
        scanner = _PrefetchScanner(
            scan_chunks,
            window=1 if needed is not None else 2,
            stats=stats, count_rows=lazy)
        # With no early exit, the per-shard row count never gates control
        # flow — so shard programs DISPATCH without synchronizing (the
        # round-5 hot spot: one blocking int(count) host read per shard
        # serialized the whole fan-out) and the counts cross the host
        # boundary once, after every program is enqueued.  Early-exit
        # scans still need the count (it IS the exit signal) but batch
        # it in WAVES: a window of shard programs dispatches without
        # synchronizing, then the wave's counts cross as ONE stacked
        # finish_all transfer.  The wave doubles while the scan keeps
        # going (mirroring the prefetch window), so a stop-at-shard-0
        # query pays a single-program wave and a scan that runs long
        # converges to pipelined dispatch.  Duck-typed evaluators
        # without run_plan_async keep the per-shard sync path.
        deferred = hasattr(evaluator, "run_plan_async")
        early_async = deferred and needed is not None
        partials = []
        wave: list = []
        wave_budget = 1
        waves_done = 0
        try:
            collected = 0
            group: list = []
            group_rows = 0
            for i in range(len(scan_chunks)):
                if token is not None:
                    # Deadline/cancel gate per shard: an expired query
                    # stops HERE — unscanned shards are never staged,
                    # their programs never launch.
                    token.check()
                chunk = scanner.get(i)
                if group_threshold > 0:
                    group.append(chunk)
                    group_rows += chunk.row_count
                    if group_rows < group_threshold and \
                            i + 1 < len(scan_chunks):
                        # No feedback here: only an EVALUATION that
                        # declined to exit proves the scan continues.
                        continue
                    chunk = concat_chunks(group) if len(group) > 1 \
                        else group[0]
                    group, group_rows = [], 0
                if deferred:
                    pending = _retry_transient(
                        lambda c=chunk: evaluator.run_plan_async(
                            bottom, c, foreign_chunks, stats=stats,
                            token=token),
                        site=_FP_EXECUTE, token=token,
                        span_name="coordinator.shard", stats=stats,
                        shard=i)
                if deferred and needed is None:
                    partials.append(pending)
                    scanner.feedback()
                    continue
                if early_async:
                    wave.append(pending)
                    if len(wave) < wave_budget and \
                            i + 1 < len(scan_chunks):
                        continue
                    finished = finish_all(wave)
                    wave = []
                    waves_done += 1
                    if waves_done >= 2:
                        # Two waves declined to exit: the scan is
                        # probably running long — start pipelining.
                        wave_budget = min(wave_budget * 2, 4)
                    partials.extend(finished)
                    collected += sum(p.row_count for p in finished)
                    if collected >= needed:
                        if stats is not None:
                            stats.shards_skipped += \
                                len(scan_chunks) - (i + 1)
                        break
                    scanner.feedback()
                    continue
                partial = _retry_transient(
                    lambda c=chunk: evaluator.run_plan(
                        bottom, c, foreign_chunks, stats=stats,
                        token=token),
                    site=_FP_EXECUTE, token=token,
                    span_name="coordinator.shard", stats=stats, shard=i)
                partials.append(partial)
                collected += partial.row_count
                if needed is not None and collected >= needed:
                    if stats is not None:
                        stats.shards_skipped += \
                            len(scan_chunks) - (i + 1)
                    break
                scanner.feedback()
        finally:
            scanner.close()
        if deferred and needed is None:
            partials = finish_all(partials)
        with child_span("coordinator.front_merge",
                        partials=len(partials)):
            merged = concat_chunks(
                [p.slice_rows(0, p.row_count) for p in partials])
            result = evaluator.run_plan(front, merged, stats=stats,
                                        token=token)
    if stats is not None:
        stats.rows_written += result.row_count
    return result


def _coalesce_shards(chunks: Sequence[ColumnarChunk],
                     min_rows: int) -> list[ColumnarChunk]:
    groups: list[list[ColumnarChunk]] = []
    current: list[ColumnarChunk] = []
    current_rows = 0
    for chunk in chunks:
        current.append(chunk)
        current_rows += chunk.row_count
        if current_rows >= min_rows:
            groups.append(current)
            current, current_rows = [], 0
    if current:
        if groups:
            groups[-1].extend(current)
        else:
            groups.append(current)
    return [concat_chunks(g) if len(g) > 1 else g[0] for g in groups]
