"""Orchid: a virtual tree of live daemon state.

Ref shape: library/orchid/orchid_service.h — every daemon exposes a YTree
of live internals (config, sensors, connections, tablet state) served over
RPC and mounted into Cypress so operators browse it with normal tree reads.

Redesign: producers are callables registered at slash-paths; a read walks
the static registry to the deepest matching producer, invokes it ONCE, then
descends into the returned plain dict.  Served two ways: the `orchid` RPC
service (thin client: `client.get_orchid(path)`) and the monitoring HTTP
endpoint (`server/monitoring.py`).
"""

from __future__ import annotations

from typing import Callable

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc.server import Service, rpc_method


def _split(path: str) -> list[str]:
    return [t for t in path.split("/") if t]


class OrchidTree:
    """Registry of live-state producers."""

    def __init__(self):
        self._producers: dict[tuple, Callable[[], object]] = {}

    def register(self, path: str, producer: Callable[[], object]) -> None:
        """Mount a producer at `/a/b`; it returns a plain dict/value each
        read (never cached — Orchid is live state by definition)."""
        self._producers[tuple(_split(path))] = producer

    def register_value(self, path: str, value) -> None:
        self.register(path, lambda: value)

    # -- reads -----------------------------------------------------------------

    def get(self, path: str = "/"):
        tokens = tuple(_split(path))
        # Deepest registered producer that prefixes the path.
        for depth in range(len(tokens), -1, -1):
            producer = self._producers.get(tokens[:depth])
            if producer is not None:
                return _descend(producer(), tokens[depth:], path)
        # No direct producer: synthesize the directory level.
        children = self._level(tokens)
        if children is None:
            raise YtError(f"Orchid has no node {path!r}",
                          code=EErrorCode.ResolveError)
        return {name: "..." for name in children}

    def list(self, path: str = "/") -> list[str]:
        """Child names: structural sub-mounts plus keys of the produced
        value when a producer covers the path."""
        tokens = tuple(_split(path))
        names: set[str] = set()
        structural = False
        for key in self._producers:
            if len(key) > len(tokens) and key[:len(tokens)] == tokens:
                names.add(key[len(tokens)])
                structural = True
        if any(key == tokens[:len(key)] for key in self._producers
               if len(key) <= len(tokens)):
            value = self.get(path)
            if isinstance(value, dict):
                names.update(k.decode() if isinstance(k, bytes) else str(k)
                             for k in value)
            elif not structural:
                raise YtError(f"Orchid node {path!r} is not a map",
                              code=EErrorCode.ResolveError)
        elif not structural and tokens:
            raise YtError(f"Orchid has no node {path!r}",
                          code=EErrorCode.ResolveError)
        return sorted(names)

    def _level(self, tokens: tuple) -> set | None:
        """Child names at a purely-structural level, None if absent."""
        children = set()
        found = False
        for key in self._producers:
            if len(key) > len(tokens) and key[:len(tokens)] == tokens:
                children.add(key[len(tokens)])
                found = True
            elif key == tokens:
                found = True
        return children if found or not tokens else None


def _descend(value, tokens, path: str):
    for token in tokens:
        if isinstance(value, dict):
            if token in value:
                value = value[token]
                continue
            if token.encode() in value:
                value = value[token.encode()]
                continue
        if isinstance(value, (list, tuple)) and token.isdigit() \
                and int(token) < len(value):
            value = value[int(token)]
            continue
        raise YtError(f"Orchid has no node {path!r} (at {token!r})",
                      code=EErrorCode.ResolveError)
    return value


class OrchidService(Service):
    """RPC surface over an OrchidTree."""

    name = "orchid"

    def __init__(self, tree: OrchidTree):
        self.tree = tree

    @rpc_method()
    def get(self, body, attachments):
        return {"value": self.tree.get(body.get("path", "/"))}

    @rpc_method()
    def list(self, body, attachments):
        return {"names": self.tree.list(body.get("path", "/"))}


def default_orchid(config=None) -> OrchidTree:
    """Standard daemon mounts: /config, /monitoring/sensors, /tracing,
    /telemetry (history rings + SLO state), /accounting."""
    from ytsaurus_tpu.utils.profiling import get_registry
    from ytsaurus_tpu.utils.tracing import get_collector

    tree = OrchidTree()
    if config is not None:
        tree.register("/config", lambda: config.to_dict())
    tree.register("/monitoring/sensors", get_registry().collect)
    tree.register("/tracing/recent_spans",
                  lambda: [s.to_dict() for s in
                           get_collector().snapshot()[-64:]])
    # Flight-recorder views: span trees by trace id (what `yt trace`
    # reads over the RPC orchid) + the bounded slow-query log.
    tree.register("/tracing/traces", _traces_producer)
    tree.register("/tracing/slow_queries", _slow_queries_producer)
    # Telemetry plane (ISSUE 6): the bounded metrics-history rings, the
    # SLO burn-rate state, and per-tenant resource accounting — the RPC
    # twins of the monitoring /metrics/history, /slo, and /accounting
    # endpoints (`yt top` reads /accounting through this orchid).
    tree.register("/telemetry/history", _history_producer)
    tree.register("/telemetry/slo", _slo_producer)
    tree.register("/accounting", _accounting_producer)
    # Workload recorder + compilation observatory (ISSUE 8): the RPC
    # twins of the monitoring /workload and /compile endpoints (`yt
    # workload capture` / `yt compile-cache top` read these remotely).
    tree.register("/workload", _workload_producer)
    tree.register("/compile", _compile_producer)
    # Mesh execution observatory (ISSUE 20): the RPC twin of the
    # monitoring /mesh endpoint (`yt mesh top` reads this remotely).
    tree.register("/mesh", _mesh_producer)
    # Continuous queries (ISSUE 13): live view-daemon state — the RPC
    # twin of the monitoring /views endpoint (`yt view list` could read
    # this remotely when no driver is reachable).
    tree.register("/views", _views_producer)
    # Concurrency sanitizer (ISSUE 15): the RPC twin of the monitoring
    # /sanitizer endpoint — observed lock-order edges + violation
    # report of the instrumented-lock layer.
    tree.register("/sanitizer", _sanitizer_producer)
    # Serving plane (ISSUE 17): the RPC twin of the monitoring /serving
    # endpoint — per-gateway fair-share admission + brown-out state
    # (`yt top --by pool` reads the share/use/demand overlay remotely).
    tree.register("/serving", _serving_producer)
    return tree


def _traces_producer() -> dict:
    from ytsaurus_tpu.utils.tracing import all_span_trees
    return all_span_trees()


def _slow_queries_producer() -> list:
    from ytsaurus_tpu.query.profile import get_flight_recorder
    return [p.to_dict(include_rows=False)
            for p in get_flight_recorder().slow_queries()]


def _history_producer() -> dict:
    from ytsaurus_tpu.utils.profiling import get_history
    return get_history().dump()


def _slo_producer() -> dict:
    from ytsaurus_tpu.utils.slo import get_slo_tracker
    return get_slo_tracker().snapshot()


def _accounting_producer() -> dict:
    from ytsaurus_tpu.query.accounting import get_accountant
    return get_accountant().snapshot()


def _workload_producer() -> dict:
    from ytsaurus_tpu.query.workload import get_workload_log
    # limit=0 serves EVERY retained record (the log is bounded by
    # WorkloadConfig.capacity anyway): remote `yt workload capture`
    # reads through here and must not silently truncate the capture.
    return get_workload_log().snapshot(limit=0)


def _compile_producer() -> dict:
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    return get_compile_observatory().snapshot()


def _mesh_producer() -> dict:
    from ytsaurus_tpu.parallel.mesh_observatory import (
        get_mesh_observatory,
    )
    return get_mesh_observatory().snapshot()


def _views_producer() -> dict:
    from ytsaurus_tpu.server.view_daemon import views_snapshot
    return {"daemons": views_snapshot()}


def _sanitizer_producer() -> dict:
    from ytsaurus_tpu.utils import sanitizers
    return sanitizers.snapshot()


def _serving_producer() -> dict:
    from ytsaurus_tpu.query.serving import serving_snapshot
    return {"gateways": serving_snapshot()}
