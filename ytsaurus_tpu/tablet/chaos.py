"""Chaos replication: replication cards, eras, coordinated sync cutover.

Ref mapping:
  replication cards + eras (server/master/chaos_server/,
    client/chaos_client/replication_card.h) → a per-table
    @replication_card document: {era, history[{era, reason, modes, ts}]}.
    Every configuration change (which replica is synchronous) bumps the
    era and appends a history entry, so participants can tell WHICH
    configuration a write ran under.
  chaos_agent.h (era-driven reconfiguration) → writers observe the card
    era when they enroll sync replicas in a commit; a commit that raced
    an era change re-delivers its events to the new configuration
    (idempotent: replicated applies preserve upstream timestamps, so a
    double delivery converges to the same version).
  switchable sync coordinator → switch_sync(): joint-era cutover.  The
    NEW sync replica is enrolled in the 2PC fanout FIRST (joint era:
    both old and new are synchronous — there is never a window without
    a synchronous copy), then the gap between its async checkpoint and
    the flip is closed by an idempotent catch-up, then the old sync is
    demoted.  A crash mid-switch leaves an over-synchronous
    configuration, never an unprotected one.

Design delta (TPU-first, consistent with tablet/replication.py): the
versioned snapshot planes ARE the replication log, so "catch up the gap"
is the same vectorized events_since filter the async replicator uses,
and the card is plain Cypress metadata riding the master WAL — no
separate chaos cell process.
"""

from __future__ import annotations

import time

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.tablet import replication as repl

CARD_ATTR = "replication_card"


def get_card(client, table_path: str) -> dict | None:
    node = client._table_node(table_path)
    card = node.attributes.get(CARD_ATTR)
    return dict(card) if card else None


def current_era(client, table_path: str) -> int:
    """Era 0 = no card yet (plain replicated table, pre-chaos)."""
    card = get_card(client, table_path)
    return int(card["era"]) if card else 0


def redeliver_commit(client, table_path: str, commit_ts: int) -> None:
    """Compensator for a commit that raced an era change: deliver this
    commit's events to every CURRENTLY enabled sync replica, bypassing
    the (possibly already advanced) checkpoint.  Safe to run even when
    nothing was missed — applies preserve upstream timestamps, so
    re-delivery is idempotent."""
    events = repl.events_since(client, table_path, commit_ts - 1)
    if not events:
        return
    for rid, rc, rpath in client._sync_replica_targets(table_path):
        repl.apply_events(rc, rpath, events)


class ChaosCoordinator:
    """Drives replication-card eras for one cluster's client."""

    def __init__(self, client):
        self.client = client

    def ensure_card(self, table_path: str) -> dict:
        card = get_card(self.client, table_path)
        if card is None:
            replicas = repl.replica_descriptors(self.client, table_path)
            card = {"era": 1, "history": [{
                "era": 1, "reason": "created",
                "modes": {rid: info.get("mode")
                          for rid, info in replicas.items()},
                "ts": time.time()}]}
            self._store(table_path, card)
        return card

    def era(self, table_path: str) -> int:
        return int(self.ensure_card(table_path)["era"])

    def _store(self, table_path: str, card: dict) -> None:
        self.client.set(table_path + "/@" + CARD_ATTR, card)

    def _bump(self, table_path: str, reason: str) -> int:
        """Era bump as an ATOMIC read-modify-write: the whole get+set
        runs under the master's mutation lock, so two coordinators
        (threads, or remote drivers executing inside the same leader
        process) cannot both read era N and store N+1 — a lost bump
        would let a racing writer's post-commit era check pass without
        re-delivering to the new configuration.  Multi-master safety
        comes from the coordinator living with the LEADER (a follower's
        writes are fenced by the WAL epoch), matching the reference's
        single chaos cell owning each card."""
        with self.client.cluster.master.mutation_lock:
            card = self.ensure_card(table_path)
            replicas = repl.replica_descriptors(self.client, table_path)
            card["era"] = int(card["era"]) + 1
            card["history"] = list(card["history"]) + [{
                "era": card["era"], "reason": reason,
                "modes": {rid: info.get("mode")
                          for rid, info in replicas.items()},
                "ts": time.time()}]
            self._store(table_path, card)
            return card["era"]

    def _catch_up_from(self, table_path: str, replica_id: str,
                       from_ts: int) -> int:
        """Close the (from_ts, now] gap on one replica regardless of its
        current checkpoint (idempotent over preserved timestamps), then
        raise the checkpoint so the async replicator does not replay."""
        replicas = repl.replica_descriptors(self.client, table_path)
        info = replicas.get(replica_id)
        if info is None:
            raise YtError(f"No such replica {replica_id!r}",
                          code=EErrorCode.ResolveError)
        rc = self.client.table_replicator.replica_client(
            info.get("cluster_root"))
        events = repl.events_since(self.client, table_path, from_ts)
        applied = repl.apply_events(rc, info["path"], events)
        if events:
            head = max(e[0] for e in events)
            replicas = repl.replica_descriptors(self.client, table_path)
            entry = replicas[replica_id]
            entry["last_replicated_ts"] = max(
                int(entry.get("last_replicated_ts", 0)), head)
            repl.set_replica_descriptors(self.client, table_path, replicas)
        return applied

    def switch_sync(self, table_path: str, new_sync_id: str) -> int:
        """Coordinated sync cutover; returns the resulting era.

        Order of operations is the safety argument:
        1. JOINT ERA — the new sync replica joins the 2PC fanout while
           the old one is still synchronous.  From this point no commit
           can miss the new replica; writes in flight from the previous
           era are handled by (2) or by the client's era re-check.
        2. GAP CATCH-UP — events between the replica's pre-flip
           checkpoint and the flip are re-delivered idempotently.
        3. SWITCHED ERA — the old sync replica(s) drop to async.
        """
        replicas = repl.replica_descriptors(self.client, table_path)
        info = replicas.get(new_sync_id)
        if info is None:
            raise YtError(f"No such replica {new_sync_id!r}",
                          code=EErrorCode.ResolveError)
        if not info.get("enabled"):
            raise YtError(f"Replica {new_sync_id!r} is disabled",
                          code=EErrorCode.InvalidTransactionState)
        old_syncs = [rid for rid, i in replicas.items()
                     if i.get("mode") == "sync" and rid != new_sync_id]
        if info.get("mode") == "sync":
            return self.era(table_path)         # already the sync replica
        pre_ckpt = int(info.get("last_replicated_ts", 0))
        self.client.alter_table_replica(table_path, new_sync_id,
                                        mode="sync")
        self._bump(table_path, f"joint:{new_sync_id}")
        self._catch_up_from(table_path, new_sync_id, pre_ckpt)
        for rid in old_syncs:
            self.client.alter_table_replica(table_path, rid, mode="async")
        return self._bump(table_path, f"switched:{new_sync_id}")
