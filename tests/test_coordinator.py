"""Distributed coordination tests: bottom/front split over multiple shards.

Ref behavior model: ytlib/query_client/executor.cpp (fan-out + front merge)
and library/query/unittests/ql_distributed_ut.cpp.
"""

import pytest

from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.coordinator import coordinate_and_execute, split_plan
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")])
T = "//t"


def _shards(rows_per_shard):
    return [ColumnarChunk.from_rows(SCHEMA, rows) for rows in rows_per_shard]


def _run(query, shards, expected=None, ordered=False):
    plan = build_query(query, {T: SCHEMA})
    out = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    rows = out.to_rows()
    if expected is not None:
        key = (lambda r: tuple(
            (v is None, v) for v in r.values()))
        if ordered:
            assert rows == expected, f"{rows} != {expected}"
        else:
            assert sorted(rows, key=key) == sorted(expected, key=key), \
                f"{rows} != {expected}"
    return rows


SHARDS = _shards([
    [(0, 0, 1), (1, 1, 2), (2, 0, 3)],
    [(3, 1, 4), (4, 0, 5)],
    [(5, 2, 6)],
])


def test_distributed_filter_project():
    _run(f"k, v FROM [{T}] WHERE v >= 3", SHARDS,
         [{"k": 2, "v": 3}, {"k": 3, "v": 4}, {"k": 4, "v": 5},
          {"k": 5, "v": 6}])


def test_distributed_group_by_sum_count():
    _run(f"g, sum(v) AS s, count(*) AS c FROM [{T}] GROUP BY g", SHARDS,
         [{"g": 0, "s": 9, "c": 3}, {"g": 1, "s": 6, "c": 2},
          {"g": 2, "s": 6, "c": 1}])


def test_distributed_avg_is_exact():
    # avg must merge via (sum, count) states, not averaging shard averages:
    # g=0 values 1,3 on shard A and 5 on shard B → avg 3.0 (naive merge of
    # shard avgs would give (2.0 + 5.0)/2 = 3.5).
    _run(f"g, avg(v) AS a FROM [{T}] GROUP BY g", SHARDS,
         [{"g": 0, "a": 3.0}, {"g": 1, "a": 3.0}, {"g": 2, "a": 6.0}])


def test_distributed_min_max_first_merge():
    _run(f"g, min(v) AS lo, max(v) AS hi FROM [{T}] GROUP BY g", SHARDS,
         [{"g": 0, "lo": 1, "hi": 5}, {"g": 1, "lo": 2, "hi": 4},
          {"g": 2, "lo": 6, "hi": 6}])


def test_distributed_having_applies_at_front():
    # HAVING must see MERGED aggregates (g=0 total 9 > 8, but no single
    # shard's partial sum exceeds 8 except none → naive per-shard having
    # would drop g=0).
    _run(f"g, sum(v) AS s FROM [{T}] GROUP BY g HAVING sum(v) > 8", SHARDS,
         [{"g": 0, "s": 9}])


def test_distributed_order_by_limit():
    _run(f"k FROM [{T}] ORDER BY v DESC LIMIT 3", SHARDS,
         [{"k": 5}, {"k": 4}, {"k": 3}], ordered=True)


def test_distributed_offset_limit():
    _run(f"k FROM [{T}] ORDER BY k OFFSET 2 LIMIT 2", SHARDS,
         [{"k": 2}, {"k": 3}], ordered=True)


def test_distributed_avg_in_having_and_order():
    _run(f"g, avg(v) AS a FROM [{T}] GROUP BY g HAVING avg(v) > 2.5 "
         f"ORDER BY avg(v) DESC, g LIMIT 10", SHARDS,
         [{"g": 2, "a": 6.0}, {"g": 0, "a": 3.0}, {"g": 1, "a": 3.0}],
         ordered=True)


def test_distributed_join():
    dim_schema = TableSchema.make([("g", "int64", "ascending"),
                                   ("name", "string")])
    dim = ColumnarChunk.from_rows(dim_schema, [(0, "zero"), (1, "one"),
                                               (2, "two")])
    plan = build_query(
        f"name, sum(v) AS s FROM [{T}] JOIN [//dim] USING g GROUP BY name",
        {T: SCHEMA, "//dim": dim_schema})
    out = coordinate_and_execute(plan, SHARDS, {"//dim": dim},
                                 evaluator=Evaluator())
    rows = sorted(out.to_rows(), key=lambda r: r["name"])
    assert rows == [{"name": b"one", "s": 6}, {"name": b"two", "s": 6},
                    {"name": b"zero", "s": 9}]


def test_split_plan_shapes():
    plan = build_query(
        f"g, avg(v) AS a FROM [{T}] GROUP BY g HAVING avg(v) > 0", {T: SCHEMA})
    bottom, front = split_plan(plan)
    # Bottom: no having/order/project, avg decomposed into sum+count states.
    assert bottom.having is None and bottom.project is None
    agg_names = [a.name for a in bottom.group.aggregate_items]
    assert [n.endswith("__s") or n.endswith("__c") for n in agg_names] == \
        [True, True]
    # Front merges states and re-applies having.
    assert front.having is not None
    assert [a.function for a in front.group.aggregate_items] == ["sum", "sum"]


def test_string_group_keys_across_shards():
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string")])
    shards = [
        ColumnarChunk.from_rows(schema, [(1, "x"), (2, "y")]),
        ColumnarChunk.from_rows(schema, [(3, "y"), (4, "z")]),
    ]
    plan = build_query(f"s, count(*) AS c FROM [{T}] GROUP BY s",
                       {T: schema})
    out = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    rows = sorted(out.to_rows(), key=lambda r: r["s"])
    assert rows == [{"s": b"x", "c": 1}, {"s": b"y", "c": 2},
                    {"s": b"z", "c": 1}]


def test_distributed_cardinality_exact():
    # Duplicates span shards: per-shard counts cannot merge; must be exact.
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    shards = [ColumnarChunk.from_rows(schema, [(1, 0, 5), (2, 0, 7)]),
              ColumnarChunk.from_rows(schema, [(3, 0, 5), (4, 1, 1)]),
              ColumnarChunk.from_rows(schema, [(5, 1, 1), (6, 1, 2)])]
    plan = build_query(f"g, cardinality(v) AS d FROM [{T}] GROUP BY g",
                       {T: schema})
    out = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    assert sorted((r["g"], r["d"]) for r in out.to_rows()) == \
        [(0, 2), (1, 2)]


def test_distributed_with_totals():
    plan = build_query(
        f"g, sum(v) AS s FROM [{T}] GROUP BY g WITH TOTALS", {T: SCHEMA})
    out = coordinate_and_execute(plan, SHARDS, evaluator=Evaluator())
    rows = out.to_rows()
    totals = [r for r in rows if r["g"] is None]
    assert totals == [{"g": None, "s": 21}]
    assert sorted((r["g"], r["s"]) for r in rows if r["g"] is not None) == \
        [(0, 9), (1, 6), (2, 6)]


def test_distributed_argmax_merges_across_shards():
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("name", "string"), ("score", "int64")])
    shards = [
        ColumnarChunk.from_rows(schema, [(1, 0, "a", 10), (2, 0, "b", 30)]),
        ColumnarChunk.from_rows(schema, [(3, 0, "c", 20), (4, 1, "d", 5)]),
        ColumnarChunk.from_rows(schema, [(5, 1, "e", 50)]),
    ]
    plan = build_query(
        f"g, argmax(name, score) AS top FROM [{T}] GROUP BY g", {T: schema})
    out = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    assert sorted((r["g"], r["top"]) for r in out.to_rows()) == \
        [(0, b"b"), (1, b"e")]


def test_distributed_mixed_aggregate_order_stable():
    # Output column order with project=None must match single-node even when
    # argmax/avg states are decomposed for the merge.
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("s", "string"), ("v", "int64")])
    rows = [(1, 0, "a", 3), (2, 0, "b", 9), (3, 1, "c", 4)]
    shards = [ColumnarChunk.from_rows(schema, rows[:2]),
              ColumnarChunk.from_rows(schema, rows[2:])]
    plan = build_query(
        f"* FROM [{T}] GROUP BY g", {T: schema})
    # build a grouped plan with mixed aggregates via explicit query:
    plan = build_query(
        "g, sum(v) AS s1, argmax(s, v) AS am, avg(v) AS a FROM [//t] "
        "GROUP BY g", {T: schema})
    single = coordinate_and_execute(plan, [ColumnarChunk.from_rows(
        schema, rows)], evaluator=Evaluator())
    multi = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    assert single.schema.column_names == multi.schema.column_names
    key = lambda r: r["g"]
    assert sorted(single.to_rows(), key=key) == sorted(multi.to_rows(),
                                                       key=key)


def test_order_by_key_prefix_early_exit():
    """ORDER BY over the shard-range key + LIMIT walks shards from the
    matching end and stops (ref ordered scans w/ scanOrder,
    engine_api/coordinator.h:81-90)."""
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.statistics import QueryStatistics
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("v", "int64")])
    shards = [ColumnarChunk.from_rows(
        schema, [(i * 100 + j, j) for j in range(10)]) for i in range(6)]

    # ASC: only the first shard needed.
    plan = build_query("k FROM [//t] ORDER BY k LIMIT 5", {"//t": schema})
    stats = QueryStatistics()
    out = coordinate_and_execute(plan, shards, range_ordered_by=["k"],
                                 stats=stats)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2, 3, 4]
    assert stats.shards_skipped == 5

    # DESC: scan from the tail.
    plan = build_query("k FROM [//t] ORDER BY k DESC LIMIT 3",
                       {"//t": schema})
    stats = QueryStatistics()
    out = coordinate_and_execute(plan, shards, range_ordered_by=["k"],
                                 stats=stats)
    assert [r["k"] for r in out.to_rows()] == [509, 508, 507]
    assert stats.shards_skipped == 5

    # WHERE keeps scanning until enough rows PASS the filter.
    plan = build_query("k FROM [//t] WHERE v >= 8 ORDER BY k LIMIT 4",
                       {"//t": schema})
    stats = QueryStatistics()
    out = coordinate_and_execute(plan, shards, range_ordered_by=["k"],
                                 stats=stats)
    assert [r["k"] for r in out.to_rows()] == [8, 9, 108, 109]
    assert stats.shards_skipped == 4

    # OFFSET counts toward the scan budget.
    plan = build_query("k FROM [//t] ORDER BY k OFFSET 12 LIMIT 3",
                       {"//t": schema})
    out = coordinate_and_execute(plan, shards, range_ordered_by=["k"])
    assert [r["k"] for r in out.to_rows()] == [102, 103, 104]

    # Non-key ORDER BY must NOT early-exit.
    plan = build_query("k FROM [//t] ORDER BY v LIMIT 3", {"//t": schema})
    stats = QueryStatistics()
    coordinate_and_execute(plan, shards, range_ordered_by=["k"],
                           stats=stats)
    assert stats.shards_skipped == 0

    # Mixed directions must NOT early-exit.
    plan = build_query("k FROM [//t] ORDER BY k, v DESC LIMIT 3",
                       {"//t": schema})
    stats = QueryStatistics()
    coordinate_and_execute(plan, shards, range_ordered_by=["k", "v"],
                           stats=stats)
    assert stats.shards_skipped == 0

    # No range info: behave exactly as before.
    plan = build_query("k FROM [//t] ORDER BY k LIMIT 5", {"//t": schema})
    stats = QueryStatistics()
    out = coordinate_and_execute(plan, shards, stats=stats)
    assert [r["k"] for r in out.to_rows()] == [0, 1, 2, 3, 4]
    assert stats.shards_skipped == 0


def test_order_by_early_exit_via_dynamic_table(tmp_path):
    """End-to-end: a resharded sorted dynamic table serves ORDER BY key
    LIMIT scanning only the needed tablets."""
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    cl = connect(str(tmp_path / "c"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    cl.create("table", "//o/t", recursive=True,
              attributes={"schema": schema, "dynamic": True})
    cl.reshard_table("//o/t", pivot_keys=[(100,), (200,), (300,)])
    cl.mount_table("//o/t")
    cl.insert_rows("//o/t", [{"k": k, "v": k * 2} for k in range(0, 400)])
    rows = cl.select_rows("k, v FROM [//o/t] ORDER BY k DESC LIMIT 3")
    assert [r["k"] for r in rows] == [399, 398, 397]
    assert cl.last_query_statistics.shards_skipped >= 1
    rows = cl.select_rows("k FROM [//o/t] ORDER BY k LIMIT 2")
    assert [r["k"] for r in rows] == [0, 1]
    assert cl.last_query_statistics.shards_skipped >= 1


def test_limit_early_exit_skips_shards():
    """A bare LIMIT (no ORDER BY/GROUP BY) stops launching shard programs
    once enough rows are collected (ref pull-model limit stop)."""
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.statistics import QueryStatistics
    from ytsaurus_tpu.schema import TableSchema

    schema = TableSchema.make([("k", "int64")])
    shards = [ColumnarChunk.from_rows(
        schema, [{"k": i * 100 + j} for j in range(10)]) for i in range(6)]
    plan = build_query("k FROM [//t] LIMIT 15", {"//t": schema})
    stats = QueryStatistics()
    out = coordinate_and_execute(plan, shards, stats=stats)
    assert out.row_count == 15
    assert stats.shards_skipped == 4          # 2 shards gave 20 >= 15
    # ORDER BY must NOT early-exit (needs every shard).
    plan2 = build_query("k FROM [//t] ORDER BY k DESC LIMIT 3",
                        {"//t": schema})
    stats2 = QueryStatistics()
    out2 = coordinate_and_execute(plan2, shards, stats=stats2)
    assert stats2.shards_skipped == 0
    assert [r["k"] for r in out2.to_rows()] == [509, 508, 507]
    # WHERE + LIMIT: filtered shards keep the scan going until satisfied.
    plan3 = build_query("k FROM [//t] WHERE k >= 500 LIMIT 5",
                        {"//t": schema})
    stats3 = QueryStatistics()
    out3 = coordinate_and_execute(plan3, shards, stats=stats3)
    assert out3.row_count == 5
    assert stats3.shards_skipped == 0         # only the last shard matches
