"""Discovery server: named group membership with TTL'd heartbeats.

Ref: yt/yt/server/discovery_server (+ client/api discovery requests) —
processes publish themselves into hierarchical groups and clients list
live members instead of carrying hardcoded peer lists.  The framework's
NodeTracker is the special case for data nodes; this generalizes the
same lease model to arbitrary groups (query trackers, proxies, custom
services).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Service, rpc_method
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils import sanitizers

# Every telemetry-bearing daemon self-registers here (member address =
# its MONITORING endpoint): the primary's /cluster roll-up lists this
# group and scrapes each member's /telemetry (server/monitoring.py).
DAEMONS_GROUP = "/daemons"


def announce_daemon(tracker: "DiscoveryTracker", member_id: str,
                    monitoring_address: str, role: str,
                    period: float = 5.0) -> threading.Thread:
    """In-process self-registration loop (primary-side daemons): keeps
    this process's monitoring endpoint alive in the tracker's /daemons
    group.  Remote daemons (data nodes) heartbeat the same group over
    the discovery RPC service instead (server/daemon.py beat loop)."""
    def loop() -> None:
        while True:
            tracker.heartbeat(DAEMONS_GROUP, member_id,
                              address=monitoring_address,
                              attributes={"role": role})
            time.sleep(period)

    thread = threading.Thread(target=loop, daemon=True,
                              name=f"daemon-announce-{member_id}")
    thread.start()
    return thread


class DiscoveryTracker:
    """Group → member_id → (address, attributes, expiry)."""

    def __init__(self, member_ttl: float = 15.0):
        self.member_ttl = member_ttl
        self._groups: dict[str, dict[str, dict]] = {}
        # guards: _groups
        self._lock = sanitizers.register_lock(
            "discovery.DiscoveryTracker._lock")

    @staticmethod
    def _check_group(group: str) -> str:
        if not group.startswith("/") or group.endswith("/") or \
                "//" in group[1:]:
            raise YtError(f"Bad group id {group!r} (use /a/b form)",
                          code=EErrorCode.ResolveError)
        return group

    def heartbeat(self, group: str, member_id: str, address: str = "",
                  attributes: Optional[dict] = None) -> None:
        group = self._check_group(group)
        with self._lock:
            members = self._groups.setdefault(group, {})
            members[member_id] = {
                "address": address,
                "attributes": dict(attributes or {}),
                "expiry": time.monotonic() + self.member_ttl,
            }

    def leave(self, group: str, member_id: str) -> None:
        with self._lock:
            members = self._groups.get(self._check_group(group)) or {}
            members.pop(member_id, None)

    def _alive_locked(self, group: str) -> dict[str, dict]:
        now = time.monotonic()
        members = self._groups.get(group) or {}
        live = {m: info for m, info in members.items()
                if info["expiry"] > now}
        if len(live) != len(members):
            self._groups[group] = live
        return live

    def list_members(self, group: str) -> list[dict]:
        group = self._check_group(group)
        with self._lock:
            live = self._alive_locked(group)
            return sorted(
                ({"id": m, "address": info["address"],
                  "attributes": dict(info["attributes"])}
                 for m, info in live.items()),
                key=lambda e: e["id"])

    def list_groups(self, prefix: str = "/") -> list[str]:
        prefix = prefix.rstrip("/") or "/"
        with self._lock:
            # Segment-aware: '/proxies' matches '/proxies/http' but not
            # '/proxiesold'.
            return sorted(
                g for g in self._groups
                if (prefix == "/" or g == prefix or
                    g.startswith(prefix + "/"))
                and self._alive_locked(g))


class DiscoveryService(Service):
    name = "discovery"

    def __init__(self, tracker: Optional[DiscoveryTracker] = None):
        self.tracker = tracker or DiscoveryTracker()

    @rpc_method()
    def heartbeat(self, body, attachments):
        self.tracker.heartbeat(
            _text(body["group"]), _text(body["member_id"]),
            address=_text(body.get("address") or ""),
            attributes=body.get("attributes") or {})
        return {"ttl": self.tracker.member_ttl}

    @rpc_method()
    def leave(self, body, attachments):
        self.tracker.leave(_text(body["group"]),
                           _text(body["member_id"]))
        return {}

    @rpc_method()
    def list_members(self, body, attachments):
        return {"members": self.tracker.list_members(
            _text(body["group"]))}

    @rpc_method()
    def list_groups(self, body, attachments):
        return {"groups": self.tracker.list_groups(
            _text(body.get("prefix") or "/"))}
