"""View daemon: the continuous-query refresh loop (ISSUE 13).

One daemon per cluster tails EVERY registered materialized view
(query/views.py): each pass walks the //sys/views registry, reloads
specs (so `yt view pause` and spec edits take effect between batches),
and drains each running view's ordered-source cursor in micro-batches —
each batch's target upsert and offset commit in one 2PC transaction, so
killing the daemon anywhere (including mid-batch) and starting a new one
resumes from the committed offsets with no loss and no double-apply.

Restart recovery is therefore trivial by construction: the daemon keeps
NO durable state of its own — the consumer table IS the checkpoint, and
the compiled programs a fresh daemon needs come back from the AOT disk
tier (ISSUE 10) with 0 fresh compiles.

Pause/resume arrives two ways, both honored per pass:
  - per-view registry state (`yt view pause|resume` → @view_spec.state);
  - dynamic config (config.ViewsConfig): `paused` names and the global
    `enable` switch — wire `daemon.apply_config` as a
    DynamicConfigManager subscriber to drive it from a config document.

The daemon registers itself in a process-wide set; `views_snapshot()`
feeds the monitoring `/views` endpoint and the `/views` orchid mount.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional

from ytsaurus_tpu.config import ViewsConfig, views_config
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query.views import (
    ViewRefresher,
    list_views,
    load_view,
    view_status,
)
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils import sanitizers

_DAEMONS: "weakref.WeakSet[ViewDaemon]" = weakref.WeakSet()

_passes_counter = Profiler("/views").counter("daemon_passes")


class ViewDaemon:
    """Background refresher over the whole view registry."""

    def __init__(self, client, config: Optional[ViewsConfig] = None,
                 evaluator=None):
        self.client = client
        self._config = config
        self._evaluator = evaluator
        # guards: _refreshers, _stats
        self._lock = sanitizers.register_lock(
            "view_daemon.ViewDaemon._lock")
        self._refreshers: dict[str, ViewRefresher] = {}
        self._stats: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.passes = 0
        _DAEMONS.add(self)

    @property
    def config(self) -> ViewsConfig:
        return self._config if self._config is not None \
            else views_config()

    def apply_config(self, config: ViewsConfig) -> None:
        """Dynamic-config subscriber hook: the next pass sees the new
        pause set / enable switch / batching knobs."""
        self._config = config

    # -- one pass --------------------------------------------------------------

    def _refresher(self, name: str) -> ViewRefresher:
        spec = load_view(self.client, name)
        with self._lock:
            current = self._refreshers.get(name)
            if current is not None and \
                    current.spec.query == spec.query and \
                    current.spec.batch_rows == spec.batch_rows:
                current.spec = spec      # pick up state/pool edits
                return current
            refresher = ViewRefresher(self.client, spec,
                                      evaluator=self._evaluator,
                                      config_provider=lambda: self.config)
            self._refreshers[name] = refresher
            return refresher

    def _is_paused(self, name: str, state: str) -> bool:
        """The ONE pause predicate (step AND snapshot share it): the
        dynamic-config master switch, per-view registry state, and the
        dynamic-config pause list."""
        cfg = self.config
        return (not cfg.enable or state == "paused"
                or name in (cfg.paused or []))

    def step(self) -> dict:
        """One pass over the registry: drain every running view (up to
        max_batches_per_pass each).  Per-view errors are recorded and do
        not stop the pass; an InjectedCrash (simulated process death)
        deliberately pierces — a dead daemon doesn't finish its pass."""
        cfg = self.config
        out: dict[str, dict] = {}
        names = list_views(self.client)
        with self._lock:
            for gone in set(self._refreshers) - set(names):
                self._refreshers.pop(gone, None)
        for name in names:
            try:
                refresher = self._refresher(name)
                if self._is_paused(name, refresher.spec.state):
                    out[name] = {"view": name, "paused": True}
                    continue
                report = refresher.refresh(
                    max_batches=cfg.max_batches_per_pass)
                out[name] = report
                self._note(name, report, None)
            except Exception as err:   # noqa: BLE001 — one broken view
                # (bad spec, dropped source, an XLA error escaping the
                # evaluator) must not stop the other views' refreshes;
                # InjectedCrash is a BaseException and still pierces.
                if isinstance(err, YtError) and \
                        err.code == EErrorCode.TransactionLockConflict:
                    # The documented-safe writer race (a manual
                    # `yt view refresh` won the batch): the loser
                    # replays next pass — a conflict, not a failure.
                    out[name] = {"view": name, "conflict": True}
                    continue
                out[name] = {"view": name, "error": str(err)}
                self._note(name, None, err)
        self.passes += 1
        _passes_counter.increment()
        return out

    def _note(self, name: str, report: Optional[dict],
              err: Optional[Exception]) -> None:
        with self._lock:
            stats = self._stats.setdefault(name, {
                "batches": 0, "rows_in": 0, "rows_out": 0,
                "errors": 0, "last_error": None})
            if report is not None:
                stats["batches"] += report["batches"]
                stats["rows_in"] += report["rows_in"]
                stats["rows_out"] += report["rows_out"]
            if err is not None:
                stats["errors"] += 1
                stats["last_error"] = str(err)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ViewDaemon":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="view-daemon")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:   # noqa: BLE001 — registry-level hiccup
                # (e.g. a view dropped mid-pass): the loop survives;
                # per-view errors were recorded.  A real crash
                # (InjectedCrash, BaseException) still kills the thread
                # the way process death would.
                pass
            self._stop.wait(self.config.poll_interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- monitoring ------------------------------------------------------------

    def snapshot(self) -> dict:
        cfg = self.config
        views: dict[str, dict] = {}
        for name in list_views(self.client):
            try:
                status = view_status(self.client, name)
            except YtError as err:
                views[name] = {"error": str(err)}
                continue
            with self._lock:
                stats = dict(self._stats.get(name) or {})
            status["daemon"] = stats
            status["paused"] = self._is_paused(name, status["state"])
            views[name] = status
        return {"running": self.running, "passes": self.passes,
                "enable": cfg.enable, "paused": list(cfg.paused or []),
                "poll_interval": cfg.poll_interval, "views": views}


def views_snapshot() -> list:
    """Every live daemon's snapshot (the /views monitoring endpoint and
    the /views orchid mount read this)."""
    return [daemon.snapshot() for daemon in list(_DAEMONS)]
