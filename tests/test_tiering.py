"""Adaptive tiered execution (ISSUE 18): the no-compile interpreter
tier (declared coverage, bit-identity against the compiled path over a
mixed-type corpus), the tier dispatcher (interpreted-first cold serving,
hot-shape background promotion with the mid-traffic atomic swap,
kill-switch), capture-driven prewarm (compile-only replay, zero inline
compiles on restart, zero compile-storm alerts), the accounting
discipline (background/prewarm compiles never book as cache misses),
and the observability surfaces (execution_tier in statistics/EXPLAIN
ANALYZE/workload records, flight-recorder promotion events,
/tiers monitoring + tier_snapshot, `yt prewarm`).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine import interp, lowering
from ytsaurus_tpu.query.engine import evaluator as ev_mod
from ytsaurus_tpu.query.engine.evaluator import (
    Evaluator,
    get_compile_observatory,
)
from ytsaurus_tpu.query.engine.prewarm import prewarm_from_capture
from ytsaurus_tpu.query.profile import (
    format_profile_dict,
    get_flight_recorder,
)
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.query.workload import WorkloadRecord
from ytsaurus_tpu.schema import ColumnSchema, EValueType, TableSchema


@pytest.fixture(autouse=True)
def _tiering_defaults():
    """Every test leaves the process-wide tiering config, observatory,
    and flight recorder the way it found them."""
    yield
    yt_config.set_tiering_config(None)
    yt_config.set_workload_config(None)
    get_compile_observatory().reset()
    get_flight_recorder().clear()


def _mixed_chunk(n=200):
    schema = TableSchema(columns=[
        ColumnSchema(name="k", type=EValueType.int64),
        ColumnSchema(name="v", type=EValueType.double),
        ColumnSchema(name="s", type=EValueType.string),
        ColumnSchema(name="b", type=EValueType.boolean),
        ColumnSchema(name="u", type=EValueType.uint64),
    ])
    rng = np.random.RandomState(7)
    rows = []
    for i in range(n):
        rows.append({
            "k": int(rng.randint(0, 5)) if i % 7 else None,
            "v": float(rng.randint(-50, 50)) if i % 5 else None,
            "s": [b"alpha", b"beta", b"gamma", None][i % 4],
            "b": bool(i % 3 == 0) if i % 11 else None,
            "u": int(rng.randint(0, 1 << 40)),
        })
    return schema, ColumnarChunk.from_rows(schema, rows)


def _small_chunk(n=100):
    schema = TableSchema.make([("k", "int64"), ("v", "int64"),
                               ("s", "string")])
    rows = [{"k": i, "v": i * 3 % 17, "s": f"u{i % 5}".encode()}
            for i in range(n)]
    return schema, ColumnarChunk.from_rows(schema, rows)


def _decode(planes, count, output):
    """Planes -> row tuples, None for invalid slots — the tier-agnostic
    result form both engines are compared in."""
    cols = []
    for (d, v), out in zip(planes, output):
        d, v = np.asarray(d), np.asarray(v)
        vals = []
        for i in range(count):
            if not v[i]:
                vals.append(None)
            elif out.type is EValueType.string:
                vals.append(bytes(out.vocab[int(d[i])]))
            elif out.type is EValueType.boolean:
                vals.append(bool(d[i]))
            elif out.type is EValueType.double:
                vals.append(float(d[i]))
            else:
                vals.append(int(d[i]))
        cols.append(vals)
    return list(zip(*cols)) if cols else []


# -- interpreter tier: coverage + bit identity ---------------------------------

# The dual-check corpus: every clause/function family the interpreter
# DECLARES covered, over nullable mixed-type data (nulls in keys,
# strings, aggregates; empty results; offset/limit; having).
CORPUS = [
    "* from t",
    "k, v from t where v > 0",
    "k, sum(v) as sv, count(v) as c, avg(v) as av from t group by k",
    "s, min(v) as mn, max(v) as mx, cardinality(k) as card from t "
    "group by s",
    "k, s, first(v) as fv from t group by k, s order by k, s limit 7",
    "k, argmin(v, u) as am, argmax(s, v) as ax from t group by k",
    "k, v from t order by v desc, k offset 3 limit 10",
    "k from t where s in ('alpha', 'beta') and k between 1 and 3",
    "concat(s, '_x') as cx, length(s) as ln from t where s like 'a%'",
    "if(b, k, -1) as ik, if_null(v, 0.0) as nv from t "
    "where not is_null(k)",
    "k + 1 as k1, k % 3 as k3, k / 2 as k2, double(k) as dk from t",
    "lower(s) as lo, upper(s) as up from t where s >= 'alpha'",
    "timestamp_floor_day(k * 100000) as d from t",
    "min_of(k, 2) as mo, max_of(v, 0.0) as xo, abs(v) as ab from t",
    "u, k from t order by u limit 5",
    "k, sum(v) as sv from t group by k having sum(v) > 0 "
    "order by sum(v) desc limit 20",
    "b, count(k) as c from t group by b order by b limit 20",
    "k from t where v > 1000",                     # empty result
    "s from t where s between 'aa' and 'bz'",
]


@pytest.mark.parametrize("query", CORPUS)
def test_interpreter_bit_identity(query):
    """ISSUE 18 acceptance: for every covered shape the interpreter's
    planes decode to EXACTLY the compiled program's rows — same values,
    same validity, same count, same order."""
    schema, chunk = _mixed_chunk()
    plan = build_query("select " + query, {"t": schema})
    assert interp.covers(plan), query
    iq = interp.try_prepare(plan, chunk)
    assert iq is not None
    planes_i, count_i = iq.execute(chunk)
    assert isinstance(count_i, int)                # host int, no sync
    prepared = lowering.prepare(plan, chunk)
    columns = {name: (col.data, col.valid)
               for name, col in chunk.columns.items()}
    planes_c, count_c = prepared.run(columns, chunk.row_valid,
                                     tuple(prepared.bindings))
    assert _decode(planes_i, count_i, iq.output) == \
        _decode(planes_c, int(count_c), prepared.output)


def test_coverage_is_declared_not_guessed():
    """Shapes outside the allow-list say so BEFORE execution: joins,
    window functions, uncovered functions."""
    schema, _chunk = _small_chunk()
    other = TableSchema.make([("jk", "int64"), ("w", "int64")])
    covered = build_query("select k, v from t where v > 1",
                          {"t": schema})
    assert interp.covers(covered)
    joined = build_query(
        "select k, w from t join u on k = jk",
        {"t": schema, "u": other})
    assert not interp.covers(joined)
    windowed = build_query(
        "select k, sum(v) over (partition by s) as sv from t",
        {"t": schema})
    assert not interp.covers(windowed)             # window functions
    farmed = build_query("select farm_hash(k) as h from t",
                         {"t": schema})
    assert not interp.covers(farmed)               # uncovered function
    assert interp.try_prepare(farmed, _chunk) is None


def test_uncovered_shape_falls_through_to_inline_compile():
    """Tiering ON + uncovered shape = the classic inline-compile path:
    compiled tier, one miss booked, no interpreter involvement."""
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=1))
    schema, chunk = _small_chunk()
    plan = build_query("select farm_hash(k) as h from t limit 4",
                       {"t": schema})
    assert not interp.covers(plan)
    e = Evaluator()
    stats = QueryStatistics()
    e.run_plan(plan, chunk, stats=stats)
    assert stats.execution_tier == "compiled"
    assert stats.compile_count == 1
    assert e._background.queue_depth() == 0


# -- tier dispatcher: lifecycle, swap, kill switch -----------------------------

def test_tier_lifecycle_interpreted_promoted_compiled():
    """The full ladder on one hot shape: cold dispatches serve
    interpreted (zero misses booked), the hot-threshold crossing
    enqueues ONE background promotion, the first post-promotion serve
    tags promoted-midstream, steady state is compiled — and every tier
    returns identical rows."""
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=2))
    schema, chunk = _small_chunk()
    plan = build_query(
        "select k, v from t where v > 3 order by v desc, k limit 5",
        {"t": schema})
    e = Evaluator()
    obs = get_compile_observatory()
    before = obs.totals()
    results, tiers = [], []
    for _ in range(2):
        stats = QueryStatistics()
        results.append(e.run_plan(plan, chunk, stats=stats).to_rows())
        tiers.append(stats.execution_tier)
    assert tiers == ["interpreted", "interpreted"]
    e._background.drain(timeout=120)
    for _ in range(2):
        stats = QueryStatistics()
        results.append(e.run_plan(plan, chunk, stats=stats).to_rows())
        tiers.append(stats.execution_tier)
    assert tiers[2:] == ["promoted-midstream", "compiled"]
    assert all(r == results[0] for r in results[1:])
    after = obs.totals()
    assert after["misses"] - before["misses"] == 0
    assert after["background_compiles"] - \
        before["background_compiles"] == 1
    # The promotion event landed in the flight recorder with the
    # interpreted-run count that triggered it.
    events = [p for p in get_flight_recorder().promotions()]
    assert events and events[-1]["runs_interpreted"] >= 2
    assert events[-1]["compile_seconds"] > 0


def test_midtraffic_swap_under_8_threads():
    """8 serving threads hammer one cold shape while the background
    compiler swaps the program in: no torn results (every response
    decodes to the same rows), EXACTLY one background compile, zero
    inline misses, and the key ends compiled."""
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=2))
    schema, chunk = _small_chunk(256)
    plan = build_query(
        "select k, v from t where v > 2 order by v desc, k limit 9",
        {"t": schema})
    e = Evaluator()
    obs = get_compile_observatory()
    before = obs.totals()
    expected = None
    outcomes, errors = [], []
    lock = threading.Lock()

    def serve(n):
        try:
            for _ in range(n):
                stats = QueryStatistics()
                rows = e.run_plan(plan, chunk, stats=stats).to_rows()
                with lock:
                    outcomes.append((stats.execution_tier, rows))
        except Exception as exc:   # noqa: BLE001 — surfaced below
            with lock:
                errors.append(exc)

    threads = [threading.Thread(target=serve, args=(6,))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    e._background.drain(timeout=120)
    assert not errors, errors
    expected = e.run_plan(plan, chunk).to_rows()
    assert all(rows == expected for _tier, rows in outcomes)
    seen_tiers = {tier for tier, _rows in outcomes}
    assert seen_tiers <= {"interpreted", "promoted-midstream",
                          "compiled"}
    assert "interpreted" in seen_tiers     # the cold burst never waited
    after = obs.totals()
    assert after["background_compiles"] - \
        before["background_compiles"] == 1
    assert after["misses"] - before["misses"] == 0
    assert e._background.compiled_n == 1
    stats = QueryStatistics()
    e.run_plan(plan, chunk, stats=stats)
    assert stats.execution_tier == "compiled"


def test_kill_switch_restores_inline_compilation():
    """TieringConfig.enabled=False (the default) is the rollout gate:
    dispatch behaves exactly as before the tier existed."""
    yt_config.set_tiering_config(None)
    schema, chunk = _small_chunk()
    plan = build_query("select k, v from t where v > 3 limit 5",
                       {"t": schema})
    e = Evaluator()
    stats = QueryStatistics()
    e.run_plan(plan, chunk, stats=stats)
    assert stats.execution_tier == "compiled"
    assert stats.compile_count == 1
    assert e._governor.snapshot() == []
    assert e._background.snapshot()["compiled"] == 0


def test_governor_arms_once_and_rearms():
    gov = ev_mod.TierGovernor()
    assert not gov.note_interpreted("fp", 0.01, threshold=2)
    assert gov.note_interpreted("fp", 0.01, threshold=2)
    assert not gov.note_interpreted("fp", 0.01, threshold=2)
    gov.rearm("fp")                 # dropped enqueue re-arms the shape
    assert gov.note_interpreted("fp", 0.01, threshold=2)
    assert gov.runs("fp") == 4
    assert gov.snapshot()[0]["runs"] == 4


def test_tier_snapshot_shape():
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=3))
    e = Evaluator()
    snap = e.tier_snapshot()
    assert snap["enabled"] is True
    assert snap["hot_threshold"] == 3
    assert set(snap["background"]) == {"queue_depth", "compiled",
                                       "dropped",
                                       "pending_promoted_tags"}
    assert snap["fingerprints"] == []


# -- observatory + sensor discipline -------------------------------------------

def test_background_ledger_never_touches_miss_books():
    """ISSUE 18 satellite: background promotions classify as
    `background_promotion` in SEPARATE books — the hits/misses totals
    the pool-sensor reconciliation and the storm SLO read stay
    untouched."""
    obs = get_compile_observatory()
    before = obs.totals()
    obs.observe_background("fp-bg", ("fp-bg", 128, ()), 0.25)
    after = obs.totals()
    assert after["misses"] == before["misses"]
    assert after["hits"] == before["hits"]
    assert after["background_compiles"] - \
        before["background_compiles"] == 1
    entry = next(e for e in obs.snapshot(top=50)["fingerprints"]
                 if e["fingerprint"] == "fp-bg")
    assert entry["last_miss_cause"] == "background_promotion"
    assert entry["compiles"] == 0          # inline books untouched
    assert entry["background_compiles"] == 1


def test_interpreted_serves_book_zero_cache_traffic():
    """An interpreted dispatch is NOT compile-cache traffic: no hit, no
    miss, no observatory entry churn — only /query/tiers counters."""
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=100))
    schema, chunk = _small_chunk()
    plan = build_query("select k from t where v > 3 limit 4",
                       {"t": schema})
    e = Evaluator()
    obs = get_compile_observatory()
    before = obs.totals()
    for _ in range(5):
        stats = QueryStatistics()
        e.run_plan(plan, chunk, stats=stats)
        assert stats.execution_tier == "interpreted"
        assert stats.compile_count == 0 and stats.cache_hits == 0
    after = obs.totals()
    assert (after["hits"], after["misses"]) == \
        (before["hits"], before["misses"])


# -- capture-driven prewarm ----------------------------------------------------

def _shape_records(schema):
    queries = [
        "k, v FROM [//t] WHERE v > 3 ORDER BY v desc, k LIMIT 5",
        "v, sum(k) AS total FROM [//t] GROUP BY v",
        "k FROM [//t] WHERE v >= 2 AND v <= 9 LIMIT 11",
        "s, max(v) AS mx FROM [//t] GROUP BY s",
        "k, v FROM [//t] ORDER BY k desc LIMIT 3",
        "v, min(k) AS mn FROM [//t] GROUP BY v ORDER BY v LIMIT 20",
    ]
    return queries, [WorkloadRecord(kind="select", query=q, literals=[])
                     for q in queries]


def test_prewarm_restart_serves_zero_inline_compiles():
    """ISSUE 18 acceptance: a fresh evaluator prewarmed from a capture
    serves every captured shape with compile_count == 0 — the first
    real dispatch is a memory-LRU hit."""
    schema, chunk = _small_chunk()
    queries, records = _shape_records(schema)
    e = Evaluator()
    report = prewarm_from_capture(records, tables={"//t": chunk},
                                  evaluator=e)
    assert report["compiled"] == len(queries)
    assert report["skipped"] == 0
    for q in queries:
        stats = QueryStatistics()
        e.run_plan(build_query(q, {"//t": schema}), chunk, stats=stats)
        assert stats.compile_count == 0, q
        assert stats.cache_hits == 1
        assert stats.execution_tier == "compiled"
    again = prewarm_from_capture(records, tables={"//t": chunk},
                                 evaluator=e)
    assert again["compiled"] == 0
    assert again["already_cached"] == len(queries)


def test_prewarm_fires_zero_storm_alerts():
    """The regression the ISSUE names: a full prewarm replay books its
    compiles in the background ledger, so the compile-storm SLO —
    which reads /query/compile_cache hit/miss deltas — stays quiet
    through the entire warm-up."""
    from ytsaurus_tpu.query import workload as wl
    from ytsaurus_tpu.utils.profiling import MetricsHistory, get_registry
    from ytsaurus_tpu.utils.slo import SloTracker
    slo = dict(wl.COMPILE_STORM_SLO, fast_window=60.0, slow_window=300.0)
    tcfg = yt_config.TelemetryConfig.from_dict(
        {"slos": {"compile_storm": slo}})
    history = MetricsHistory(registry=get_registry())
    tracker = SloTracker(tcfg, history=history)
    schema, chunk = _small_chunk()
    _queries, records = _shape_records(schema)
    e = Evaluator()
    obs = get_compile_observatory()
    # One inline dispatch creates the sensor series pre-baseline.
    e.run_plan(build_query("k FROM [//t] WHERE v < 99",
                           {"//t": schema}), chunk)
    before = obs.totals()
    t0 = 1_000_000.0
    history.sample_once(t0)
    prewarm_from_capture(records, tables={"//t": chunk}, evaluator=e)
    history.sample_once(t0 + 400.0)
    snap = tracker.evaluate(now=t0 + 400.0)
    assert not snap["slos"]["compile_storm"]["firing"]
    assert not snap["active_alerts"]
    after = obs.totals()
    assert after["misses"] - before["misses"] == 0
    assert after["background_compiles"] - \
        before["background_compiles"] == len(records)


def test_prewarm_skips_what_it_cannot_warm():
    schema, chunk = _small_chunk()
    other = TableSchema.make([("jk", "int64"), ("w", "int64")])
    other_chunk = ColumnarChunk.from_rows(
        other, [{"jk": i, "w": i} for i in range(8)])
    records = [
        WorkloadRecord(kind="select",
                       query="k, v FROM [//t] WHERE v > 1 LIMIT 3",
                       literals=[]),
        WorkloadRecord(kind="select",
                       query="k, w FROM [//t] JOIN [//u] ON k = jk",
                       literals=[]),
        WorkloadRecord(kind="select", query="k FROM [//gone] LIMIT 1",
                       literals=[]),
        WorkloadRecord(kind="write", query="", literals=[],
                       table="//t"),
    ]
    report = prewarm_from_capture(
        records, tables={"//t": chunk, "//u": other_chunk})
    assert report["compiled"] == 1
    assert report["skipped"] == 3
    reasons = report["skip_reasons"]
    assert reasons.get("joins") == 1
    assert reasons.get("non_select") == 1


def test_prewarm_requires_a_chunk_source():
    _queries, records = _shape_records(None)
    with pytest.raises(Exception):
        prewarm_from_capture(records)


# -- observability surfaces ----------------------------------------------------

def test_execution_tier_in_statistics_and_explain_analyze():
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=50))
    schema, chunk = _small_chunk()
    plan = build_query("select k from t where v > 3 limit 4",
                       {"t": schema})
    e = Evaluator()
    stats = QueryStatistics()
    e.run_plan(plan, chunk, stats=stats)
    assert stats.execution_tier == "interpreted"
    assert stats.to_dict()["execution_tier"] == "interpreted"
    rendered = format_profile_dict(
        {"query": "q", "statistics": stats.to_dict()})
    assert "execution tier: interpreted" in rendered
    # Old profiles (no field) render as compiled.
    assert "execution tier: compiled" in \
        format_profile_dict({"query": "q", "statistics": {}})


def test_workload_record_carries_execution_tier():
    record = WorkloadRecord(kind="select", query="k FROM [//t]",
                            literals=[], execution_tier="interpreted")
    assert WorkloadRecord.from_dict(
        record.to_dict()).execution_tier == "interpreted"
    # Old captures (field absent) load as compiled.
    d = record.to_dict()
    d.pop("execution_tier")
    assert WorkloadRecord.from_dict(d).execution_tier == "compiled"


def test_monitoring_tiers_endpoint():
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    yt_config.set_tiering_config(
        yt_config.TieringConfig(enabled=True, hot_threshold=7))
    server = MonitoringServer(port=0)
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://{server.address}/tiers?top=5") as resp:
            tiers = json.loads(resp.read())
        assert tiers["enabled"] is True
        assert tiers["hot_threshold"] == 7
        assert "background" in tiers and "fingerprints" in tiers
    finally:
        server.stop()


def test_tiering_config_defaults_and_daemon_wiring():
    cfg = yt_config.TieringConfig()
    assert cfg.enabled is False            # kill switch: default OFF
    assert cfg.hot_threshold == 2
    assert cfg.queue_depth == 64
    assert cfg.prewarm_capture is None
    daemon = yt_config.DaemonConfig.from_dict(
        {"tiering": {"enabled": True, "hot_threshold": 5}})
    assert daemon.tiering.enabled is True
    assert daemon.tiering.hot_threshold == 5
    with pytest.raises(Exception):
        yt_config.TieringConfig.from_dict({"hot_threshold": 0})


def test_cli_prewarm(tmp_path, capsys):
    from ytsaurus_tpu import cli
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.query import workload as wl
    client = connect(str(tmp_path / "cluster"))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)
    client.create("table", "//pw/t",
                  attributes={"schema": schema, "dynamic": True},
                  recursive=True)
    client.mount_table("//pw/t")
    client.insert_rows("//pw/t",
                       [{"k": i, "v": i * 2} for i in range(64)])
    client.freeze_table("//pw/t")
    wl.configure(None)
    client.select_rows("k, v FROM [//pw/t] WHERE v < 10")
    client.select_rows("v, sum(k) AS s FROM [//pw/t] GROUP BY v")
    capture = str(tmp_path / "capture.json")
    assert wl.get_workload_log().export_capture(capture) == 2
    rc = cli.run(["prewarm", "--capture", capture, "--json"],
                 client=client)
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["records"] == 2
    assert report["compiled"] + report["aot_hits"] + \
        report["already_cached"] >= 1
    assert report["skipped"] == 0
