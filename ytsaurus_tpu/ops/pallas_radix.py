"""Pallas TPU kernel for the radix partition's counting phase.

The XLA radix engine (ops/radix.py) spends its per-pass budget on a
batched per-tile sort network plus a binary-search inversion.  This
module replaces the counting side with ONE VMEM pass: a Pallas kernel
computes, per tile, the 2^bits-bin digit histogram AND every element's
stable within-tile rank (count of equal digits earlier in the tile) —
the two quantities that determine each element's global destination

    dest[i] = bin_start[d_i] + earlier_tiles_count[t, d_i] + rank[i]

The movement itself is a permutation scatter (unique indices by
construction).  Engine name: "pallas" in stable_argsort_u32 /
radix_argsort_u32 dispatch.

Kernel shape notes:
- digits arrive as (tiles, SUBLANES, 128) so every block is a natively
  tiled (8k, 128) int32 tile;
- the per-bin loop is a fori_loop over 2^bits iterations of vectorized
  (SUBLANES, 128) work — row-major prefix counts via an axis-1 cumsum
  plus an exclusive row-total cumsum, no gathers, no scalar loops;
- runs in interpret mode off-TPU so the engine stays testable on the
  CPU mesh.

Reference analog: the partition phase of the Sort pipeline
(yt/yt/server/job_proxy/partition_job.cpp:40-120,
yt/yt/ytlib/table_client/partitioner.cpp:25,86) — the per-row
IPartitioner bucket loop becomes a vectorized counting kernel.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

PALLAS_TILE = int(os.environ.get("YT_TPU_PALLAS_TILE", 2048))
PALLAS_BITS = int(os.environ.get("YT_TPU_PALLAS_BITS", 6))
_LANES = 128


def _interpret() -> bool:
    backend = jax.default_backend()
    return backend != "tpu"


def _hist_rank_kernel(bits: int, d_ref, counts_ref, rank_ref):
    """One grid step = one tile of digits (1, SUBLANES, 128) int32.

    counts_ref: (1, 2^bits) int32 — histogram of this tile.
    rank_ref:   (1, SUBLANES, 128) int32 — stable row-major rank among
                equal digits within the tile.
    """
    d = d_ref[0]
    nbins = 1 << bits
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (1, nbins), 1)

    def per_bin(b, carry):
        rank, hist = carry
        mask = (d == b).astype(jnp.int32)
        within_row = (jnp.cumsum(mask, axis=1, dtype=jnp.int32)
                      - mask)                            # exclusive
        row_tot = jnp.sum(mask, axis=1, keepdims=True,
                          dtype=jnp.int32)               # (S, 1)
        rows_before = (jnp.cumsum(row_tot, axis=0, dtype=jnp.int32)
                       - row_tot)
        rank_b = rows_before + within_row
        # Histogram accumulates as a vector select — no dynamic-index
        # scalar stores in the kernel body.
        hist = hist + jnp.where(bin_iota == b,
                                jnp.sum(mask, dtype=jnp.int32),
                                jnp.zeros((), jnp.int32))
        return rank + mask * rank_b, hist

    rank, hist = jax.lax.fori_loop(
        0, nbins, per_bin,
        (jnp.zeros_like(d), jnp.zeros((1, nbins), jnp.int32)))
    counts_ref[...] = hist
    rank_ref[0] = rank


@functools.partial(jax.jit, static_argnames=("bits", "tile"))
def hist_rank(digits: jax.Array, bits: int = PALLAS_BITS,
              tile: int = PALLAS_TILE):
    """digits: (N,) int32 with N % tile == 0, values < 2^bits.
    Returns (counts (tiles, 2^bits) int32, rank (N,) int32)."""
    from jax.experimental import pallas as pl

    n = digits.shape[0]
    nt = n // tile
    sub = tile // _LANES
    assert sub * _LANES == tile and nt * tile == n
    d3 = digits.reshape(nt, sub, _LANES).astype(jnp.int32)
    nbins = 1 << bits
    counts, rank = pl.pallas_call(
        functools.partial(_hist_rank_kernel, bits),
        grid=(nt,),
        in_specs=[pl.BlockSpec((1, sub, _LANES),
                               lambda t: (t, 0, 0))],
        out_specs=[pl.BlockSpec((1, nbins), lambda t: (t, 0)),
                   pl.BlockSpec((1, sub, _LANES), lambda t: (t, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((nt, nbins), jnp.int32),
                   jax.ShapeDtypeStruct((nt, sub, _LANES), jnp.int32)],
        interpret=_interpret(),
    )(d3)
    return counts, rank.reshape(n)


def radix_pass_pallas(digit: jax.Array, payloads: list[jax.Array],
                      bits: int) -> list[jax.Array]:
    """One stable partition by `digit` (< 2^bits): Pallas counting pass +
    destination arithmetic + a unique-index permutation scatter."""
    n = digit.shape[0]
    tile = min(PALLAS_TILE, n)
    counts, rank = hist_rank(digit.astype(jnp.int32), bits=bits, tile=tile)
    nt = counts.shape[0]
    per_bin = counts.sum(axis=0)                         # (B,)
    bin_start = jnp.cumsum(per_bin) - per_bin            # (B,)
    tile_excl = jnp.cumsum(counts, axis=0) - counts      # (nt, B)
    run_start = (bin_start[None, :] + tile_excl).reshape(-1)   # (nt*B,)
    t_idx = jnp.arange(n, dtype=jnp.int32) // tile
    d32 = digit.astype(jnp.int32)
    dest = run_start[t_idx * (1 << bits) + d32] + rank
    return [jnp.zeros(n, p.dtype).at[dest].set(p, unique_indices=True,
                                               mode="drop")
            for p in payloads]
