"""SPMD distributed query execution over a device mesh.

The host-coordinated path (query/coordinator.py) loops over shards; this
module is the TPU-native fast path: every shard (tablet analog) lives on its
own device, the bottom query runs as ONE shard_map program, and the front
merge happens on-device via all_gather over ICI — no host round-trip, no bus.

Ref mapping (SURVEY.md §2.8 parallelism table):
  partition-parallel scan  → shard_map over the 'shard' mesh axis
  two-phase aggregation    → per-shard partial states + all_gather + re-group
  (psum applies when group keys are static; the general re-group handles
  arbitrary key sets)
"""

from __future__ import annotations

import warnings as _warnings
from dataclasses import dataclass
from typing import Optional, Sequence

import jax

# Buffer donation (ISSUE 19) is inert on CPU backends but warns per
# call; keep the armed SPMD path quiet on the CPU test floor.
_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ytsaurus_tpu.parallel.compat import shard_map

from ytsaurus_tpu.chunks.columnar import (
    Column,
    ColumnarChunk,
    unify_dictionaries,
)
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import split_plan
from ytsaurus_tpu.query.parameterize import plan_fingerprint
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.schema import EValueType, TableSchema
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger

_ladder_log = get_logger("Distributed")


def _exchange_error(site: str) -> YtError:
    return YtError(f"injected collective failure at {site}",
                   code=EErrorCode.QueryExecutionError,
                   attributes={"failpoint": site})


# Shuffle-boundary fault sites: all_to_all guards the co-partition
# exchange, gather the all_gather merge.  coordinate_distributed's
# degradation ladder steps down a rung when one of them fails.
_FP_ALL_TO_ALL = failpoints.register_site("parallel.all_to_all",
                                          error=_exchange_error)
_FP_GATHER = failpoints.register_site("parallel.gather",
                                      error=_exchange_error)

# Mid-plan host-sync accounting (ISSUE 12): every blocking device→host
# read a distributed query performs notes here — the stitched rungs pay
# one per exchange-quota decision plus the final count; the whole-plan
# path pays exactly one (the final stacked transfer).  A plain counter
# (not a sensor): `bench.py --config whole_plan` reads deltas.
_host_syncs_n = 0


def _note_host_sync() -> None:
    global _host_syncs_n
    _host_syncs_n += 1


def host_sync_count() -> int:
    return _host_syncs_n


@dataclass
class _RepColumn:
    """Vocabulary/type carrier used to bind plans without device planes."""
    type: EValueType
    dictionary: Optional[np.ndarray]


@dataclass
class _RepChunk:
    capacity: int
    columns: dict


class ShardedTable:
    """A table partitioned across a device mesh.

    All shards share one schema, one per-shard capacity and ONE unified
    string vocabulary per column (so dictionary codes agree across devices —
    the HBM-staging analog of the reference's in_memory_manager keeping
    chunks resident in a common format, tablet_node/in_memory_manager.h).

    Planes are global arrays of shape (n_shards * capacity,) sharded along
    the mesh axis; each device holds its (capacity,) slice.
    """

    def __init__(self, schema: TableSchema, mesh: Mesh, capacity: int,
                 columns: dict[str, Column], row_counts: list[int],
                 row_valid: jax.Array):
        self.schema = schema
        self.mesh = mesh
        self.capacity = capacity            # per shard
        self.columns = columns              # global sharded planes
        self.row_counts = row_counts
        self.row_valid = row_valid

    @property
    def n_shards(self) -> int:
        return len(self.row_counts)

    @property
    def total_rows(self) -> int:
        return sum(self.row_counts)

    @staticmethod
    def from_chunks(mesh: Mesh, chunks: Sequence[ColumnarChunk]
                    ) -> "ShardedTable":
        n = mesh.devices.size
        if len(chunks) != n:
            raise YtError(f"Need exactly {n} shards for this mesh, "
                          f"got {len(chunks)}",
                          code=EErrorCode.QueryExecutionError)
        schema = chunks[0].schema
        for c in chunks[1:]:
            if c.schema != schema:
                raise YtError("Shard schema mismatch",
                              code=EErrorCode.QueryExecutionError)
        cap = max(c.capacity for c in chunks)
        chunks = [c.with_capacity(cap) for c in chunks]
        shard_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        columns: dict[str, Column] = {}
        for col_schema in schema:
            cols = [c.column(col_schema.name) for c in chunks]
            vocab = None
            if col_schema.type is EValueType.string:
                cols, vocab = unify_dictionaries(cols)
            data = jnp.concatenate([col.data for col in cols])
            valid = jnp.concatenate([col.valid for col in cols])
            data = jax.device_put(data, shard_sharding)
            valid = jax.device_put(valid, shard_sharding)
            columns[col_schema.name] = Column(
                type=col_schema.type, data=data, valid=valid, dictionary=vocab)
        row_valid = jnp.concatenate(
            [jnp.arange(cap) < c.row_count for c in chunks])
        row_valid = jax.device_put(row_valid, shard_sharding)
        return ShardedTable(schema=schema, mesh=mesh, capacity=cap,
                            columns=columns,
                            row_counts=[c.row_count for c in chunks],
                            row_valid=row_valid)

    def rep_chunk(self) -> _RepChunk:
        return _RepChunk(
            capacity=self.capacity,
            columns={name: _RepColumn(type=col.type, dictionary=col.dictionary)
                     for name, col in self.columns.items()})


def _assemble_chunk(prepared_output, out_planes, out_count) -> ColumnarChunk:
    """Materialize prepared-query output planes into a ColumnarChunk."""
    out_columns: dict[str, Column] = {}
    out_schema_cols = []
    for out_col, (data, valid) in zip(prepared_output, out_planes):
        out_schema_cols.append((out_col.name, out_col.type.value))
        out_columns[out_col.name] = Column(
            type=out_col.type, data=data, valid=valid,
            dictionary=out_col.vocab)
    return ColumnarChunk(schema=TableSchema.make(out_schema_cols),
                         row_count=int(out_count), columns=out_columns)


def _canonical_hash_plane(data: jax.Array) -> jax.Array:
    """Canonicalize values before hashing for routing: -0.0 and +0.0
    compare equal but differ by bit pattern, so without this two rows
    that MATCH under the join/group comparison could land on different
    devices and never meet."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        return jnp.where(data == 0, jnp.zeros_like(data), data)
    return data


def _vocab_remap_slots(self_bound, f_bound, bindings: list):
    """String join keys: both sides' dictionary codes are remapped onto a
    MERGED vocabulary so equality compares one code space (the SPMD
    analog of execute_join's host remap).  Returns per-key binding slots
    (None for non-string keys); tables are appended to `bindings`."""
    import numpy as np

    from ytsaurus_tpu.query.engine.expr import (
        _merge_vocabs, _pad_np, _remap_table, _vocab_bucket,
    )

    self_slots: list = []
    foreign_slots: list = []
    for sb, fb in zip(self_bound, f_bound):
        if sb.vocab is None and fb.vocab is None:
            self_slots.append(None)
            foreign_slots.append(None)
            continue
        s_vocab = sb.vocab if sb.vocab is not None \
            else np.array([], dtype=object)
        f_vocab = fb.vocab if fb.vocab is not None \
            else np.array([], dtype=object)
        merged = _merge_vocabs(s_vocab, f_vocab)
        for vocab in (s_vocab, f_vocab):
            table = _remap_table(vocab, merged)
            bindings.append(jnp.asarray(
                _pad_np(table, _vocab_bucket(len(table)), 0)))
        self_slots.append(len(bindings) - 2)
        foreign_slots.append(len(bindings) - 1)
    return self_slots, foreign_slots


@dataclass
class _JoinSetup:
    """Device-resident broadcast-join plan: replicated sorted foreign
    planes + a traceable per-shard augment step."""
    apply: callable          # (columns, mask, bindings, args) -> (cols, mask)
    bindings: tuple          # host-bound remap/constant slots
    args: tuple              # replicated device planes (P() specs)
    rep_columns: dict        # joined-namespace _RepColumns for prepare()
    fingerprint: tuple


def _chunk_memo(cache: dict, key: tuple, chunk, build):
    """id()-keyed per-chunk memo with a weakref liveness guard and
    finalizer eviction (the stats_for_chunk discipline): a recycled
    object id can never serve a DEAD chunk's staged planes, and a dead
    chunk's device buffers do not outlive it in the cache."""
    import weakref
    entry = cache.get(key)
    if entry is not None and entry[0]() is chunk:
        return entry[1]
    value = build()
    cache[key] = (weakref.ref(chunk), value)
    weakref.finalize(chunk, cache.pop, key, None)
    return value


def _foreign_host_order(cache: dict, join: ir.JoinClause, foreign,
                        self_bound, f_bound, foreign_slots, bindings):
    """Host phase shared by the stitched broadcast join and the fused
    whole-plan join: encode + sort the foreign keys once, verify
    uniqueness, memoize per (join shape, foreign chunk identity, vocab
    identities).  Returns (f_order, f_sorted, unique)."""
    from ytsaurus_tpu.query.engine.expr import EmitContext
    from ytsaurus_tpu.query.engine.joins import (
        _emit_encoded_keys, sort_foreign_keys,
    )

    f_ctx = EmitContext(columns={
        name: (foreign.columns[name].data, foreign.columns[name].valid)
        for name in foreign.schema.column_names},
        bindings=tuple(bindings), capacity=foreign.capacity)
    f_keys = _emit_encoded_keys(f_bound, foreign_slots, f_ctx)
    n_foreign = foreign.row_count
    # Deliberately the VALUE-CARRYING fingerprint (not the parameterized
    # one): this cache holds computed key planes, not a program, so
    # equation literals must distinguish.  Remapped codes depend on BOTH
    # sides' vocabularies (the merged space): key on their identities.
    host_key = ("join-host", ir.fingerprint(ir.Query(
        schema=join.foreign_schema, source=join.foreign_table,
        joins=(join,))), id(foreign), foreign.capacity, n_foreign,
        tuple(id(b.vocab) if b.vocab is not None else None
              for b in list(self_bound) + list(f_bound)))

    def build():
        f_order, f_sorted = sort_foreign_keys(f_keys, foreign.row_valid)
        # Unique-key check over adjacent sorted pairs.  Null-keyed rows
        # match nothing, so duplicates among them are fine.
        live = jnp.arange(foreign.capacity) < (n_foreign - 1)
        same = jnp.ones(foreign.capacity, dtype=bool)
        non_null = jnp.ones(foreign.capacity, dtype=bool)
        for v, d in f_sorted:
            same = same & (v == jnp.roll(v, -1)) & \
                (d == jnp.roll(d, -1))
            non_null = non_null & (v > 0)
        unique = not bool(jnp.any(same & live & non_null))
        return f_order, f_sorted, unique

    return _chunk_memo(cache, host_key, foreign, build)


def _stitched_mesh_block(stats, plan: ir.Query, key, n: int, in_rows,
                         out_rows, exchanges, stages=None) -> None:
    """Stitched-rung mesh telemetry (ISSUE 20 parity): assemble the SAME
    block shape the fused program returns — from host values the
    stitched rungs ALREADY read for their quota/capacity decisions, so
    this costs zero additional device→host transfers — and fan it out
    to the same surfaces (whole_plan._publish_mesh).  Blocks carry
    path="stitched", so /mesh and `yt mesh top` show which lowering
    measured what."""
    from ytsaurus_tpu.parallel.whole_plan import (
        _mesh_armed, _mesh_block, _publish_mesh)
    if not _mesh_armed():
        return
    block = _mesh_block(n, in_rows, out_rows, exchanges, stages=stages,
                        path="stitched")
    _publish_mesh(stats, plan_fingerprint(plan), key, block)


class DistributedEvaluator:
    """Compiles and caches SPMD (join ∘ bottom ∘ all_gather ∘ front)
    programs."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._cache: dict = {}
        # Settled exchange quotas per whole-plan shape (parallel/
        # whole_plan.py): the data-dependent decision the stitched path
        # host-syncs for, memoized instead of measured per query.
        self._quota_memo: dict = {}
        # Per-process compile split for the restart acceptance leg: a
        # warm-started daemon serves SPMD plans with fresh_compiles == 0.
        self.fresh_compiles = 0
        self.disk_hits = 0

    def _dispatch_spmd(self, key: tuple, build, args, donate: tuple = ()):
        """Run one SPMD program through the compile-once ladder (ISSUE
        10, extended to the distributed plane): memory cache → AOT disk
        tier (`aot_cache.py` — serialize_executable products of
        `lower().compile()`, so a rolling restart or a mesh resize is a
        cache fill) → fresh compile.  `build()` returns the un-jitted
        program; `args` are the concrete call arguments AOT lowering
        pins shapes from."""
        from ytsaurus_tpu.config import compile_config
        if not compile_config().donate_buffers:
            donate = ()
        fn = self._cache.get(key)
        if fn is None:
            fn = self._compile_spmd(key, build, args, donate)
        try:
            return fn(*args)
        except Exception:
            if hasattr(fn, "lower"):
                raise             # plain jitted fn: a genuine error
            # AOT-compiled executable rejects an aval drift the cache
            # key did not capture: rebuild through the tolerant jit
            # wrapper (a genuine execution error re-raises identically).
            # This IS a fresh compile — count it, or a rotten disk tier
            # could report a perfect warm start while recompiling
            # everything.  (Aval rejection happens before execution, so
            # donated inputs are still alive for the retry.)
            fn = jax.jit(build(), donate_argnums=donate)
            self.fresh_compiles += 1
            self._cache[key] = fn
            return fn(*args)

    def _compile_spmd(self, key: tuple, build, args, donate: tuple = ()):
        import time as _time

        from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
        disk = get_disk_cache()
        fn = disk.load(key) if disk is not None else None
        if fn is not None:
            self.disk_hits += 1
            self._observe_compiled(key, fn)
        else:
            jitted = jax.jit(build(), donate_argnums=donate)
            t0 = _time.perf_counter()
            lowered = None
            try:
                lowered = jitted.lower(*args)
                fn = lowered.compile()
            except Exception:   # noqa: BLE001 — AOT is an optimization;
                # anything it cannot lower OR compile falls back to the
                # jit wrapper (first call compiles fused); lowered must
                # reset or the store below would serialize the wrapper.
                fn = jitted
                lowered = None
            self.fresh_compiles += 1
            seconds = _time.perf_counter() - t0
            if disk is not None and lowered is not None:
                disk.store(key, fn, str(key[0]), seconds)
            if lowered is not None:
                self._observe_compiled(key, fn, lowered, seconds)
        self._cache[key] = fn
        return fn

    @staticmethod
    def _observe_compiled(key: tuple, fn, lowered=None,
                          seconds: float = 0.0) -> None:
        """Compile-time capture for one SPMD executable (ISSUE 20):
        memory_analysis()/cost_analysis() land in the mesh observatory
        (keyed by the program cache key the dispatch site holds — the
        runtime telemetry block joins them at decode time), and — behind
        `WorkloadConfig.capture_artifacts` — the HLO + FLOPs/bytes land
        in the compile observatory's artifact ring, so fused/stitched
        SPMD programs show up in `yt compile-cache top` instead of
        blanks.  Never observe_hit/observe_miss here: those counters
        must reconcile with the /query/compile_cache pool sensors,
        which only count the local evaluator's dispatches."""
        from ytsaurus_tpu.parallel.mesh_observatory import (
            get_mesh_observatory, memory_analysis_dict)
        from ytsaurus_tpu.query.engine.evaluator import (
            _cost_analysis, get_compile_observatory)
        try:
            cost = _cost_analysis(fn)
            get_mesh_observatory().record_compile(
                key, memory_analysis_dict(fn), cost)
            from ytsaurus_tpu.config import workload_config
            if workload_config().capture_artifacts and lowered is not None:
                get_compile_observatory().capture_artifact(
                    f"spmd/{key[0]}", key, lowered.as_text(), cost,
                    seconds)
        except Exception:   # noqa: BLE001 — observability capture is a
            # debugging aid, never an execution hazard.
            pass

    def run(self, plan: ir.Query, table: ShardedTable,
            foreign_chunks: Optional[dict] = None,
            shuffle: Optional[bool] = None, stats=None) -> ColumnarChunk:
        """Execute a plan SPMD.  `shuffle=True` uses the all_to_all
        repartition path for GROUP BY (ref CoordinateAndExecuteWithShuffle,
        engine_api/coordinator.h:92): rows move to hash(key)-owned devices
        and each device computes its COMPLETE groups — right when group
        cardinality is high (the all_gather merge would replicate heavy
        front work).  Default: gather-merge.

        Joined plans run one of two ways:
        - broadcast join (unique foreign keys, the lookup shape, e.g.
          TPC-H Q3): each foreign table is key-sorted once, replicated to
          every device, and probed per shard with a vectorized
          lexicographic binary search (the batch reshaping of
          MultiJoinOpHelper's foreign lookups, cg_routines/
          registry.cpp:599);
        - partitioned hash join (non-unique keys / fact-to-fact, or
          under shuffle=True): BOTH sides are routed by join-key hash
          over one all_to_all so equal keys co-locate, then each device
          joins locally with match expansion — the shuffle-aware join of
          engine_api/coordinator.h:92-97.
        String keys work on both paths via merged vocabularies."""
        join_setup = None
        if plan.joins:
            # Cost-based execution order (query/planner.py): the same
            # decisions the fused rung makes, so a query degrading off
            # the whole-plan rung runs the SAME join order — and the
            # reordered plan's fingerprint keys every stitched program
            # cache (a stats-driven order flip never reuses stale).
            from ytsaurus_tpu.query import planner
            plan, _jplan = planner.reorder_for_chunks(
                plan, table.total_rows, foreign_chunks or {})
            join_setup = None if shuffle else self._prepare_joins(
                plan, table, foreign_chunks or {})
            if join_setup is None:
                return self._run_partitioned(plan, table,
                                             foreign_chunks or {},
                                             bool(shuffle), stats=stats)
        if plan.window is not None and plan.window.partition_items and \
                shuffle is not False and join_setup is None:
            # Window functions co-partition by the PARTITION BY key over
            # one all_to_all (default path): each device then owns
            # COMPLETE partitions and computes exact windows locally;
            # only order/project/offset/limit merge at the front.
            # shuffle=False forces the gather-merge fallback (the front
            # recomputes the window over the full gathered rowset).
            return self._finish_shuffled(
                plan, {name: (col.data, col.valid)
                       for name, col in table.columns.items()},
                table.row_valid,
                {name: _RepColumn(type=col.type, dictionary=col.dictionary)
                 for name, col in table.columns.items()},
                table.capacity, stats=stats,
                in_rows=list(table.row_counts))
        if shuffle and plan.group is not None and not plan.group.totals:
            return self._run_shuffled(plan, table, stats=stats)
        columns_global = {name: (col.data, col.valid)
                          for name, col in table.columns.items()}
        if join_setup is None:
            rep_columns = {
                name: _RepColumn(type=col.type, dictionary=col.dictionary)
                for name, col in table.columns.items()}
        else:
            rep_columns = join_setup.rep_columns
        return self._finish_gather(plan, columns_global, table.row_valid,
                                   rep_columns, table.capacity,
                                   join_setup=join_setup, stats=stats,
                                   in_rows=list(table.row_counts))

    def _finish_gather(self, plan: ir.Query, columns_global: dict,
                       row_valid, rep_columns: dict, cap: int,
                       join_setup: "Optional[_JoinSetup]" = None,
                       stats=None, in_rows=None) -> ColumnarChunk:
        """Bottom-per-shard + all_gather front merge over bare sharded
        planes — run()'s tail for both the no-join and broadcast-join
        shapes, reusable after a partitioned join has replaced the table
        planes.  With join_setup, the broadcast probe runs as a traced
        step ahead of the bottom query inside the same program."""
        _FP_GATHER.hit()
        n = self.mesh.devices.size
        bottom, front = split_plan(plan)
        rep = _RepChunk(capacity=cap, columns=dict(rep_columns))
        prepared_b = prepare(bottom, rep)
        inter_rep = _RepChunk(
            capacity=n * prepared_b.out_capacity,
            columns={c.name: _RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_b.output})
        prepared_f = prepare(front, inter_rep)
        # Compiled-program caches key on the PARAMETERIZED shape
        # fingerprint (ISSUE 10): the emit paths are literal-value-
        # independent (values ride the bindings tuple, passed as args
        # per dispatch), so one SPMD program serves every constant.
        key = ("finish", plan_fingerprint(bottom), plan_fingerprint(front), n,
               cap, prepared_b.binding_shapes(),
               prepared_f.binding_shapes(),
               join_setup.fingerprint if join_setup else None)
        columns = {c.name: columns_global[c.name]
                   for c in bottom.schema if c.name in columns_global}
        extra = (join_setup.args, tuple(join_setup.bindings)) \
            if join_setup else ()
        out_planes, out_count = self._dispatch_spmd(
            key, lambda: self._build(prepared_b, prepared_f, cap,
                                     join_setup),
            (columns, row_valid, tuple(prepared_b.bindings),
             tuple(prepared_f.bindings), *extra))
        _note_host_sync()
        if in_rows is not None:
            # The gather rung's only host-known per-shard cardinality is
            # the input spread (the front count is a merged global): its
            # skew IS the per-shard work on this rung, so it doubles as
            # the output spread in the parity block.
            _stitched_mesh_block(stats, plan, key, n, in_rows, in_rows,
                                 [])
        return _assemble_chunk(prepared_f.output, out_planes, out_count)

    def _run_partitioned(self, plan: ir.Query, table: ShardedTable,
                         foreign_chunks: dict, shuffle: bool, stats=None
                         ) -> ColumnarChunk:
        """Partitioned hash join: route BOTH sides of each join by
        join-key hash over one all_to_all so equal keys co-locate, then
        join locally per device with match expansion — the general
        fact-to-fact shape (non-unique foreign keys), composing with the
        shuffled GROUP BY.  Ref: shuffle-aware join coordination,
        engine_api/coordinator.h:92-97 + executor.cpp join routing.

        Static-shape discipline (per join): a count pass sizes the
        exchange quotas; a route+probe program moves rows and computes
        per-self-row match ranges (outputs stay device-resident); the
        host reads only the per-device totals to pick the expansion
        capacity; an expand program materializes the joined planes."""
        from dataclasses import replace as dc_replace

        from ytsaurus_tpu.chunks.columnar import pad_capacity
        from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder,
            _combine_u64, _mix_u64,
        )
        from ytsaurus_tpu.query.engine.joins import (
            _bind_keys, _emit_encoded_keys, _lex_searchsorted,
            null_key_mask, sort_foreign_keys,
        )

        mesh = self.mesh
        n = table.n_shards
        shard_sharding = NamedSharding(mesh, P(SHARD_AXIS))

        cur_cap = table.capacity
        columns_global = {name: (col.data, col.valid)
                          for name, col in table.columns.items()}
        # Only planes the plan actually reads ride the exchange — a wide
        # table joined on one key must not pay all_to_all bandwidth for
        # dead columns.
        needed = ir.referenced_columns(plan)
        if needed is not None:
            columns_global = {name: planes
                              for name, planes in columns_global.items()
                              if name in needed}
        row_valid = table.row_valid
        namespace = {name: ColumnBinding(type=col.type, vocab=col.dictionary)
                     for name, col in table.columns.items()}
        rep_columns = {
            name: _RepColumn(type=col.type, dictionary=col.dictionary)
            for name, col in table.columns.items()}
        # Mesh parity telemetry (ISSUE 20): the quota/capacity host
        # reads this path already pays carry enough to assemble the
        # fused block's shape — exchange demand vs granted per side,
        # per-shard joined-output totals.  Transfer MATRICES stay on
        # device here (only their maxes cross), so entries carry
        # matrix=None.
        mesh_exchanges: list = []
        mesh_stages: list = []
        mesh_out_rows = list(table.row_counts)

        for join_index, join in enumerate(plan.joins):
            foreign = foreign_chunks.get(join.foreign_table)
            if foreign is None:
                raise YtError(
                    f"No data provided for join table "
                    f"{join.foreign_table!r}",
                    code=EErrorCode.QueryExecutionError)
            bindings: list = []
            bind_structure: list = []
            bind_ctx = BindContext(columns=dict(namespace),
                                   bindings=bindings,
                                   structure=bind_structure)
            binder = ExprBinder(bind_ctx)
            self_bound = [binder.bind(e) for e in join.self_equations]
            f_bound = _bind_keys(foreign, join.foreign_schema,
                                 join.foreign_equations, bindings,
                                 structure=bind_structure)
            self_slots, foreign_slots = _vocab_remap_slots(
                self_bound, f_bound, bindings)
            bnd = tuple(bindings)
            is_left = join.is_left
            s_cap = cur_cap

            flat_names = [
                (f"{join.alias}.{f}" if join.alias else f, f)
                for f in join.foreign_columns]
            if needed is not None:
                flat_names = [(flat, f) for flat, f in flat_names
                              if flat in needed]
            # Shard the foreign table across the mesh (1/n per device);
            # route only the planes the join reads (key-expression
            # sources + pulled columns that survive pruning).
            f_count = foreign.row_count
            f_slice = pad_capacity(max((f_count + n - 1) // n, 1))
            f_total = n * f_slice
            f_key_refs: set = set()
            for eq in join.foreign_equations:
                f_key_refs.update(ir.expr_references(eq))
            f_names = sorted(f_key_refs | {f for _, f in flat_names})
            f_global = {}
            for fname in f_names:
                fcol = foreign.columns[fname]
                pad = f_total - f_count
                data = jnp.concatenate(
                    [fcol.data[:f_count],
                     jnp.zeros(pad, dtype=fcol.data.dtype)])
                valid = jnp.concatenate(
                    [fcol.valid[:f_count], jnp.zeros(pad, dtype=bool)])
                f_global[fname] = (jax.device_put(data, shard_sharding),
                                   jax.device_put(valid, shard_sharding))
            f_row_valid = jax.device_put(
                jnp.arange(f_total) < f_count, shard_sharding)

            def make_pid(keys, mask, keep_null_local: bool):
                """Destination device by key hash; null-keyed live rows
                stay local for LEFT joins (they must still emit an
                unmatched output row) and are discarded otherwise."""
                acc = jnp.full(mask.shape, np.uint64(0x9E3779B97F4A7C15),
                               dtype=jnp.uint64)
                for v, d in keys:
                    h = _mix_u64(_canonical_hash_plane(d))
                    h = jnp.where(v > 0, h, jnp.zeros_like(h))
                    acc = _combine_u64(acc, h)
                pid = (acc % np.uint64(n)).astype(jnp.int32)
                null = null_key_mask(keys)
                if keep_null_local:
                    me = jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
                    pid = jnp.where(null, me, pid)
                else:
                    pid = jnp.where(null, n, pid)
                return jnp.where(mask, pid, n)

            def emit_self(cols, capacity, bnd_t):
                ctx = EmitContext(columns=cols, bindings=bnd_t,
                                  capacity=capacity)
                return _emit_encoded_keys(self_bound, self_slots, ctx)

            def emit_foreign(cols, capacity, bnd_t):
                ctx = EmitContext(columns=cols, bindings=bnd_t,
                                  capacity=capacity)
                return _emit_encoded_keys(f_bound, foreign_slots, ctx)

            def count_pass(cols, mask, fcols, fmask, bnd_t):
                pid_s = make_pid(emit_self(cols, s_cap, bnd_t), mask,
                                 is_left)
                pid_f = make_pid(emit_foreign(fcols, f_slice, bnd_t),
                                 fmask, False)
                return (transfer_counts(pid_s, pid_s < n, n),
                        transfer_counts(pid_f, pid_f < n, n))

            key_base = ("pjoin", plan_fingerprint(plan), join_index, n,
                        s_cap, f_slice, f_count > 0,
                        # Bind-phase structure notebook: baked host
                        # constants (concat widths) binding shapes
                        # alone cannot distinguish (ISSUE 10).
                        tuple(bind_structure),
                        tuple((tuple(b.shape), str(b.dtype))
                              for b in bindings))
            counts_s, counts_f = self._dispatch_spmd(
                key_base + ("count",),
                lambda: shard_map(
                    count_pass, mesh=mesh,
                    in_specs=(P(SHARD_AXIS),) * 4 + (P(),),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    check_vma=False),
                (columns_global, row_valid, f_global, f_row_valid, bnd))
            _note_host_sync()
            # One stacked device→host transfer for both quotas (the
            # `yt analyze` jax pass flagged the original pair of
            # np.asarray reads — the self and foreign counts each
            # blocked the dispatch queue separately).
            # analyze: allow(host-sync): routing quotas are a host decision; one stacked transfer
            quotas = np.asarray(jnp.stack([counts_s.max(),
                                           counts_f.max()]))
            # analyze: allow(host-sync): quotas is host numpy (the one stacked transfer above)
            demand_s, demand_f = (int(q) for q in quotas)
            quota_s = pad_capacity(max(demand_s, 1))
            quota_f = pad_capacity(max(demand_f, 1))
            S, F = n * quota_s, n * quota_f
            from ytsaurus_tpu.parallel.whole_plan import (
                _mesh_exchange_entry, _row_bytes)
            mesh_exchanges.append(_mesh_exchange_entry(
                f"join[{join_index}]/self", None, demand_s,
                quota_s, _row_bytes({name: rep_columns[name]
                                     for name in columns_global
                                     if name in rep_columns})))
            mesh_exchanges.append(_mesh_exchange_entry(
                f"join[{join_index}]/foreign", None, demand_f,
                quota_f, _row_bytes({
                    f: _RepColumn(type=foreign.columns[f].type,
                                  dictionary=foreign.columns[f].dictionary)
                    for f in f_names})))

            def route_probe(cols, mask, fcols, fmask, bnd_t):
                pid_s = make_pid(emit_self(cols, s_cap, bnd_t), mask,
                                 is_left)
                recv_s, mask_s = route_rows(cols, pid_s, n, quota_s, s_cap)
                pid_f = make_pid(emit_foreign(fcols, f_slice, bnd_t),
                                 fmask, False)
                recv_f, mask_f = route_rows(fcols, pid_f, n, quota_f,
                                            f_slice)
                s_keys = emit_self(recv_s, S, bnd_t)
                f_keys = emit_foreign(recv_f, F, bnd_t)
                f_order, f_sorted = sort_foreign_keys(f_keys, mask_f)
                n_f = mask_f.sum()
                lo = _lex_searchsorted(f_sorted, n_f, F, s_keys, "left")
                hi = _lex_searchsorted(f_sorted, n_f, F, s_keys, "right")
                s_null = null_key_mask(s_keys)
                counts = jnp.where(mask_s & ~s_null, hi - lo, 0)
                per_row = jnp.where(mask_s, jnp.maximum(counts, 1), 0) \
                    if is_left else counts
                return (recv_s, mask_s, recv_f, f_order, lo, counts,
                        per_row.sum()[None])

            (recv_s, mask_s, recv_f, f_order, lo, counts,
             totals) = self._dispatch_spmd(
                key_base + ("probe", quota_s, quota_f),
                lambda: shard_map(
                    route_probe, mesh=mesh,
                    in_specs=(P(SHARD_AXIS),) * 4 + (P(),),
                    out_specs=(P(SHARD_AXIS),) * 7, check_vma=False),
                (columns_global, row_valid, f_global, f_row_valid, bnd))
            _note_host_sync()
            # analyze: allow(host-sync): join output capacity is a host decision — one totals transfer
            totals_np = np.asarray(totals)
            out_cap = pad_capacity(max(int(totals_np.max()), 1))
            mesh_out_rows = [int(t) for t in totals_np.reshape(-1)]
            mesh_stages.append({
                "stage": join_index, "table": join.foreign_table,
                "strategy": "partition", "est_rows": 0,
                "actual_rows": int(totals_np.sum()), "drift": 0.0})
            self_names = sorted(columns_global)

            def expand(recv_s, mask_s, recv_f, f_order, lo, counts):
                per_row = jnp.where(mask_s, jnp.maximum(counts, 1), 0) \
                    if is_left else counts
                offsets = jnp.cumsum(per_row)
                total = offsets[-1]
                starts = jnp.concatenate(
                    [jnp.zeros(1, dtype=offsets.dtype), offsets[:-1]])
                out_idx = jnp.arange(out_cap)
                self_row = jnp.clip(
                    jnp.searchsorted(offsets, out_idx, side="right"),
                    0, S - 1)
                within = out_idx - starts[self_row]
                matched = counts[self_row] > 0
                f_pos = jnp.clip(lo[self_row] + within, 0, F - 1)
                f_row = f_order[f_pos]
                live = out_idx < total
                out = {}
                for name in self_names:
                    d, v = recv_s[name]
                    out[name] = (d[self_row], v[self_row] & live)
                for flat, fname in flat_names:
                    d, v = recv_f[fname]
                    out[flat] = (d[f_row], v[f_row] & live & matched)
                return out, live

            # Every input of `expand` is a route_probe output this
            # loop iteration owns, consumed exactly once here — donate
            # all six so the routed planes' buffers are reused for the
            # expanded output (ISSUE 19; inert on CPU).
            columns_global, row_valid = self._dispatch_spmd(
                key_base + ("expand", quota_s, quota_f, out_cap),
                lambda: shard_map(
                    expand, mesh=mesh,
                    in_specs=(P(SHARD_AXIS),) * 6,
                    out_specs=P(SHARD_AXIS), check_vma=False),
                (recv_s, mask_s, recv_f, f_order, lo, counts),
                donate=(0, 1, 2, 3, 4, 5))
            cur_cap = out_cap
            for flat, fname in flat_names:
                fcol = foreign.columns[fname]
                namespace[flat] = ColumnBinding(type=fcol.type,
                                                vocab=fcol.dictionary)
                rep_columns[flat] = _RepColumn(type=fcol.type,
                                               dictionary=fcol.dictionary)

        _stitched_mesh_block(stats, plan, None, n,
                             list(table.row_counts), mesh_out_rows,
                             mesh_exchanges, stages=mesh_stages)

        plan_nojoin = dc_replace(plan, joins=())
        if needed is not None:
            # The finish stages bind every schema column; drop the ones
            # pruned out of the exchange so the namespaces agree.
            plan_nojoin = dc_replace(plan_nojoin, schema=TableSchema(
                columns=tuple(c for c in plan.schema
                              if c.name in needed)))
        if plan_nojoin.window is not None and \
                plan_nojoin.window.partition_items and shuffle:
            return self._finish_shuffled(
                plan_nojoin, columns_global, row_valid, rep_columns,
                cur_cap, stats=stats, in_rows=mesh_out_rows)
        if shuffle and plan.group is not None and not plan.group.totals:
            return self._finish_shuffled(plan_nojoin, columns_global,
                                         row_valid, rep_columns, cur_cap,
                                         stats=stats,
                                         in_rows=mesh_out_rows)
        return self._finish_gather(plan_nojoin, columns_global, row_valid,
                                   rep_columns, cur_cap, stats=stats,
                                   in_rows=mesh_out_rows)

    def _run_shuffled(self, plan: ir.Query, table: ShardedTable,
                      stats=None) -> ColumnarChunk:
        columns_global = {name: (col.data, col.valid)
                          for name, col in table.columns.items()}
        rep_columns = {
            name: _RepColumn(type=col.type, dictionary=col.dictionary)
            for name, col in table.columns.items()}
        return self._finish_shuffled(plan, columns_global, table.row_valid,
                                     rep_columns, table.capacity,
                                     stats=stats,
                                     in_rows=list(table.row_counts))

    def _finish_shuffled(self, plan: ir.Query, columns_global: dict,
                         row_valid, rep_columns: dict, cap: int,
                         stats=None, in_rows=None) -> ColumnarChunk:
        """Key-hash all_to_all finish, shared by two stage shapes:

        - GROUP BY (route by group key): every device owns complete
          groups, so group+having run fully local;
        - window stage (route by PARTITION BY key): every device owns
          complete partitions, so the segmented-scan window stage is
          exact per device.

        Only order/project/offset/limit merge at the front.  Operates on
        bare sharded planes so it also finishes partitioned-join
        outputs."""
        _FP_ALL_TO_ALL.hit()
        from dataclasses import replace as dc_replace

        import numpy as np

        from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
        from ytsaurus_tpu.chunks.columnar import pad_capacity
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder, _mix_u64,
            _combine_u64,
        )

        mesh = self.mesh
        n = mesh.devices.size

        # Bind where + routing-key expressions (PARTITION BY keys for a
        # window stage, group keys otherwise) against the (shared) vocab.
        key_items = plan.window.partition_items if plan.window is not None \
            else plan.group.group_items

        def bind_keys():
            bind_ctx = BindContext(columns={
                name: ColumnBinding(type=rc.type, vocab=rc.dictionary)
                for name, rc in rep_columns.items()})
            binder = ExprBinder(bind_ctx)
            where_b = binder.bind(plan.where) if plan.where is not None else None
            key_b = [binder.bind(item.expr) for item in key_items]
            return bind_ctx, where_b, key_b

        bind_ctx, where_b, key_b = bind_keys()
        bindings = tuple(bind_ctx.bindings)
        names = [c.name for c in plan.schema if c.name in columns_global]
        columns_global = {name: columns_global[name] for name in names}

        def dest_ids(columns, row_valid, bnd):
            ctx = EmitContext(columns=columns, bindings=bnd, capacity=cap)
            mask = row_valid
            if where_b is not None:
                d, v = where_b.emit(ctx)
                mask = mask & v & d.astype(bool)
            acc = jnp.full(cap, np.uint64(0x9E3779B97F4A7C15), dtype=jnp.uint64)
            for kb in key_b:
                data, valid = kb.emit(ctx)
                if data.dtype == jnp.bool_:
                    data = data.astype(jnp.int8)
                h = _mix_u64(_canonical_hash_plane(data))
                h = jnp.where(valid, h, jnp.zeros_like(h))
                acc = _combine_u64(acc, h)
            pid = (acc % np.uint64(n)).astype(jnp.int32)
            return jnp.where(mask, pid, n), mask

        # Pass 1: transfer matrix → exact quota.  Cached + AOT-tiered
        # like every SPMD program (a fresh closure per query used to
        # defeat jax.jit's identity cache — the count pass silently
        # recompiled on every shuffled query).
        def count_pass(columns, row_valid, bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            return transfer_counts(pid, mask, n)

        count_key = ("shuffled-count", plan_fingerprint(plan), n, cap,
                     tuple(bind_ctx.structure),
                     tuple((tuple(b.shape), str(b.dtype))
                           for b in bindings))
        counts = self._dispatch_spmd(
            count_key,
            lambda: shard_map(
                count_pass, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
                out_specs=P(SHARD_AXIS), check_vma=False),
            (columns_global, row_valid, bindings))
        _note_host_sync()
        # analyze: allow(host-sync): all_to_all quota is a host decision — one transfer-matrix read
        counts_np = np.asarray(counts)
        quota = pad_capacity(max(int(counts_np.max()), 1))
        recv_cap = quota * n

        # Local plan: complete groups (group + having) or complete
        # partitions (where + window, identity projection carrying the
        # slots) per device; then the front (order/project/offset/limit)
        # runs ON THE MESH over the all_gathered rows — no host
        # round-trip (the round-1 host-merge contradiction of this
        # module's framing).
        local_plan = dc_replace(plan, order=None, project=None, offset=0,
                                limit=None)
        local_rep = _RepChunk(
            capacity=recv_cap,
            columns={name: _RepColumn(type=rc.type,
                                      dictionary=rc.dictionary)
                     for name, rc in rep_columns.items()})
        prepared_local = prepare(local_plan, local_rep)
        front = ir.FrontQuery(
            schema=local_plan.output_schema(), order=plan.order,
            project=plan.project, offset=plan.offset, limit=plan.limit)
        out_cap = prepared_local.out_capacity
        front_rep = _RepChunk(
            capacity=n * out_cap,
            columns={c.name: _RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_local.output})
        prepared_front = prepare(front, front_rep)

        def exchange_group_front(columns, row_valid, bnd, local_bnd,
                                 front_bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            recv, recv_mask = route_rows(columns, pid, n, quota, cap)
            planes, count = prepared_local.run(recv, recv_mask, local_bnd)
            shard_mask = jnp.arange(out_cap) < count
            gathered = {}
            for out_col, (d, v) in zip(prepared_local.output, planes):
                gathered[out_col.name] = (
                    jax.lax.all_gather(d, SHARD_AXIS)
                    .reshape((-1,) + d.shape[1:]),
                    jax.lax.all_gather(v, SHARD_AXIS).reshape(-1))
            g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
            return prepared_front.run(gathered, g_mask, front_bnd)

        key = ("shuffled", plan_fingerprint(plan), n, cap, quota,
               # dest_ids' where/key binds can bake host constants
               # (concat widths) — fold their structure notebook in.
               tuple(bind_ctx.structure),
               prepared_local.binding_shapes(),
               prepared_front.binding_shapes())
        out_planes, out_count = self._dispatch_spmd(
            key,
            lambda: shard_map(
                exchange_group_front, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(), P()),
                out_specs=P(), check_vma=False),
            (columns_global, row_valid, bindings,
             tuple(prepared_local.bindings),
             tuple(prepared_front.bindings)))
        _note_host_sync()
        # Mesh parity telemetry (ISSUE 20): the quota decision above
        # already transferred the FULL n x n transfer matrix to the
        # host, so this rung reports the same exchange detail as the
        # fused block — per-shard received rows (column sums) give the
        # post-exchange skew — at zero additional transfers.
        from ytsaurus_tpu.parallel.whole_plan import (
            _mesh_exchange_entry, _row_bytes)
        entry = _mesh_exchange_entry(
            "shuffle/stitched", counts_np.reshape(-1),
            int(counts_np.max()), quota, _row_bytes(rep_columns))
        recv_rows = [int(r) for r in counts_np.sum(axis=0)]
        _stitched_mesh_block(
            stats, plan, key, n,
            in_rows if in_rows is not None else
            [int(r) for r in counts_np.sum(axis=1)],
            recv_rows, [entry])
        return _assemble_chunk(prepared_front.output, out_planes,
                               out_count)

    def _prepare_joins(self, plan: ir.Query, table: ShardedTable,
                       foreign_chunks: dict) -> "Optional[_JoinSetup]":
        """Bind every join as a replicated lookup: sort the foreign side
        once on the host device, verify key uniqueness, and return a
        traceable per-shard probe step.  String keys ride merged
        vocabularies (self codes remapped at probe time via a binding
        table, foreign codes remapped host-side before the sort).
        Returns None when any join's foreign keys are NOT unique — the
        caller falls back to the partitioned-exchange path."""
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder,
        )
        from ytsaurus_tpu.query.engine.joins import (
            _bind_keys, _emit_encoded_keys, probe_replicated,
        )

        cap = table.capacity
        bindings: list = []
        namespace: dict[str, ColumnBinding] = {
            name: ColumnBinding(type=col.type, vocab=col.dictionary)
            for name, col in table.columns.items()}
        rep_columns: dict = {
            name: _RepColumn(type=col.type, dictionary=col.dictionary)
            for name, col in table.columns.items()}
        steps = []          # (self_bound, n_keys, is_left, flat_names, arg_slice)
        args: list = []
        fingerprint_parts = []

        for join in plan.joins:
            foreign = foreign_chunks.get(join.foreign_table)
            if foreign is None:
                raise YtError(
                    f"No data provided for join table "
                    f"{join.foreign_table!r}",
                    code=EErrorCode.QueryExecutionError)
            # Bind self keys against the namespace accumulated so far.
            bind_structure: list = []
            bind_ctx = BindContext(columns=dict(namespace),
                                   bindings=bindings,
                                   structure=bind_structure)
            binder = ExprBinder(bind_ctx)
            self_bound = [binder.bind(e) for e in join.self_equations]
            f_bound = _bind_keys(foreign, join.foreign_schema,
                                 join.foreign_equations, bindings,
                                 structure=bind_structure)
            self_slots, foreign_slots = _vocab_remap_slots(
                self_bound, f_bound, bindings)
            # Host phase cached per (join shape, foreign chunk identity):
            # repeated queries against an unchanged dimension table must
            # not re-sort it or pay the uniqueness-check device sync.
            f_order, f_sorted, unique = _foreign_host_order(
                self._cache, join, foreign, self_bound, f_bound,
                foreign_slots, bindings)
            n_foreign = foreign.row_count
            if not unique:
                return None     # fact-to-fact: partitioned exchange path
            # Replicated args: sorted key planes + gathered foreign columns.
            arg_start = len(args)
            for v, d in f_sorted:
                args.append(v)
                args.append(d)
            flat_names = []
            for fname in join.foreign_columns:
                fcol = foreign.columns[fname]
                flat = f"{join.alias}.{fname}" if join.alias else fname
                flat_names.append(flat)
                args.append(fcol.data[f_order])
                args.append(fcol.valid[f_order])
                namespace[flat] = ColumnBinding(type=fcol.type,
                                                vocab=fcol.dictionary)
                rep_columns[flat] = _RepColumn(type=fcol.type,
                                               dictionary=fcol.dictionary)
            args.append(jnp.asarray(n_foreign, dtype=jnp.int64))
            steps.append((self_bound, self_slots, len(f_bound),
                          join.is_left, flat_names, (arg_start, len(args)),
                          foreign.capacity))
            fingerprint_parts.append(
                (plan_fingerprint(ir.Query(schema=join.foreign_schema,
                                           source=join.foreign_table,
                                           joins=(join,))),
                 foreign.capacity, n_foreign > 0,
                 # Exact vocab lens + the bind-phase structure notebook
                 # (baked concat widths etc., ISSUE 10).
                 tuple(bind_structure),
                 tuple(len(b.vocab) if b.vocab is not None else -1
                       for b in list(self_bound) + list(f_bound))))

        join_bindings = tuple(bindings)

        def apply(columns, mask, bnd, join_args):
            for (self_bound, self_slots, n_keys, is_left, flat_names,
                 (a0, a1), f_cap) in steps:
                ctx = EmitContext(columns=columns, bindings=bnd,
                                  capacity=cap)
                self_keys = _emit_encoded_keys(
                    self_bound, self_slots, ctx)
                pulled, mask = probe_replicated(
                    join_args[a0:a1], n_keys, f_cap, self_keys, mask,
                    is_left)
                columns = dict(columns)
                for flat, plane in zip(flat_names, pulled):
                    columns[flat] = plane
            return columns, mask

        return _JoinSetup(apply=apply, bindings=join_bindings,
                          args=tuple(args), rep_columns=rep_columns,
                          fingerprint=tuple(fingerprint_parts))

    def _build(self, prepared_b, prepared_f, cap: int, join_setup=None):
        mesh = self.mesh
        join_apply = join_setup.apply if join_setup is not None else None

        def spmd(columns, row_valid, b_bindings, f_bindings,
                 join_args=(), join_bindings=()):
            if join_apply is not None:
                columns, row_valid = join_apply(columns, row_valid,
                                                join_bindings, join_args)
            planes, count = prepared_b.run(columns, row_valid, b_bindings)
            shard_mask = jnp.arange(prepared_b.out_capacity) < count
            gathered = {}
            for out_col, (d, v) in zip(prepared_b.output, planes):
                gd = jax.lax.all_gather(d, SHARD_AXIS) \
                    .reshape((-1,) + d.shape[1:])
                gv = jax.lax.all_gather(v, SHARD_AXIS).reshape(-1)
                gathered[out_col.name] = (gd, gv)
            g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
            return prepared_f.run(gathered, g_mask, f_bindings)

        # check_vma=False: outputs ARE replicated (every device computes the
        # same front merge over the all_gathered states), but the checker
        # can't infer that through the gather+sort pipeline.
        n_extra = 2 if join_apply is not None else 0
        return shard_map(
            spmd, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P())
            + (P(),) * n_extra,
            out_specs=P(), check_vma=False)


def coordinate_distributed(plan: ir.Query, mesh: Mesh,
                           chunks: Sequence[ColumnarChunk],
                           foreign_chunks: Optional[dict] = None,
                           evaluator: Optional[DistributedEvaluator] = None,
                           host_evaluator=None,
                           prefer_shuffle: bool = True,
                           stats=None) -> ColumnarChunk:
    """Distributed execution with a graceful-degradation ladder (ISSUE 2,
    extended by ISSUE 12's whole-plan rung):

        whole-plan fused SPMD  →  all_to_all co-partition  →
        gather-merge SPMD  →  host coordinator

    Each rung trades throughput for fewer moving parts: the whole-plan
    rung fuses every stage (and its exchange) into ONE program with one
    final host sync (parallel/whole_plan.py — gated per plan by
    `can_fuse` and `CompileConfig.whole_plan`), the stitched shuffle
    path needs every device link healthy, gather-merge only the
    all_gather collective, and the host coordinator nothing but
    per-shard programs (which carry their own per-shard retry —
    query/coordinator.py).  A YtError on one rung degrades to the next
    instead of failing the query; the final error (if every rung fails)
    aggregates the rungs' errors.  Ref: the coordinator falling back
    from CoordinateAndExecuteWithShuffle to plain CoordinateAndExecute
    when a tablet cell cannot serve the shuffle
    (engine_api/coordinator.h:92).
    """
    import logging as _logging

    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.utils.logging import log_event
    from ytsaurus_tpu.utils.tracing import child_span

    errors: "list[YtError]" = []
    de = evaluator if evaluator is not None else DistributedEvaluator(mesh)
    table = None
    if len(chunks) == mesh.devices.size and \
            all(not callable(c) for c in chunks):
        try:
            table = ShardedTable.from_chunks(mesh, list(chunks))
        except YtError:
            table = None        # ragged shards: host path handles them
    if table is not None:
        from ytsaurus_tpu.config import compile_config
        from ytsaurus_tpu.parallel.whole_plan import can_fuse, \
            run_whole_plan
        if compile_config().whole_plan and can_fuse(plan) is None:
            try:
                # One span per degradation rung, tagged with its rung
                # index — a query served off-rung shows WHERE it fell.
                with child_span("distributed.whole_plan", rung=0,
                                shards=len(chunks)):
                    return run_whole_plan(de, plan, table, stats=stats,
                                          foreign_chunks=foreign_chunks)
            except Exception as err:   # noqa: BLE001 — the fused rung
                # degrades on ANY fault (whole_plan.py's contract): a
                # plan shape whose fused lowering trips an XLA/dtype
                # error must still be served by the stitched rungs, not
                # fail a query that worked before this rung existed.
                if not isinstance(err, YtError):
                    err = YtError(f"whole-plan lowering failed: {err!r}",
                                  code=EErrorCode.QueryExecutionError)
                errors.append(err)
                log_event(_ladder_log, _logging.WARNING,
                          "degrade_to_stitched", error=str(err))
        shuffled_shape = (plan.group is not None and not plan.group.totals) \
            or (plan.window is not None and plan.window.partition_items)
        if prefer_shuffle and shuffled_shape and not plan.joins:
            try:
                with child_span("distributed.shuffle", rung=1,
                                shards=len(chunks)):
                    return de.run(plan, table, foreign_chunks,
                                  shuffle=True, stats=stats)
            except YtError as err:
                errors.append(err)
                log_event(_ladder_log, _logging.WARNING,
                          "degrade_to_gather", error=str(err))
        try:
            with child_span("distributed.gather_merge", rung=2,
                            shards=len(chunks)):
                return de.run(plan, table, foreign_chunks, shuffle=False,
                              stats=stats)
        except YtError as err:
            errors.append(err)
            log_event(_ladder_log, _logging.WARNING,
                      "degrade_to_host", error=str(err))
    try:
        with child_span("distributed.host_coordinate", rung=3,
                        shards=len(chunks)):
            return coordinate_and_execute(plan, list(chunks),
                                          foreign_chunks,
                                          evaluator=host_evaluator,
                                          stats=stats)
    except YtError as err:
        if not errors:
            raise
        raise YtError(
            "distributed query failed on every rung of the degradation "
            "ladder", code=EErrorCode.QueryExecutionError,
            inner_errors=[*errors, err]) from err
