"""Chunk store: content-addressed chunk files on a filesystem + block cache.

Ref mapping: data node chunk storage (server/node/data_node/blob_chunk.h,
chunk_store.h) collapses to a host-side store whose unit is the whole
columnar chunk (the reference's block granularity matters for its TCP data
plane; here chunks decode straight into device planes, so the cache holds
decoded chunks — the analog of the tablet node's in-memory mode
(tablet_node/in_memory_manager.h) at `uncompressed` level).
"""

from __future__ import annotations

import os
import threading
import uuid
from collections import OrderedDict
from typing import Optional

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import (
    DEFAULT_CODEC,
    deserialize_chunk,
    read_chunk_meta,
    serialize_chunk,
)
from ytsaurus_tpu.errors import EErrorCode, YtError


def new_chunk_id() -> str:
    return uuid.uuid4().hex


class FsChunkStore:
    """Chunks as files under root/<id[:2]>/<id>.chunk."""

    def __init__(self, root: str, codec: str = DEFAULT_CODEC):
        self.root = root
        self.codec = codec
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, chunk_id: str) -> str:
        return os.path.join(self.root, chunk_id[:2], f"{chunk_id}.chunk")

    def write_chunk(self, chunk: ColumnarChunk,
                    chunk_id: Optional[str] = None,
                    codec: Optional[str] = None) -> str:
        chunk_id = chunk_id or new_chunk_id()
        blob = serialize_chunk(chunk, codec or self.codec)
        path = self._path(chunk_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)      # atomic publish
        return chunk_id

    def read_chunk(self, chunk_id: str) -> ColumnarChunk:
        return deserialize_chunk(self._read_blob(chunk_id))

    def read_meta(self, chunk_id: str) -> dict:
        return read_chunk_meta(self._read_blob(chunk_id))

    def _read_blob(self, chunk_id: str) -> bytes:
        path = self._path(chunk_id)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise YtError(f"No such chunk {chunk_id}",
                          code=EErrorCode.NoSuchChunk)

    def exists(self, chunk_id: str) -> bool:
        return os.path.exists(self._path(chunk_id))

    def remove_chunk(self, chunk_id: str) -> None:
        try:
            os.unlink(self._path(chunk_id))
        except FileNotFoundError:
            pass

    def list_chunks(self) -> list[str]:
        out = []
        for sub in os.listdir(self.root):
            subdir = os.path.join(self.root, sub)
            if not os.path.isdir(subdir):
                continue
            for name in os.listdir(subdir):
                if name.endswith(".chunk"):
                    out.append(name[:-len(".chunk")])
        return sorted(out)


class ChunkCache:
    """LRU cache of DECODED chunks (device-resident planes), byte-budgeted.

    The HBM staging manager: holding a decoded chunk pins its planes on
    device, so the budget bounds device memory spent on cached table data.
    """

    def __init__(self, store: FsChunkStore, capacity_bytes: int = 2 << 30):
        self.store = store
        self.capacity_bytes = capacity_bytes
        self._entries: OrderedDict[str, tuple[ColumnarChunk, int]] = OrderedDict()
        self._used = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _chunk_bytes(chunk: ColumnarChunk) -> int:
        total = 0
        for col in chunk.columns.values():
            total += col.data.size * col.data.dtype.itemsize
            total += col.valid.size
        return total

    def get(self, chunk_id: str) -> ColumnarChunk:
        with self._lock:
            entry = self._entries.get(chunk_id)
            if entry is not None:
                self._entries.move_to_end(chunk_id)
                self.hits += 1
                return entry[0]
        chunk = self.store.read_chunk(chunk_id)
        size = self._chunk_bytes(chunk)
        with self._lock:
            self.misses += 1
            if chunk_id not in self._entries:
                self._entries[chunk_id] = (chunk, size)
                self._used += size
                while self._used > self.capacity_bytes and len(self._entries) > 1:
                    _, (_, evicted_size) = self._entries.popitem(last=False)
                    self._used -= evicted_size
        return chunk

    def invalidate(self, chunk_id: str) -> None:
        with self._lock:
            entry = self._entries.pop(chunk_id, None)
            if entry is not None:
                self._used -= entry[1]
