"""Equi-join execution: device sort-merge over columnar planes.

TPU-first redesign of the reference's MultiJoinOpHelper (cg_routines/
registry.cpp:599 — batched hash lookups into foreign tables): the foreign
side is lex-sorted by join key once, each self row finds its match range via
a vectorized lexicographic binary search, and the (self, foreign) index pairs
are materialized with a static output capacity computed host-side between the
two jitted phases (shape buckets keep recompiles bounded).

Both phases are jit-compiled and cached by (join fingerprint, capacities,
binding shapes); only the total match count crosses to the host between
them.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import lexsort_indices
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.expr import (
    BindContext,
    ColumnBinding,
    EmitContext,
    ExprBinder,
    _merge_vocabs,
    _pad_np,
    _remap_table,
    _vocab_bucket,
)
from ytsaurus_tpu.schema import TableSchema


def _bind_keys(chunk: ColumnarChunk, schema: TableSchema,
               equations: tuple[ir.TExpr, ...], shared_bindings: list,
               structure: "list | None" = None):
    """Host phase: bind join-key expressions against a chunk's vocabularies.
    All slots index into ONE shared bindings list so both sides' emit
    closures can run under the same traced tuple.  `structure` (when
    given) collects the bind-phase structure notebook — baked host
    constants like concat's pair width — which the CALLER must fold
    into its program-cache key (ISSUE 10 sharing contract)."""
    bind_ctx = BindContext(columns={
        c.name: ColumnBinding(type=c.type, vocab=chunk.columns[c.name].dictionary)
        for c in schema}, bindings=shared_bindings,
        structure=structure if structure is not None else [])
    binder = ExprBinder(bind_ctx)
    return [binder.bind(e) for e in equations]


def _emit_encoded_keys(bound, remap_slots, ctx: EmitContext):
    """Trace phase: emit key planes encoded as (null_rank, value) pairs with
    string codes remapped onto the shared vocabulary."""
    out = []
    for b, slot in zip(bound, remap_slots):
        data, valid = b.emit(ctx)
        if slot is not None:
            table = ctx.bindings[slot]
            data = table[jnp.clip(data, 0, table.shape[0] - 1)]
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
        data = jnp.where(valid, data, jnp.zeros_like(data))
        out.append((valid.astype(jnp.int8), data))
    return out


def _lex_less(a_planes, b_planes, a_idx, b_idx, or_equal: bool):
    """Lexicographic a[a_idx] < b[b_idx] (or <= when or_equal) over encoded
    (null_rank, value) key plane pairs; null sorts before any value."""
    result = jnp.full(a_idx.shape, or_equal, dtype=bool)
    for (av, ad), (bv, bd) in reversed(list(zip(a_planes, b_planes))):
        a_v, a_d = av[a_idx], ad[a_idx]
        b_v, b_d = bv[b_idx], bd[b_idx]
        lt = (a_v < b_v) | ((a_v == b_v) & (a_d < b_d))
        eq = (a_v == b_v) & (a_d == b_d)
        result = lt | (eq & result)
    return result


def _lex_searchsorted(sorted_planes, n_sorted, max_n: int, query_planes,
                      side: str):
    """For each query row, binary-search the sorted key planes.
    side='left' → first index whose key >= query; 'right' → first > query.
    `n_sorted` is a traced scalar (live row count); `max_n` is the static
    capacity bound driving the iteration count so the compiled program is
    row-count independent."""
    cap_q = query_planes[0][0].shape[0]
    lo = jnp.zeros(cap_q, dtype=jnp.int64)
    hi = jnp.full(cap_q, n_sorted, dtype=jnp.int64)
    iters = max(1, int(np.ceil(np.log2(max(max_n, 2)))) + 1)
    q_idx = jnp.arange(cap_q)

    def body(_, carry):
        lo, hi = carry
        active = lo < hi
        mid = (lo + hi) // 2
        mid_c = jnp.clip(mid, 0, max(max_n - 1, 0))
        go_right = _lex_less(sorted_planes, query_planes, mid_c, q_idx,
                             or_equal=(side == "right"))
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return lo


def sort_foreign_keys(f_keys, f_valid):
    """Sort encoded foreign key planes (masked rows last); returns
    (f_order, f_sorted).  THE foreign-side ordering used by both the host
    join phases and the SPMD broadcast join."""
    sort_keys = []
    for v, d in reversed(f_keys):
        sort_keys.extend([d, v])
    sort_keys.append((~f_valid).astype(jnp.int8))
    f_order = lexsort_indices(sort_keys)
    return f_order, [(v[f_order], d[f_order]) for v, d in f_keys]


def null_key_mask(self_keys):
    """Rows whose join key has ANY null component (match nothing — SQL
    semantics)."""
    cap = self_keys[0][0].shape[0]
    s_null = jnp.zeros(cap, dtype=bool)
    for v, _ in self_keys:
        s_null = s_null | (v == 0)
    return s_null


def probe_replicated(sl, n_keys: int, f_cap: int, self_keys, mask,
                     is_left: bool):
    """THE broadcast-join probe body, shared by the stitched SPMD join
    (distributed.py) and the fused whole-plan join (whole_plan.py).

    `sl` is one join's replicated arg slice, laid out as
    [v_0, d_0, … v_{k-1}, d_{k-1},  pulled (data, valid) pairs …,
    n_foreign]: lex-search the sorted foreign key planes for each self
    row, gather every pulled plane at the (unique-key) match row masked
    to matched, and narrow the row mask for INNER joins.  Returns
    (pulled_planes, new_mask)."""
    f_sorted = [(sl[2 * i], sl[2 * i + 1]) for i in range(n_keys)]
    n_foreign = sl[-1]
    lo = _lex_searchsorted(f_sorted, n_foreign, f_cap, self_keys, "left")
    hi = _lex_searchsorted(f_sorted, n_foreign, f_cap, self_keys,
                           "right")
    matched = mask & ~null_key_mask(self_keys) & (hi > lo)
    pos = jnp.clip(lo, 0, f_cap - 1)
    base = 2 * n_keys
    pulled = [(sl[base + 2 * i][pos], sl[base + 2 * i + 1][pos] & matched)
              for i in range((len(sl) - base - 1) // 2)]
    return pulled, (mask if is_left else matched)


def _join_fingerprint(join: ir.JoinClause) -> str:
    # The full JoinClause serialized (equations, alias, is_left, pulled
    # columns) as a SHAPE fingerprint (ISSUE 10): the phase programs
    # read equation literals from the shared bindings tuple per call,
    # and the cache key already carries binding shapes + exact vocab
    # structure, so one program serves every equation constant.
    from ytsaurus_tpu.query.parameterize import plan_fingerprint
    return plan_fingerprint(ir.Query(
        schema=join.foreign_schema, source=join.foreign_table,
        joins=(join,)))


def execute_join(chunk: ColumnarChunk, combined_schema: TableSchema,
                 join: ir.JoinClause, foreign_chunk: ColumnarChunk,
                 cache: dict) -> ColumnarChunk:
    """Materialize `chunk ⋈ foreign_chunk` into a wider columnar chunk.

    `combined_schema` is the namespace *after* this join (flat names);
    `cache` holds the compiled phase programs (owned by the Evaluator so
    lifetime/clearing follow the plan cache).
    """
    self_schema = chunk.schema
    all_bindings: list = []
    bind_structure: list = []
    self_bound = _bind_keys(chunk, self_schema, join.self_equations,
                            all_bindings, structure=bind_structure)
    f_bound = _bind_keys(foreign_chunk, join.foreign_schema,
                         join.foreign_equations, all_bindings,
                         structure=bind_structure)
    # String keys: remap both sides onto merged vocabularies (host).
    self_slots: list = []
    foreign_slots: list = []

    def add_binding(value):
        all_bindings.append(value)
        return len(all_bindings) - 1

    for sb, fb in zip(self_bound, f_bound):
        if sb.vocab is not None or fb.vocab is not None:
            merged = _merge_vocabs(sb.vocab, fb.vocab)
            s_vocab = sb.vocab if sb.vocab is not None else \
                np.array([], dtype=object)
            f_vocab = fb.vocab if fb.vocab is not None else \
                np.array([], dtype=object)
            s_table = _remap_table(s_vocab, merged)
            f_table = _remap_table(f_vocab, merged)
            self_slots.append(add_binding(jnp.asarray(
                _pad_np(s_table, _vocab_bucket(len(s_table)), 0))))
            foreign_slots.append(add_binding(jnp.asarray(
                _pad_np(f_table, _vocab_bucket(len(f_table)), 0))))
        else:
            self_slots.append(None)
            foreign_slots.append(None)

    n_foreign = foreign_chunk.row_count
    # Exact vocab lengths of every key expr: bound-vocab-derived Python
    # constants (e.g. concat's pair-table width) bake into the traced
    # program, and bucket-padded binding shapes alone cannot distinguish
    # them.
    vocab_structure = tuple(
        (len(b.vocab) if b.vocab is not None else -1)
        for b in list(self_bound) + list(f_bound))
    cache_key = (_join_fingerprint(join), chunk.capacity,
                 foreign_chunk.capacity,
                 tuple(c.name for c in self_schema),
                 vocab_structure,
                 # Bind-phase structure notebook (ISSUE 10): host
                 # constants the equation binds BAKE (concat's nb
                 # multiplier) that neither vocab lengths nor padded
                 # binding shapes can distinguish.
                 tuple(bind_structure),
                 tuple((tuple(b.shape), str(b.dtype)) for b in all_bindings))
    entry = cache.get(cache_key)
    if entry is None:
        entry = _build_join_programs(
            self_bound, f_bound, self_slots, foreign_slots,
            chunk.capacity, foreign_chunk.capacity, join.is_left,
            [c.name for c in self_schema], list(join.foreign_columns))
        cache[cache_key] = entry
    phase1, make_phase2 = entry

    self_columns = {c.name: (chunk.columns[c.name].data,
                             chunk.columns[c.name].valid)
                    for c in self_schema}
    foreign_columns = {name: (foreign_chunk.columns[name].data,
                              foreign_chunk.columns[name].valid)
                       for name in set(list(join.foreign_columns) +
                                       list(join.foreign_schema.column_names))}
    args = (self_columns, foreign_columns, chunk.row_valid,
            foreign_chunk.row_valid, tuple(all_bindings),
            jnp.asarray(n_foreign, dtype=jnp.int64))
    lo, counts, f_order, total = phase1(*args)
    total = int(total)
    out_cap = pad_capacity(max(total, 1))
    phase2 = make_phase2(out_cap)
    out_planes, self_row, foreign_row = phase2(*args, lo, counts, f_order)

    columns: dict[str, Column] = {}
    self_row_np = None
    for name, col in chunk.columns.items():
        data, valid = out_planes["self"][name]
        host_values = None
        if col.host_values is not None:
            if self_row_np is None:
                # analyze: allow(host-sync): string/any columns live on host — the gather index must cross once
                self_row_np = np.asarray(self_row)
            host_values = _gather_host(col, self_row_np, out_cap)
        columns[name] = replace(col, data=data, valid=valid,
                                host_values=host_values)
    foreign_row_np = None
    for fname in join.foreign_columns:
        fcol = foreign_chunk.columns[fname]
        flat = f"{join.alias}.{fname}" if join.alias else fname
        data, valid = out_planes["foreign"][fname]
        host_values = None
        if fcol.host_values is not None:
            if foreign_row_np is None:
                # analyze: allow(host-sync): string/any columns live on host — the gather index must cross once
                foreign_row_np = np.asarray(foreign_row)
            host_values = _gather_host(fcol, foreign_row_np, out_cap)
        columns[flat] = replace(fcol, data=data, valid=valid,
                                host_values=host_values)
    out_columns = {}
    for col_schema in combined_schema:
        if col_schema.name not in columns:
            raise YtError(f"Join produced no column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        out_columns[col_schema.name] = columns[col_schema.name]
    return ColumnarChunk(schema=combined_schema, row_count=total,
                         columns=out_columns)


def _build_join_programs(self_bound, f_bound, self_slots, foreign_slots,
                         self_cap, foreign_cap,
                         is_left, self_names, foreign_names):
    def phase1(self_columns, foreign_columns, s_valid, f_valid, bindings,
               n_foreign):
        s_ctx = EmitContext(columns=self_columns, bindings=bindings,
                            capacity=self_cap)
        f_ctx = EmitContext(columns=foreign_columns, bindings=bindings,
                            capacity=foreign_cap)
        self_keys = _emit_encoded_keys(self_bound, self_slots, s_ctx)
        foreign_keys = _emit_encoded_keys(f_bound, foreign_slots, f_ctx)
        # Sort foreign side (first key most significant; masked rows last).
        f_order, f_sorted = sort_foreign_keys(foreign_keys, f_valid)
        lo = _lex_searchsorted(f_sorted, n_foreign, foreign_cap, self_keys,
                               "left")
        hi = _lex_searchsorted(f_sorted, n_foreign, foreign_cap, self_keys,
                               "right")
        s_null = null_key_mask(self_keys)
        counts = jnp.where(s_valid & ~s_null, hi - lo, 0)
        if is_left:
            per_row = jnp.where(s_valid, jnp.maximum(counts, 1), 0)
        else:
            per_row = counts
        total = jnp.sum(per_row)
        return lo, counts, f_order, total

    phase2_cache: dict[int, callable] = {}

    def make_phase2(out_cap: int):
        fn = phase2_cache.get(out_cap)
        if fn is not None:
            return fn

        def phase2(self_columns, foreign_columns, s_valid, f_valid, bindings,
                   n_foreign, lo, counts, f_order):
            if is_left:
                per_row = jnp.where(s_valid, jnp.maximum(counts, 1), 0)
            else:
                per_row = counts
            offsets = jnp.cumsum(per_row)
            total = offsets[-1]
            starts = jnp.concatenate(
                [jnp.zeros(1, dtype=offsets.dtype), offsets[:-1]])
            out_idx = jnp.arange(out_cap)
            self_row = jnp.searchsorted(offsets, out_idx, side="right")
            self_row = jnp.clip(self_row, 0, self_cap - 1)
            within = out_idx - starts[self_row]
            matched = counts[self_row] > 0
            foreign_pos = jnp.clip(lo[self_row] + within, 0, foreign_cap - 1)
            foreign_row = f_order[foreign_pos]
            out_valid_row = out_idx < total
            out = {"self": {}, "foreign": {}}
            for name in self_names:
                data, valid = self_columns[name]
                out["self"][name] = (data[self_row],
                                     valid[self_row] & out_valid_row)
            for name in foreign_names:
                data, valid = foreign_columns[name]
                out["foreign"][name] = (
                    data[foreign_row],
                    valid[foreign_row] & out_valid_row & matched)
            return out, self_row, foreign_row

        # lo/counts/f_order are phase1 outputs owned by execute_join
        # and phase2 is their only consumer — donate them so XLA reuses
        # the three chunk-sized planes for phase2's gather outputs
        # (ISSUE 19; inert on CPU).  Donation mode bakes at build time;
        # programs are cached, so a mid-process config flip keeps the
        # built mode (donation never changes results, only residency).
        from ytsaurus_tpu.config import compile_config
        donate = (6, 7, 8) if compile_config().donate_buffers else ()
        fn = jax.jit(phase2, donate_argnums=donate)
        phase2_cache[out_cap] = fn
        return fn

    return jax.jit(phase1), make_phase2


def _gather_host(col: Column, idx: np.ndarray, out_cap: int):
    if col.host_values is None:
        return None
    vals = [col.host_values[int(i)] if int(i) < len(col.host_values) else None
            for i in idx[:out_cap]]
    return vals
