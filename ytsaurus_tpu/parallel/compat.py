"""JAX version compatibility for the parallel layer.

`shard_map` moved from `jax.experimental.shard_map` (jax 0.4.x, kwarg
`check_rep`) to the top-level `jax.shard_map` (kwarg `check_vma`).  The
modules in this package code against the new spelling; this shim adapts
older installs so the SPMD paths work on both.
"""

from __future__ import annotations

try:                                     # jax >= 0.5
    from jax import shard_map            # noqa: F401
except ImportError:                      # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
