// Package yt is a native Go client for the ytsaurus_tpu HTTP proxy
// (/api/v4) — the counterpart of the reference's first-class Go SDK
// (yt/go/yt/interface.go + yt/go/yt/internal/httpclient) over this
// framework's REST surface.  Dependency-free: net/http + encoding/json
// only.  Every command in the driver registry is callable through
// Execute; the typed verbs below cover the interface.go CRUD +
// dynamic-table surface.
package yt

import (
	"bytes"
	"encoding/json"
	"fmt"
	"io"
	"net/http"
	"strings"
	"time"
)

// Error is a non-2xx proxy response (the X-YT-Error payload rides the
// response body as JSON).
type Error struct {
	HTTPStatus int
	Body       string
}

func (e *Error) Error() string {
	return fmt.Sprintf("yt: proxy error (HTTP %d): %s", e.HTTPStatus, e.Body)
}

// Client talks to one HTTP proxy.  Zero-value fields are defaulted by
// NewClient; construct directly only if you set every field.
type Client struct {
	Addr       string // "host:port"
	User       string // rides X-YT-User (per-request principal)
	HTTPClient *http.Client
}

// NewClient returns a client for the proxy at addr ("host:port").
func NewClient(addr string) *Client {
	return &Client{
		Addr:       addr,
		User:       "root",
		HTTPClient: &http.Client{Timeout: 120 * time.Second},
	}
}

func (c *Client) do(method, path string, body []byte,
	contentType string) ([]byte, error) {
	req, err := http.NewRequest(method, "http://"+c.Addr+path,
		bytes.NewReader(body))
	if err != nil {
		return nil, err
	}
	req.Header.Set("X-YT-User", c.User)
	if contentType != "" {
		req.Header.Set("Content-Type", contentType)
	}
	resp, err := c.HTTPClient.Do(req)
	if err != nil {
		return nil, err
	}
	defer resp.Body.Close()
	data, err := io.ReadAll(resp.Body)
	if err != nil {
		return nil, err
	}
	if resp.StatusCode < 200 || resp.StatusCode >= 300 {
		return nil, &Error{HTTPStatus: resp.StatusCode, Body: string(data)}
	}
	return data, nil
}

// Execute POSTs one driver command with a JSON parameter object and
// returns the raw response body (a {"value": ...} wrapper for JSON
// results, raw format bytes for table payloads).
func (c *Client) Execute(command string, params any) ([]byte, error) {
	blob, err := json.Marshal(params)
	if err != nil {
		return nil, err
	}
	return c.do("POST", "/api/v4/"+command, blob, "application/json")
}

// execute runs a command and unmarshals the {"value": ...} wrapper into
// out (which may be nil for commands whose result is ignored).
func (c *Client) execute(command string, params any, out any) error {
	data, err := c.Execute(command, params)
	if err != nil || out == nil {
		return err
	}
	var wrapper struct {
		Value json.RawMessage `json:"value"`
	}
	if err := json.Unmarshal(data, &wrapper); err != nil {
		return fmt.Errorf("yt: bad %s response: %w", command, err)
	}
	if wrapper.Value == nil {
		return nil
	}
	return json.Unmarshal(wrapper.Value, out)
}

// Ping checks proxy liveness (GET /ping).
func (c *Client) Ping() error {
	_, err := c.do("GET", "/ping", nil, "")
	return err
}

// CreateOptions mirrors the create verb's optional parameters.
type CreateOptions struct {
	Recursive  bool
	Attributes map[string]any
}

func (c *Client) Create(typ, path string, opts *CreateOptions) error {
	params := map[string]any{"type": typ, "path": path}
	if opts != nil {
		params["recursive"] = opts.Recursive
		if opts.Attributes != nil {
			params["attributes"] = opts.Attributes
		}
	}
	return c.execute("create", params, nil)
}

func (c *Client) Exists(path string) (bool, error) {
	var out bool
	err := c.execute("exists", map[string]any{"path": path}, &out)
	return out, err
}

// Get reads a Cypress node or attribute into out (a pointer).
func (c *Client) Get(path string, out any) error {
	return c.execute("get", map[string]any{"path": path}, out)
}

func (c *Client) Set(path string, value any) error {
	return c.execute("set",
		map[string]any{"path": path, "value": value}, nil)
}

func (c *Client) Remove(path string, recursive bool) error {
	return c.execute("remove",
		map[string]any{"path": path, "recursive": recursive}, nil)
}

func (c *Client) List(path string) ([]string, error) {
	var out []string
	err := c.execute("list", map[string]any{"path": path}, &out)
	return out, err
}

// WriteTable writes rows to a static table (overwrites).
func (c *Client) WriteTable(path string, rows []map[string]any) error {
	return c.execute("write_table",
		map[string]any{"path": path, "rows": rows}, nil)
}

// ReadTable reads a static table as rows (json-lines wire format).
func (c *Client) ReadTable(path string) ([]map[string]any, error) {
	data, err := c.Execute("read_table",
		map[string]any{"path": path, "format": "json"})
	if err != nil {
		return nil, err
	}
	return parseJSONRows(data)
}

func parseJSONRows(data []byte) ([]map[string]any, error) {
	rows := []map[string]any{}
	for _, line := range strings.Split(string(data), "\n") {
		line = strings.TrimSpace(line)
		if line == "" {
			continue
		}
		var row map[string]any
		if err := json.Unmarshal([]byte(line), &row); err != nil {
			return nil, fmt.Errorf("yt: bad table row %q: %w", line, err)
		}
		rows = append(rows, row)
	}
	return rows, nil
}

// -- dynamic tables ---------------------------------------------------------

func (c *Client) MountTable(path string) error {
	return c.execute("mount_table", map[string]any{"path": path}, nil)
}

func (c *Client) UnmountTable(path string) error {
	return c.execute("unmount_table", map[string]any{"path": path}, nil)
}

func (c *Client) InsertRows(path string, rows []map[string]any) error {
	return c.execute("insert_rows",
		map[string]any{"path": path, "rows": rows}, nil)
}

func (c *Client) DeleteRows(path string, keys [][]any) error {
	return c.execute("delete_rows",
		map[string]any{"path": path, "keys": keys}, nil)
}

// LookupRows point-reads; each result element is the row or nil.
func (c *Client) LookupRows(path string, keys [][]any) ([]map[string]any, error) {
	var out []map[string]any
	err := c.execute("lookup_rows",
		map[string]any{"path": path, "keys": keys}, &out)
	return out, err
}

// SelectRows runs a QL query and returns the result rows.
func (c *Client) SelectRows(query string) ([]map[string]any, error) {
	var out []map[string]any
	err := c.execute("select_rows", map[string]any{"query": query}, &out)
	return out, err
}
