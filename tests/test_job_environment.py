"""Job isolation environments: per-job resource enforcement.

Ref model: server/node/exec_node/job_environment.cpp (simple / porto /
CRI) — here realized as rlimits applied between fork and exec, with
failure classification so an operator sees WHY a limited job died.
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.operations.job_environment import (
    classify_failure,
    limits_from_spec,
    make_preexec,
)


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


def test_limits_extraction():
    assert limits_from_spec({}) is None
    assert limits_from_spec({"memory_limit": 1 << 30}) == \
        {"memory_limit": 1 << 30}
    assert limits_from_spec({"cpu_limit": 2, "nice": 5,
                             "command": "cat"}) == \
        {"cpu_limit": 2, "nice": 5}
    assert make_preexec(None) is None
    assert make_preexec({"memory_limit": 1 << 30}) is not None


def test_memory_limit_kills_allocation(client):
    """A job allocating past memory_limit dies and the error names the
    cause; a job under the limit sails through."""
    client.write_table("//in", [{"k": 1}])
    hog = ("python3 -c \"import sys; x = bytearray(512 * 1024 * 1024); "
           "sys.stdout.write(sys.stdin.read())\"")
    with pytest.raises(YtError) as ei:
        client.run_map(hog, "//in", "//out",
                       memory_limit=128 << 20, remote_jobs=False)
    flat = str(ei.value.to_dict())
    assert "memory limit exceeded" in flat or "MemoryError" in flat
    # Same allocation WITHOUT the limit succeeds (the box has RAM).
    op = client.run_map(hog, "//in", "//out2", remote_jobs=False)
    assert op.state == "completed"


def test_cpu_limit_kills_spinner(client):
    """RLIMIT_CPU caps CPU seconds, distinct from wall-clock timeouts:
    a busy-loop dies even though no job_time_limit is set."""
    client.write_table("//in", [{"k": 1}])
    with pytest.raises(YtError) as ei:
        client.run_map("while :; do :; done", "//in", "//out",
                       cpu_limit=1, remote_jobs=False)
    flat = str(ei.value.to_dict())
    assert "cpu limit exceeded" in flat or "exit code -" in flat


def test_limited_job_within_budget_unaffected(client):
    client.write_table("//in", [{"k": i} for i in range(20)])
    op = client.run_map("cat", "//in", "//out",
                        memory_limit=256 << 20, cpu_limit=30,
                        max_open_files=256, remote_jobs=False)
    assert op.state == "completed"
    assert len(client.read_table("//out")) == 20


@pytest.mark.slow   # ~25s; tier-1 keeps limit-enforcement coverage via the
# in-process memory/cpu kill + within-budget tests above — this is the
# spawned-exec-node E2E variant of the same ladder.
def test_limits_enforced_on_exec_nodes(tmp_path):
    """The distributed path: limits ride the start_job RPC and the exec
    NODE applies them to the user process."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote

    with LocalCluster(str(tmp_path / "c"), n_nodes=1) as cluster:
        cl = connect_remote(cluster.primary_address)
        cl.write_table("//in", [{"k": 1}])
        hog = ("python3 -c \"import sys; x = bytearray(512 * 1024 * "
               "1024); sys.stdout.write(sys.stdin.read())\"")
        with pytest.raises(YtError) as ei:
            cl.run_map(hog, "//in", "//out", memory_limit=128 << 20)
        flat = str(ei.value.to_dict())
        assert "memory limit" in flat or "MemoryError" in flat or \
            "exited" in flat
        op = cl.run_map("cat", "//in", "//ok", memory_limit=256 << 20)
        assert op.state == "completed"
        cl.close()


def test_classify_failure():
    import signal
    assert classify_failure(0, b"", {"memory_limit": 1}) is None
    assert classify_failure(1, b"MemoryError",
                            {"memory_limit": 1}) == \
        "memory limit exceeded (RLIMIT_AS)"
    assert classify_failure(-signal.SIGXCPU, b"",
                            {"cpu_limit": 1}) == \
        "cpu limit exceeded (SIGXCPU)"
    assert classify_failure(1, b"boom", None) is None
