"""Structured errors with nested inner errors and stable codes.

TPU-native analog of the reference's TError (yt/yt/core/misc/error.h): an error
carries an integer code, a message, attributes, and a list of inner errors; the
whole tree serializes to/from plain dicts (and therefore YSON/JSON).
"""

from __future__ import annotations

import enum
from typing import Any, Iterable


class EErrorCode(enum.IntEnum):
    # Generic codes (ref: yt/yt/core/misc/public.h TErrorCode values).
    OK = 0
    Generic = 1
    Timeout = 3
    Canceled = 2

    # Query engine (ref: yt/yt/client/query_client/public.h).
    QueryParseError = 1000
    QueryTypeError = 1001
    QueryUnsupported = 1002
    QueryExecutionError = 1003

    # Chunk / storage.
    NoSuchChunk = 1100
    ChunkFormatError = 1101

    # Cypress / metadata.
    ResolveError = 500
    AlreadyExists = 501
    NoSuchNode = 502
    NoSuchTransaction = 503
    ConcurrentTransactionLockConflict = 402

    # Tablet / transactions.
    TransactionLockConflict = 1700
    NoSuchTablet = 1701
    TabletNotMounted = 1702
    RowIsBlocked = 1703
    TransactionAborted = 1704
    InvalidTransactionState = 1705

    # Scheduler / operations.
    NoSuchOperation = 1800
    OperationFailed = 1801

    # Table client (ref: yt/yt/client/table_client/public.h).
    SortOrderViolation = 301

    # Journals / quorum WAL.
    JournalPositionMismatch = 1850
    JournalEpochFenced = 1851
    JournalDivergence = 1852

    # Config (ref: yt/yt/core/ytree yson_struct validation).
    InvalidConfig = 216

    # Security (ref: yt/yt/client/security_client/public.h).
    AuthenticationError = 900
    AuthorizationError = 901
    AccountLimitExceeded = 902

    # RPC (ref: yt/yt/core/rpc/public.h EErrorCode).
    NoSuchMethod = 1900
    NoSuchService = 1901
    TransportError = 1902
    RpcTimeout = 1903
    PeerUnavailable = 1904

    # Query serving plane (ref: NRpc::EErrorCode::RequestQueueSizeLimit-
    # Exceeded + the request deadline propagated by TServiceContext).
    RequestThrottled = 1910
    DeadlineExceeded = 1911


class YtError(Exception):
    """An error with a code, attributes and nested inner errors."""

    def __init__(
        self,
        message: str,
        code: int = EErrorCode.Generic,
        attributes: dict[str, Any] | None = None,
        inner_errors: Iterable["YtError"] | None = None,
    ):
        super().__init__(message)
        self.message = message
        self.code = int(code)
        self.attributes = dict(attributes or {})
        self.inner_errors: list[YtError] = list(inner_errors or [])

    def find(self, code: int) -> "YtError | None":
        """Find an error with the given code anywhere in the tree."""
        if self.code == int(code):
            return self
        for inner in self.inner_errors:
            found = inner.find(code)
            if found is not None:
                return found
        return None

    def contains(self, code: int) -> bool:
        return self.find(code) is not None

    def to_dict(self) -> dict[str, Any]:
        return {
            "code": self.code,
            "message": self.message,
            "attributes": self.attributes,
            "inner_errors": [e.to_dict() for e in self.inner_errors],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "YtError":
        return cls(
            message=d.get("message", ""),
            code=d.get("code", EErrorCode.Generic),
            attributes=d.get("attributes") or {},
            inner_errors=[cls.from_dict(e) for e in d.get("inner_errors", [])],
        )

    def __str__(self) -> str:
        parts = [f"[{self.code}] {self.message}"]
        if self.attributes:
            parts.append(f"attrs={self.attributes}")
        for inner in self.inner_errors:
            inner_str = "\n    ".join(str(inner).splitlines())
            parts.append(f"\n  <- {inner_str}")
        return " ".join(parts[:2]) + "".join(parts[2:])


class YtResponseError(YtError):
    """Error returned from a service call."""


class ThrottledError(YtError):
    """Admission rejection from the query serving plane (or any bounded
    queue): the request was NEVER executed, so resending it — even a
    mutation — is safe.  Carries a `retry_after` hint (seconds) computed
    from the rejecting queue's observed drain rate; retry wrappers honor
    it instead of their generic backoff curve."""

    def __init__(self, message: str = "request throttled",
                 retry_after: float = 0.1, **kwargs):
        attributes = dict(kwargs.pop("attributes", None) or {})
        attributes.setdefault("retry_after", float(retry_after))
        super().__init__(message, code=EErrorCode.RequestThrottled,
                         attributes=attributes, **kwargs)

    @property
    def retry_after(self) -> float:
        return float(self.attributes.get("retry_after", 0.0))


def retry_after_hint(err: YtError) -> "float | None":
    """The `retry_after` hint carried by a throttled error anywhere in
    the tree (wire round-trips reconstruct plain YtErrors, so the hint
    must be read from attributes, not the ThrottledError type)."""
    throttled = err.find(EErrorCode.RequestThrottled)
    if throttled is None:
        return None
    hint = throttled.attributes.get("retry_after")
    return float(hint) if hint is not None else None
