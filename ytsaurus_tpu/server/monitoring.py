"""Monitoring HTTP endpoint: /metrics (Prometheus), /metrics/history
(bounded time-series rings), /accounting (per-tenant usage), /slo
(burn-rate alerts), /cluster (fleet roll-up), /orchid/..., /healthz,
/traces (query flight recorder).

Ref shape: library/profiling/solomon/exporter.h:25 — every daemon hosts a
pull endpoint the monitoring system scrapes; Orchid doubles as the
human-readable live-state browser.  stdlib http.server on a daemon thread
is plenty: scrape traffic is tiny and the handlers only read in-process
state.  The one outbound path is `/cluster`: the PRIMARY's monitoring
server scrapes every DiscoveryTracker-registered daemon's `/telemetry`
endpoint and serves the fleet view (member telemetry + merged alerts +
summed accounting).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.server.orchid import OrchidTree
from ytsaurus_tpu.utils.profiling import (
    MetricsHistory,
    ProfilerRegistry,
    get_history,
    get_registry,
)


class MonitoringServer:
    # Per-member scrape budget for the /cluster roll-up.
    CLUSTER_SCRAPE_TIMEOUT = 2.0

    def __init__(self, orchid: Optional[OrchidTree] = None,
                 registry: Optional[ProfilerRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 history: Optional[MetricsHistory] = None,
                 slo_tracker=None, accountant=None,
                 cluster_members: Optional[Callable[[], list]] = None):
        self.orchid = orchid or OrchidTree()
        self.registry = registry or get_registry()
        self._history = history
        self._slo_tracker = slo_tracker
        self._accountant = accountant
        # Fleet membership provider (primary only): () -> [{"id",
        # "address", "attributes"}] of every /daemons-registered member;
        # None serves /cluster over this process alone.
        self.cluster_members = cluster_members
        # Per-replica /serving scope (ISSUE 17): when several serving
        # replicas share one process (bench/test harnesses), each
        # replica's endpoint must report ITS gateway only or the
        # ReplicaRouter would see every replica's load on every scrape.
        # None keeps the default: every live gateway in the process.
        self.serving_gateways: Optional[list] = None
        # /tiers scope (ISSUE 18): the daemon points this at its
        # cluster's serving evaluator; None falls back to the process
        # default (engine-level embedders, tests).
        self.tier_evaluator = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):      # silence stderr chatter
                pass

            def do_GET(self):
                try:
                    outer._handle(self)
                except (ConnectionError, BrokenPipeError):
                    pass
                except Exception as exc:   # noqa: BLE001 — one bad orchid
                    # producer must yield a diagnosable 500, not a dropped
                    # connection.
                    try:
                        outer._reply(self, 500, repr(exc).encode(),
                                     "text/plain")
                    except (ConnectionError, BrokenPipeError):
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="monitoring-http")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- telemetry-plane data sources (overridable per server in tests) --------

    @property
    def history(self) -> MetricsHistory:
        return self._history if self._history is not None \
            else get_history()

    @property
    def slo_tracker(self):
        if self._slo_tracker is not None:
            return self._slo_tracker
        from ytsaurus_tpu.utils.slo import get_slo_tracker
        return get_slo_tracker()

    @property
    def accountant(self):
        if self._accountant is not None:
            return self._accountant
        from ytsaurus_tpu.query.accounting import get_accountant
        return get_accountant()

    # -- request handling ------------------------------------------------------

    def _handle(self, request) -> None:
        path, _, query_string = request.path.partition("?")
        params = {k: v[-1] for k, v in
                  urllib.parse.parse_qs(query_string).items()}
        if path == "/healthz":
            self._reply(request, 200, b"ok", "text/plain")
        elif path == "/failpoints":
            # Fault-injection observability (utils/failpoints.py): the
            # active schedule + cumulative per-site hit/trigger counters
            # (triggers also mirror into /metrics as failpoints_*).
            from ytsaurus_tpu.utils import failpoints
            body = json.dumps({
                "active_spec": failpoints.active_spec(),
                "schedule": failpoints.schedule_snapshot(),
                "sites": failpoints.counters(),
            }, indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/sanitizer":
            # Concurrency sanitizer (ISSUE 15): the bounded live report
            # of the instrumented-lock layer — observed acquisition
            # edges, lock-order inversions, hold-budget violations, and
            # blocking ops under hot locks (counters mirror on /metrics
            # as sanitizer_*).  {"enabled": false} when the sanitizer
            # is off (the production default).
            from ytsaurus_tpu.utils import sanitizers
            body = json.dumps(sanitizers.snapshot(), indent=2,
                              default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/serving":
            # Query serving plane (query/serving.py): per-pool admission
            # state + lookup batching counters of every live gateway in
            # this process (histograms export via /metrics serving_*).
            from ytsaurus_tpu.query.serving import serving_snapshot
            if self.serving_gateways is not None:
                gateways = [g.snapshot() for g in self.serving_gateways]
            else:
                gateways = serving_snapshot()
            body = json.dumps({"gateways": gateways},
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/views":
            # Continuous-query plane (ISSUE 13): every live view
            # daemon's registry walk — per-view cursor offset, lag,
            # freshness, pause state, and daemon roll-ups (the raw
            # sensors also render on /metrics as views_*).
            from ytsaurus_tpu.server.view_daemon import views_snapshot
            body = json.dumps({"daemons": views_snapshot()},
                              indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/tablet":
            # Tablet read-path caches (tablet/tablet.py): process-wide
            # snapshot-cache hit/miss/evict counters + bytes pinned
            # (the raw sensors also render on /metrics as
            # tablet_snapshot_cache_*).
            from ytsaurus_tpu.tablet.tablet import snapshot_cache_stats
            body = json.dumps({"snapshot_cache": snapshot_cache_stats()},
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/traces" or path.startswith("/traces/"):
            # Query flight recorder (ISSUE 5): the listing serves recent
            # trace summaries + the bounded slow-query/recent profile
            # logs; /traces/<trace_id> renders that trace's span tree.
            from ytsaurus_tpu.query.profile import get_flight_recorder
            from ytsaurus_tpu.utils.tracing import span_tree, trace_summaries
            if path == "/traces":
                body = json.dumps({
                    "recent_traces": trace_summaries(),
                    **get_flight_recorder().snapshot(),
                }, indent=2, default=_json_default).encode()
                self._reply(request, 200, body, "application/json")
            else:
                trace_id = path[len("/traces/"):]
                tree = span_tree(trace_id)
                if not tree:
                    self._reply(request, 404, json.dumps(
                        {"error": f"no such trace {trace_id!r} "
                                  "(unsampled or evicted)"}).encode(),
                        "application/json")
                    return
                body = json.dumps({"trace_id": trace_id, "spans": tree},
                                  indent=2,
                                  default=_json_default).encode()
                self._reply(request, 200, body, "application/json")
        elif path == "/workload":
            # Workload recorder (ISSUE 8): the bounded log of admitted
            # queries (normalized text, hoisted literals, outcome,
            # wall/compile/execute split) + per-fingerprint roll-up —
            # what `yt workload capture` pulls and `yt replay` re-runs.
            from ytsaurus_tpu.query.workload import get_workload_log
            limit = int(params.get("limit", 128))
            body = json.dumps(get_workload_log().snapshot(limit=limit),
                              indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/compile":
            # Compilation observatory (ISSUE 8): per-fingerprint compile
            # burn (count, cumulative seconds, shape-spectrum
            # cardinality, evictions, last-miss cause) + captured XLA
            # artifacts metadata — `yt compile-cache top`'s data source.
            from ytsaurus_tpu.query.engine.evaluator import (
                get_compile_observatory,
            )
            top = int(params.get("top", 50))
            body = json.dumps(get_compile_observatory().snapshot(top=top),
                              indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/mesh":
            # Mesh execution observatory (ISSUE 20): per-fingerprint
            # roll-up of the in-program SPMD telemetry blocks (shard
            # skew, exchange bytes, quota headroom, memory watermark)
            # plus the skew SLO spec — `yt mesh top`'s data source.
            from ytsaurus_tpu.parallel.mesh_observatory import (
                get_mesh_observatory,
            )
            top = int(params.get("top", 50))
            body = json.dumps(get_mesh_observatory().snapshot(top=top),
                              indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/tiers":
            # Adaptive tiering plane (ISSUE 18): kill switch + hot
            # threshold, the background promotion pipeline's queue/
            # compiled/dropped counters, and the per-fingerprint
            # interpreted-run roll-up feeding the promotion decision.
            from ytsaurus_tpu.query.engine import evaluator as _ev
            evaluator = self.tier_evaluator or _ev._global_evaluator
            top = int(params.get("top", 50))
            body = json.dumps(evaluator.tier_snapshot(top=top),
                              indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/metrics/history":
            # Telemetry plane (ISSUE 6): bounded time-series rings the
            # sampler thread fills from every registered sensor.
            # ?name=/serving/select_latency_seconds&tags=pool=prod
            # &since=<unix ts>&tier=fine|coarse
            tags = None
            if params.get("tags"):
                tags = dict(kv.split("=", 1)
                            for kv in params["tags"].split(",") if "=" in kv)
            since = float(params["since"]) if "since" in params else None
            body = json.dumps({
                "sample_period": self.history.sample_period,
                "samples_taken": self.history.samples_taken,
                "series": self.history.query(
                    name=params.get("name"), tags=tags, since=since,
                    tier=params.get("tier", "fine")),
            }, indent=2, default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/accounting":
            # Per-tenant resource accounting: the full (pool, user)
            # usage matrix plus per-pool / per-user roll-ups and the
            # plane totals (`yt top`'s data source).
            body = json.dumps(self.accountant.snapshot(), indent=2,
                              default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/slo":
            # SLO burn-rate state: a fresh evaluation pass (so operators
            # always read current burn rates, not the last sampler tick)
            # plus active/resolved alerts.
            body = json.dumps(self.slo_tracker.evaluate(), indent=2,
                              default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/telemetry":
            # Compact single-daemon telemetry summary — what the
            # primary's /cluster roll-up scrapes from every member.
            body = json.dumps(self._telemetry_summary(), indent=2,
                              default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path == "/cluster":
            body = json.dumps(self._cluster_rollup(), indent=2,
                              default=_json_default).encode()
            self._reply(request, 200, body, "application/json")
        elif path in ("/metrics", "/solomon"):
            body = self.registry.render_prometheus().encode()
            self._reply(request, 200, body, "text/plain; version=0.0.4")
        elif path == "/orchid" or path.startswith("/orchid/"):
            sub = path[len("/orchid"):] or "/"
            try:
                value = self.orchid.get(sub)
            except YtError as err:
                self._reply(request, 404,
                            json.dumps(err.to_dict()).encode(),
                            "application/json")
                return
            body = json.dumps(value, default=_json_default,
                              indent=2).encode()
            self._reply(request, 200, body, "application/json")
        else:
            self._reply(request, 404, b"not found", "text/plain")

    # -- fleet roll-up ---------------------------------------------------------

    def _telemetry_summary(self) -> dict:
        """One daemon's telemetry in scrapeable form: SLO state,
        accounting roll-ups, and history metadata (series list, not the
        full rings — /metrics/history serves points on demand)."""
        history = self.history
        return {
            "address": self.address,
            "slo": self.slo_tracker.snapshot(),
            "accounting": self.accountant.snapshot(),
            "history": {
                "sample_period": history.sample_period,
                "samples_taken": history.samples_taken,
                "series_names": history.series_names(),
            },
        }

    def _scrape_member(self, address):
        if address == self.address:
            return self._telemetry_summary()
        with urllib.request.urlopen(
                f"http://{address}/telemetry",
                timeout=self.CLUSTER_SCRAPE_TIMEOUT) as resp:
            return json.loads(resp.read())

    def _cluster_rollup(self) -> dict:
        """The fleet view (primary): scrape every discovery-registered
        daemon's /telemetry and aggregate — per-member summaries, every
        member's active alerts merged (tagged by member), and the
        accounting totals summed cluster-wide.  Scrapes fan out on a
        pool so the wall time of a fleet with dead members is ONE
        scrape timeout, not their sum."""
        from concurrent.futures import ThreadPoolExecutor
        members = list(self.cluster_members()) \
            if self.cluster_members is not None else []
        if not any(m.get("address") == self.address for m in members):
            members.insert(0, {"id": "self", "address": self.address})
        out_members: dict = {}
        alerts: list = []
        totals: dict = {}
        errors: dict = {}
        with ThreadPoolExecutor(
                max_workers=min(8, max(len(members), 1)),
                thread_name_prefix="cluster-scrape") as pool:
            futures = [(m, pool.submit(self._scrape_member,
                                       m.get("address")))
                       for m in members]
        for member, future in futures:
            member_id = member.get("id") or member.get("address")
            address = member.get("address")
            try:
                summary = future.result()
            except Exception as exc:  # noqa: BLE001 — one dead member
                # must not take down the fleet view.
                errors[member_id] = repr(exc)
                out_members[member_id] = {"address": address,
                                          "reachable": False}
                continue
            out_members[member_id] = {
                "address": address, "reachable": True,
                "attributes": dict(member.get("attributes") or {}),
                **summary,
            }
            for alert in (summary.get("slo") or {}).get(
                    "active_alerts") or []:
                alerts.append({"member": member_id, **alert})
            for field, value in ((summary.get("accounting") or {})
                                 .get("totals") or {}).items():
                totals[field] = totals.get(field, 0.0) + value
        return {"members": out_members, "active_alerts": alerts,
                "accounting_totals": totals, "errors": errors}

    @staticmethod
    def _reply(request, status: int, body: bytes, ctype: str) -> None:
        request.send_response(status)
        request.send_header("Content-Type", ctype)
        request.send_header("Content-Length", str(len(body)))
        request.end_headers()
        request.wfile.write(body)


def _json_default(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)
