"""Erasure coding: systematic Reed–Solomon over GF(2^8).

Ref: library/cpp/erasure (codecs RS(6,3), LRC(12,2,2) via ISA-L/Jerasure,
wrapped by yt/yt/library/erasure).  This is an independent numpy
implementation: a systematic generator derived from an extended Vandermonde
matrix; any k of the k+m parts reconstruct the original (m erasures
tolerated).  rs_6_3 matches the reference's default storage codec shape.
LRC is future work (PARITY.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError

# --- GF(2^8) arithmetic (poly 0x11D, generator 2) ----------------------------

_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def _gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_EXP[255 - _LOG[a]])


def _gf_matmul_vec(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(r, k) GF matrix × (k, n) byte planes → (r, n)."""
    r, k = matrix.shape
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for j in range(k):
            c = int(matrix[i, j])
            if c == 0:
                continue
            # Vectorized GF multiply-by-constant via log tables.
            row = data[j]
            nz = row != 0
            prod = np.zeros_like(row)
            prod[nz] = _EXP[(_LOG[row[nz]] + _LOG[c]) % 255]
            acc ^= prod
        out[i] = acc
    return out


def _gf_gauss_invert(matrix: np.ndarray) -> np.ndarray:
    """Invert a square GF(256) matrix by Gauss-Jordan elimination."""
    n = matrix.shape[0]
    aug = np.concatenate(
        [matrix.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise YtError("Singular matrix during erasure repair",
                          code=EErrorCode.ChunkFormatError)
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = _gf_inv(int(aug[col, col]))
        aug[col] = _gf_constant_mul(aug[col], inv)
        for row in range(n):
            if row != col and aug[row, col] != 0:
                factor = int(aug[row, col])
                aug[row] ^= _gf_constant_mul(aug[col], factor)
    return aug[:, n:]


def _gf_constant_mul(row: np.ndarray, c: int) -> np.ndarray:
    if c == 0:
        return np.zeros_like(row)
    nz = row != 0
    out = np.zeros_like(row)
    out[nz] = _EXP[(_LOG[row[nz]] + _LOG[c]) % 255]
    return out


def _gf_pow(a: int, e: int) -> int:
    if e == 0:
        return 1
    if a == 0:
        return 0
    return int(_EXP[(_LOG[a] * e) % 255])


def _systematic_generator(k: int, m: int) -> np.ndarray:
    """(k+m, k) systematic generator: top k rows identity, bottom m parity.

    Vandermonde over distinct evaluation points 0..k+m-1 (any k rows are
    independent), right-multiplied by the inverse of its top k×k block.
    """
    v = np.zeros((k + m, k), dtype=np.uint8)
    for i in range(k + m):
        for j in range(k):
            v[i, j] = _gf_pow(i, j)
    top_inv = _gf_gauss_invert(v[:k].copy())
    return _gf_matrix_mul(v, top_inv)


def _gf_matrix_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    r, k = a.shape
    k2, c = b.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            acc = 0
            for t in range(k):
                acc ^= _gf_mul(int(a[i, t]), int(b[t, j]))
            out[i, j] = acc
    return out


@dataclass(frozen=True)
class ErasureCodec:
    name: str
    data_parts: int          # k
    parity_parts: int        # m
    generator: np.ndarray    # (k+m, k) systematic

    @property
    def total_parts(self) -> int:
        return self.data_parts + self.parity_parts

    # -- encode ----------------------------------------------------------------

    def encode(self, blob: bytes) -> list[bytes]:
        """Split into k data parts (padded) + m parity parts.  Part 0 carries
        no length header; callers must remember the original byte length."""
        k = self.data_parts
        part_len = (len(blob) + k - 1) // k
        part_len = max(part_len, 1)
        data = np.frombuffer(
            blob.ljust(k * part_len, b"\0"), dtype=np.uint8).reshape(k, part_len)
        parity = _gf_matmul_vec(self.generator[k:], data)
        return [data[i].tobytes() for i in range(k)] + \
            [parity[i].tobytes() for i in range(self.parity_parts)]

    # -- decode / repair -------------------------------------------------------

    def decode(self, parts: Sequence[Optional[bytes]], size: int) -> bytes:
        """Reconstruct the original blob from any k available parts."""
        k = self.data_parts
        available = [i for i, p in enumerate(parts) if p is not None]
        if len(available) < k:
            raise YtError(
                f"Erasure decode needs {k} parts, only {len(available)} "
                f"available", code=EErrorCode.ChunkFormatError)
        use = available[:k]
        if use == list(range(k)):
            data = np.stack([np.frombuffer(parts[i], dtype=np.uint8)
                             for i in range(k)])
        else:
            sub = self.generator[use]                    # (k, k)
            inv = _gf_gauss_invert(sub)
            received = np.stack([np.frombuffer(parts[i], dtype=np.uint8)
                                 for i in use])
            data = _gf_matmul_vec(inv, received)
        return data.reshape(-1).tobytes()[:size]


_CODECS: dict[str, ErasureCodec] = {}


def get_erasure_codec(name: str) -> ErasureCodec:
    codec = _CODECS.get(name)
    if codec is None:
        if name == "rs_6_3":
            codec = ErasureCodec("rs_6_3", 6, 3, _systematic_generator(6, 3))
        elif name == "rs_3_2":
            codec = ErasureCodec("rs_3_2", 3, 2, _systematic_generator(3, 2))
        else:
            raise YtError(f"Unknown erasure codec {name!r}",
                          code=EErrorCode.ChunkFormatError)
        _CODECS[name] = codec
    return codec
