"""RPC server: services with method registries hosted on one TCP endpoint.

Ref shape: core/rpc/service_detail.h (method registry, per-method
concurrency limits, error-to-wire mapping) — redesigned on asyncio.
Handlers are plain sync callables (they do numpy/jax work) executed on a
thread pool; the event loop only frames/unframes packets, so one slow
handler never stalls the bus.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextvars
import socket
import threading
import traceback

from ytsaurus_tpu import yson
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc.packet import PacketError, read_packet, write_packet
from ytsaurus_tpu.rpc.wire import decode_body, encode_body
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils.tracing import TraceContext

logger = get_logger("rpc")
_profiler = Profiler("/rpc/server")

# Server-side receive fault: `error`/`crash-once` drop the connection
# (the client sees exactly what a dying peer produces — a reset with no
# reply), `delay` stalls the reply (straggler server).
_FP_RECV = failpoints.register_site("rpc.server.recv")


def rpc_method(name: str | None = None, concurrency: int = 16):
    """Marks a Service method as remotely callable."""
    def wrap(fn):
        fn._rpc_name = name or fn.__name__
        fn._rpc_concurrency = concurrency
        return fn
    return wrap


class Service:
    """Base: subclasses define @rpc_method handlers.

    Handler signature: handler(body: dict, attachments: list[bytes])
    → body | (body, attachments).  Raise YtError for application errors."""

    name: str = "service"

    def rpc_methods(self) -> dict[str, tuple]:
        out = {}
        for attr in dir(self):
            fn = getattr(self, attr)
            if callable(fn) and hasattr(fn, "_rpc_name"):
                out[fn._rpc_name] = (fn, fn._rpc_concurrency)
        return out


def _error_to_wire(err: YtError) -> dict:
    return {
        "code": int(err.code),
        "message": err.message,
        "attributes": err.attributes or {},
        "inner_errors": [_error_to_wire(e) for e in err.inner_errors],
    }


def error_from_wire(wire: dict) -> YtError:
    return YtError(
        _text(wire.get("message", b"")),
        code=int(wire.get("code", EErrorCode.Generic)),
        attributes=wire.get("attributes") or {},
        inner_errors=[error_from_wire(w)
                      for w in wire.get("inner_errors", [])],
    )


def _text(v) -> str:
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)


class RpcServer:
    """Hosts services on a TCP port inside a dedicated event-loop thread."""

    def __init__(self, services, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16):
        self.host = host
        self.port = port
        self._services = {}
        for svc in services:
            self.add_service(svc)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="rpc-worker")
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server: asyncio.AbstractServer | None = None
        self._connections: set = set()
        self._started = threading.Event()

    def add_service(self, svc) -> None:
        """Register a service (also usable after start: daemons bring up
        bootstrap services first, then the driver once state is recovered)."""
        self._services[svc.name] = {
            mname: (fn, asyncio.Semaphore(conc))
            for mname, (fn, conc) in svc.rpc_methods().items()}

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        """Starts the loop thread; returns once the port is bound."""
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rpc-server")
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise YtError("RPC server failed to start")

    def serve_forever(self) -> None:
        """Runs the server on the CURRENT thread (daemon main loop)."""
        self._run()

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._bind())
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.run_until_complete(self._loop.shutdown_asyncgens())
            self._loop.close()

    async def _bind(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port,
            family=socket.AF_INET)
        self.port = self._server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self._loop is None:
            return
        def _shutdown():
            if self._server is not None:
                self._server.close()
            # Close live connections, or clients on a half-dead peer hang
            # until their call timeout instead of reconnecting.
            for writer in list(self._connections):
                writer.close()
            self._connections.clear()
            self._loop.stop()
        self._loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=False, cancel_futures=True)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection handling ---------------------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        write_lock = asyncio.Lock()
        self._connections.add(writer)
        try:
            while True:
                try:
                    parts = await read_packet(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except PacketError as exc:
                    logger.warning("dropping connection from %s: %s",
                                   peer, exc)
                    return
                asyncio.ensure_future(
                    self._dispatch(parts, writer, write_lock))
        finally:
            self._connections.discard(writer)
            writer.close()

    async def _dispatch(self, parts, writer, write_lock) -> None:
        act = _FP_RECV.fire()
        if act is not None:
            mode, ms = act
            if mode == "delay":
                await asyncio.sleep(ms / 1000.0)
            else:
                # Simulated peer death mid-request: no reply, reset.
                writer.close()
                return
        try:
            envelope = yson.loads(parts[0], encoding=None)
            rid = int(envelope["rid"])
            service = _text(envelope.get("service", b""))
            method = _text(envelope.get("method", b""))
        except Exception as exc:   # noqa: BLE001 — protocol garbage
            logger.warning("malformed envelope from peer: %r; dropping "
                           "connection", exc)
            writer.close()
            return
        try:
            svc = self._services.get(service)
            if svc is None:
                raise YtError(f"No such service {service!r}",
                              code=EErrorCode.NoSuchService)
            entry = svc.get(method)
            if entry is None:
                raise YtError(
                    f"No such method {service}.{method}",
                    code=EErrorCode.NoSuchMethod)
            fn, sem = entry
            body = decode_body(yson.loads(parts[1], encoding=None)) \
                if len(parts) > 1 else {}
            attachments = list(parts[2:])
            trace_wire = envelope.get("trace")

            def invoke():
                # Server span continues the caller's trace (ref: rpc
                # handlers run under the propagated TTraceContext).  An
                # UNtraced request gets the null span — handlers must
                # not mint root traces per RPC (the entry points that
                # own sampling are the gateway/scheduler/proxy).
                from ytsaurus_tpu.utils.tracing import NULL_SPAN
                span = TraceContext.from_wire(
                    trace_wire, f"{service}.{method}") \
                    if trace_wire else NULL_SPAN
                with span:
                    span.add_tag("service", service)
                    prof = _profiler.with_tags(service=service,
                                               method=method)
                    prof.counter("request_count").increment()
                    with prof.timer("request_time"):
                        return fn(body, attachments)

            # EXPLICIT contextvars capture (ISSUE 5 satellite): the
            # handler runs on a pooled executor thread whose context is
            # whatever the PREVIOUS request left behind —
            # run_in_executor does not propagate or isolate contextvars.
            # Running inside a fresh copy of the (clean) loop context
            # both restores the caller's restored-from-wire trace and
            # guarantees a handler that leaked an ambient context cannot
            # poison the next request on the same thread.
            handler_ctx = contextvars.copy_context()
            async with sem:
                result = await asyncio.get_event_loop().run_in_executor(
                    self._pool, lambda: handler_ctx.run(invoke))
            if isinstance(result, tuple):
                out_body, out_attachments = result
            else:
                out_body, out_attachments = result, []
            reply_env = yson.dumps({"rid": rid, "kind": "rsp"}, binary=True)
            reply_body = yson.dumps(
                encode_body(out_body if out_body is not None else {}),
                binary=True)
            out = [reply_env, reply_body, *out_attachments]
        except YtError as err:
            out = [yson.dumps({"rid": rid, "kind": "err"}, binary=True),
                   yson.dumps(_error_to_wire(err), binary=True)]
        except Exception as exc:      # noqa: BLE001 — wire boundary
            logger.error("unhandled error in %s.%s: %s\n%s", service, method,
                         exc, traceback.format_exc())
            err = YtError(f"Unhandled server error: {exc!r}",
                          code=EErrorCode.Generic)
            out = [yson.dumps({"rid": rid, "kind": "err"}, binary=True),
                   yson.dumps(_error_to_wire(err), binary=True)]
        try:
            async with write_lock:
                await write_packet(writer, out)
        except (ConnectionError, RuntimeError):
            pass
