"""Native codec fast paths: g++-compiled C++ via ctypes, Python fallback.

See fastpath.cpp for the ops.  `lib()` returns the loaded library or None;
the module-level functions transparently use native code when available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import zlib

import numpy as np

_SOURCE = os.path.join(os.path.dirname(__file__), "fastpath.cpp")
_LIB = None
_TRIED = False


def _build_dir() -> str:
    cache = os.environ.get("YTSAURUS_TPU_NATIVE_DIR")
    if cache:
        return cache
    return os.path.join(os.path.dirname(__file__), "_build")


def lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    try:
        with open(_SOURCE, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        build_dir = _build_dir()
        os.makedirs(build_dir, exist_ok=True)
        so_path = os.path.join(build_dir, f"fastpath-{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 _SOURCE, "-o", tmp],
                check=True, capture_output=True)
            os.replace(tmp, so_path)
        handle = ctypes.CDLL(so_path)
        handle.yt_varint_encode_zigzag.restype = ctypes.c_int64
        handle.yt_varint_decode_zigzag.restype = ctypes.c_int64
        handle.yt_bitmap_unpack.restype = ctypes.c_int64
        handle.yt_crc64.restype = ctypes.c_uint64
        handle.yt_crc64.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.c_uint64]
        _LIB = handle
    except Exception:
        _LIB = None
    return _LIB


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


# --- varint ------------------------------------------------------------------


def varint_encode(values: np.ndarray) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.int64)
    handle = lib()
    if handle is not None:
        out = np.empty(len(values) * 10 + 1, dtype=np.uint8)
        n = handle.yt_varint_encode_zigzag(
            _ptr(values), ctypes.c_int64(len(values)), _ptr(out))
        return out[:n].tobytes()
    buf = bytearray()
    for v in values.tolist():
        z = ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1)
        while z >= 0x80:
            buf.append((z & 0x7F) | 0x80)
            z >>= 7
        buf.append(z)
    return bytes(buf)


def varint_decode(data: bytes, count: int) -> np.ndarray:
    handle = lib()
    if handle is not None:
        out = np.empty(count, dtype=np.int64)
        src = np.frombuffer(data, dtype=np.uint8)
        consumed = handle.yt_varint_decode_zigzag(
            _ptr(src), ctypes.c_int64(len(src)), ctypes.c_int64(count),
            _ptr(out))
        if consumed < 0:
            raise ValueError("truncated varint stream")
        return out
    out = np.empty(count, dtype=np.int64)
    pos = 0
    for i in range(count):
        value = 0
        shift = 0
        while True:
            byte = data[pos]
            pos += 1
            value |= (byte & 0x7F) << shift
            shift += 7
            if not byte & 0x80:
                break
        out[i] = (value >> 1) ^ -(value & 1)
    return out


# --- bitmaps -----------------------------------------------------------------


def bitmap_pack(bools: np.ndarray) -> bytes:
    bools = np.ascontiguousarray(bools, dtype=np.uint8)
    handle = lib()
    if handle is not None:
        out = np.zeros((len(bools) + 7) // 8, dtype=np.uint8)
        handle.yt_bitmap_pack(_ptr(bools), ctypes.c_int64(len(bools)),
                              _ptr(out))
        return out.tobytes()
    return np.packbits(bools, bitorder="little").tobytes()


def bitmap_unpack(data: bytes, count: int) -> np.ndarray:
    if len(data) * 8 < count:
        raise ValueError(
            f"bitmap too small: {len(data)} bytes for {count} bits")
    handle = lib()
    if handle is not None:
        src = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(count, dtype=np.uint8)
        rc = handle.yt_bitmap_unpack(_ptr(src), ctypes.c_int64(len(src)),
                                     ctypes.c_int64(count), _ptr(out))
        if rc != 0:
            raise ValueError("bitmap too small")
        return out.astype(bool)
    return np.unpackbits(np.frombuffer(data, dtype=np.uint8),
                         count=count, bitorder="little").astype(bool)


# --- delta -------------------------------------------------------------------


def delta_encode(values: np.ndarray) -> np.ndarray:
    values = np.ascontiguousarray(values, dtype=np.int64)
    handle = lib()
    if handle is not None:
        out = np.empty_like(values)
        handle.yt_delta_encode(_ptr(values), ctypes.c_int64(len(values)),
                               _ptr(out))
        return out
    out = np.empty_like(values)
    if len(values):
        out[0] = values[0]
        np.subtract(values[1:], values[:-1], out=out[1:])
    return out


def delta_decode(deltas: np.ndarray) -> np.ndarray:
    deltas = np.ascontiguousarray(deltas, dtype=np.int64)
    handle = lib()
    if handle is not None:
        out = np.empty_like(deltas)
        handle.yt_delta_decode(_ptr(deltas), ctypes.c_int64(len(deltas)),
                               _ptr(out))
        return out
    return np.cumsum(deltas, dtype=np.int64)


# --- checksums / remap -------------------------------------------------------


def checksum(data: bytes, seed: int = 0) -> int:
    handle = lib()
    if handle is not None:
        src = np.frombuffer(data, dtype=np.uint8) if data else \
            np.empty(0, dtype=np.uint8)
        return int(handle.yt_crc64(_ptr(src), ctypes.c_int64(len(src)),
                                   ctypes.c_uint64(seed)))
    # Fallback: crc32 widened (weaker; tagged with a high bit so native and
    # fallback checksums never silently compare equal).
    return zlib.crc32(data, seed & 0xFFFFFFFF) | (1 << 62)


def remap_i32(codes: np.ndarray, table: np.ndarray) -> np.ndarray:
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    table = np.ascontiguousarray(table, dtype=np.int32)
    handle = lib()
    if handle is not None:
        out = np.empty_like(codes)
        handle.yt_remap_i32(_ptr(codes), ctypes.c_int64(len(codes)),
                            _ptr(table), ctypes.c_int64(len(table)), _ptr(out))
        return out
    safe = np.clip(codes, 0, max(len(table) - 1, 0))
    out = table[safe] if len(table) else np.zeros_like(codes)
    out[(codes < 0) | (codes >= len(table))] = 0
    return out
