"""The no-compile interpreter tier: PreparedQuery plans over numpy planes.

The adaptive-tiering gap (arxiv 2311.04692; Flare, arxiv 1703.08219): the
FIRST execution of a genuinely new plan shape pays its 200-400 ms XLA
compile inline.  This module is the tier below the compiled path — a
vectorized numpy interpreter that executes the SAME staged pipeline
lowering.py traces (filter → group → order → project → compact/offset/
limit) over the SAME ColumnarChunk planes, with zero compilation.  The
evaluator serves a cold shape from here immediately while the background
compiler (evaluator.BackgroundCompiler) builds the XLA program off-thread.

Bit-identity contract: every stage mirrors lowering.py / expr.py /
ops/segments.py formula-for-formula — including garbage values under
invalid lanes, the flags-word-major group ordering of the sort-group
path, the dense-slot ordering of the fast-group path (identical
`_column_min_max` probe, so the fast/sort decision can never diverge),
and the clamped offset/limit finale.  The only sanctioned divergence is
float SUM accumulation order (XLA tree-reduce vs numpy sequential);
everything else is decode-identical, test-enforced by
tests/test_tiering.py's dual-check corpus.

Coverage is DECLARED, never guessed: `covers()` walks the plan against an
explicit allow-list (scan/filter/project/group/order/limit, the full
aggregate set, and the expression subset below).  Joins, windows, NEAREST
(vector types), and the host-table string builtins fall through to the
compiled path.  ORDER BY ... LIMIT takes a full stable lexsort instead of
the device's top-k candidate pruning — provably identical over the
visible [offset, offset+limit) window (lax.top_k breaks ties by lowest
index, the candidate set is a superset of the window, and the compacted
candidate count clamps to the same value).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.expr import (
    _EMPTY_VOCAB,
    _merge_vocabs,
    _pad_np,
    _range_code,
    _remap_table,
    _string_matcher,
    _vocab_bucket,
    _vocab_code,
)
from ytsaurus_tpu.schema import EValueType, device_dtype
from ytsaurus_tpu.utils import sanitizers


class InterpUnsupported(Exception):
    """Plan/expression outside the declared interpreter coverage: the
    caller falls through to the compiled path (never an error)."""


# --- declared coverage --------------------------------------------------------

COVERED_FUNCTIONS = frozenset({
    "if", "is_null", "if_null",
    "int64", "uint64", "double", "boolean",
    "abs", "floor", "ceil", "sqrt",
    "min_of", "max_of",
    "length", "lower", "upper", "concat",
    "is_finite", "is_nan",
    "timestamp_floor_hour", "timestamp_floor_day", "timestamp_floor_week",
    "timestamp_floor_month", "timestamp_floor_year",
})

COVERED_AGGREGATES = frozenset({
    "sum", "min", "max", "avg", "count", "first",
    "argmin", "argmax", "cardinality",
})


def _check_expr(node: ir.TExpr) -> None:
    """Raise InterpUnsupported for any node outside the allow-list."""
    if isinstance(node, ir.TLiteral):
        if not isinstance(node.type, EValueType):
            raise InterpUnsupported("vector literal")   # NEAREST vectors
        return
    if isinstance(node, ir.TReference):
        if not isinstance(node.type, EValueType):
            raise InterpUnsupported("vector column")
        return
    if isinstance(node, ir.TUnary):
        _check_expr(node.operand)
        return
    if isinstance(node, ir.TBinary):
        _check_expr(node.lhs)
        _check_expr(node.rhs)
        return
    if isinstance(node, ir.TFunction):
        if node.name not in COVERED_FUNCTIONS:
            raise InterpUnsupported(f"function {node.name}")
        for arg in node.args:
            _check_expr(arg)
        return
    if isinstance(node, (ir.TIn, ir.TBetween)):
        for operand in node.operands:
            _check_expr(operand)
        return
    if isinstance(node, ir.TStringPredicate):
        _check_expr(node.operand)
        return
    raise InterpUnsupported(type(node).__name__)


def covers(plan) -> bool:
    """The declared-coverage predicate: True iff every clause and
    expression of `plan` is inside the interpreter's allow-list."""
    if not isinstance(plan, (ir.Query, ir.FrontQuery)):
        return False
    if getattr(plan, "joins", ()):
        return False
    if plan.window is not None:
        return False
    try:
        for col in plan.schema:
            if not isinstance(col.type, EValueType) or \
                    col.type is EValueType.any:
                raise InterpUnsupported(f"column type {col.type!r}")
        where = getattr(plan, "where", None)
        if where is not None:
            _check_expr(where)
        if plan.group is not None:
            if len(plan.group.group_items) > 31:
                raise InterpUnsupported("too many group keys")
            for item in plan.group.group_items:
                _check_expr(item.expr)
            for agg in plan.group.aggregate_items:
                if agg.function not in COVERED_AGGREGATES:
                    raise InterpUnsupported(f"aggregate {agg.function}")
                if agg.argument is None:
                    raise InterpUnsupported("argument-less aggregate")
                _check_expr(agg.argument)
                if agg.by_argument is not None:
                    _check_expr(agg.by_argument)
        if plan.having is not None:
            _check_expr(plan.having)
        if plan.order is not None:
            for item in plan.order.items:
                _check_expr(item.expr)
        if plan.project is not None:
            for item in plan.project.items:
                _check_expr(item.expr)
    except InterpUnsupported:
        return False
    return True


# --- numpy mirrors of device primitives ---------------------------------------

_SIGN64 = np.uint64(1 << 63)


def _np_monotone_u64(data: np.ndarray) -> np.ndarray:
    """Order-preserving uint64 encoding — the (hi << 32 | lo) collapse of
    segments.monotone_u32_words, identical order and tie classes."""
    if data.dtype == np.bool_:
        return data.astype(np.uint64)
    if np.issubdtype(data.dtype, np.floating):
        bits = np.ascontiguousarray(
            data.astype(np.float64)).view(np.uint64)
        sign = (bits >> np.uint64(63)).astype(bool)
        return np.where(sign, ~bits, bits | _SIGN64)
    if np.issubdtype(data.dtype, np.unsignedinteger):
        return data.astype(np.uint64)
    return data.astype(np.int64).astype(np.uint64) ^ _SIGN64


def _np_equality_u64(data: np.ndarray) -> np.ndarray:
    """Equality-class uint64 encoding (bit view; order irrelevant)."""
    if data.dtype == np.bool_:
        return data.astype(np.uint64)
    if np.issubdtype(data.dtype, np.floating):
        return np.ascontiguousarray(
            data.astype(np.float64)).view(np.uint64)
    return data.astype(np.int64).astype(np.uint64) \
        if np.issubdtype(data.dtype, np.signedinteger) \
        else data.astype(np.uint64)


def _np_compare(op: str, lhs: np.ndarray, rhs: np.ndarray) -> np.ndarray:
    if op == "=":
        return lhs == rhs
    if op == "!=":
        return lhs != rhs
    if op == "<":
        return lhs < rhs
    if op == "<=":
        return lhs <= rhs
    if op == ">":
        return lhs > rhs
    if op == ">=":
        return lhs >= rhs
    raise AssertionError(op)


def _np_promote_pair(a: np.ndarray,
                     b: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    if a.dtype == b.dtype:
        return a, b
    target = np.promote_types(a.dtype, b.dtype)
    return a.astype(target), b.astype(target)


def _np_trunc_div(ld: np.ndarray, rd: np.ndarray) -> np.ndarray:
    """C++ truncating integer division (jax.lax.div semantics)."""
    if np.issubdtype(ld.dtype, np.unsignedinteger):
        return ld // rd
    q = np.floor_divide(ld, rd)
    r = ld - q * rd
    return q + ((r != 0) & ((ld < 0) != (rd < 0)))


def _np_days_to_civil(days: np.ndarray):
    z = days + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = np.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = np.floor_divide(5 * doy + 2, 153)
    d = doy - np.floor_divide(153 * mp + 2, 5) + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y, m, d


def _np_civil_to_days(y, m, d) -> np.ndarray:
    y = y - (m <= 2)
    era = np.floor_divide(y, 400)
    yoe = y - era * 400
    mp = np.mod(m + 9, 12)
    doy = np.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _np_timestamp_floor(ts: np.ndarray, unit: str) -> np.ndarray:
    if unit == "hour":
        return ts - np.mod(ts, 3600)
    if unit == "day":
        return ts - np.mod(ts, 86400)
    days = np.floor_divide(ts, 86400)
    if unit == "week":
        dow = np.mod(days + 3, 7)
        return (days - dow) * 86400
    y, m, _ = _np_days_to_civil(days)
    if unit == "month":
        return _np_civil_to_days(y, m, np.ones_like(m)) * 86400
    if unit == "year":
        one = np.ones_like(y)
        return _np_civil_to_days(y, one, one) * 86400
    raise InterpUnsupported(f"timestamp unit {unit}")


def _reduce_neutral(dtype, function: str):
    if np.issubdtype(dtype, np.floating):
        return np.array(np.inf if function == "min" else -np.inf,
                        dtype=dtype)
    info = np.iinfo(dtype)
    return np.array(info.max if function == "min" else info.min,
                    dtype=dtype)


def _seg_reduce(function: str, data: np.ndarray, seg: np.ndarray,
                nseg: int) -> np.ndarray:
    """Per-segment sum/min/max; rows with seg outside [0, nseg) are
    dropped (the device's `seg == s` compare never matches them)."""
    keep = (seg >= 0) & (seg < nseg)
    if not keep.all():
        data = data[keep]
        seg = seg[keep]
    if function == "sum":
        out = np.zeros(nseg, dtype=data.dtype)
        np.add.at(out, seg, data)
        return out
    neutral = _reduce_neutral(data.dtype, function)
    out = np.full(nseg, neutral, dtype=data.dtype)
    (np.minimum if function == "min" else np.maximum).at(out, seg, data)
    return out


def _seg_first_index(eligible: np.ndarray, seg: np.ndarray,
                     nseg: int) -> np.ndarray:
    cap = eligible.shape[0]
    idx = np.where(eligible, np.arange(cap, dtype=np.int64),
                   np.int64(cap - 1))
    first = _seg_reduce("min", idx, seg, nseg)
    return np.clip(first, 0, cap - 1)


def _np_segment_aggregate(function: str, data: np.ndarray,
                          valid: np.ndarray, seg: np.ndarray, nseg: int,
                          value_type) -> tuple[np.ndarray, np.ndarray]:
    contributes = valid
    count = _seg_reduce("sum", contributes.astype(np.int64), seg, nseg)
    any_valid = count > 0
    if function == "count":
        return count, np.ones_like(any_valid)
    if function == "sum":
        masked = np.where(contributes, data, np.zeros_like(data))
        return _seg_reduce("sum", masked, seg, nseg), any_valid
    if function in ("min", "max"):
        if data.dtype == np.bool_:
            data = data.astype(np.int8)
        neutral = _reduce_neutral(data.dtype, function)
        masked = np.where(contributes, data, neutral)
        out = _seg_reduce(function, masked, seg, nseg)
        if value_type is EValueType.boolean:
            out = out.astype(np.bool_)
        return out, any_valid
    if function == "first":
        first_idx = _seg_first_index(contributes, seg, nseg)
        return data[first_idx], any_valid
    raise InterpUnsupported(f"segment aggregate {function}")


def _np_segment_arg_by(value_data, value_valid, by_data, by_valid,
                       seg, nseg, take_max: bool):
    if by_data.dtype == np.bool_:
        by_data = by_data.astype(np.int8)
    competes = by_valid
    if np.issubdtype(by_data.dtype, np.floating):
        competes = competes & ~np.isnan(by_data)
    fn = "max" if take_max else "min"
    neutral = _reduce_neutral(by_data.dtype, fn)
    masked_by = np.where(competes, by_data, neutral)
    extreme = _seg_reduce(fn, masked_by, seg, nseg)
    safe_seg = np.clip(seg, 0, nseg - 1)
    winner = competes & (masked_by == extreme[safe_seg]) & (seg < nseg)
    first_idx = _seg_first_index(winner, seg, nseg)
    any_competes = _seg_reduce(
        "sum", competes.astype(np.int64), seg, nseg) > 0
    return value_data[first_idx], value_valid[first_idx] & any_competes


def _np_segment_distinct_count(data, valid, seg, nseg):
    value = np.where(valid, data, np.zeros_like(data))
    nan_flag = np.zeros(value.shape[0], dtype=np.int8)
    if np.issubdtype(value.dtype, np.floating):
        is_nan = np.isnan(value)
        nan_flag = is_nan.astype(np.int8)
        value = np.where(is_nan, np.full_like(value, np.inf),
                         value + 0.0)
    flags = (valid.astype(np.uint32) << np.uint32(1)) | \
        nan_flag.astype(np.uint32)
    enc = _np_equality_u64(value)
    order = np.lexsort((enc, flags, seg))
    seg_s = seg[order]
    enc_s = enc[order]
    valid_s = valid[order]
    flags_s = flags[order]
    new = (seg_s != np.roll(seg_s, 1)) | (enc_s != np.roll(enc_s, 1)) | \
        (flags_s != np.roll(flags_s, 1))
    if len(new):
        new[0] = True
    counts = _seg_reduce("sum", (new & valid_s).astype(np.int64),
                         seg_s, nseg)
    return counts.astype(np.uint64), np.ones(nseg, dtype=bool)


# --- expression interpretation ------------------------------------------------


@dataclass
class _Ctx:
    """Stage state: numpy (data, valid) planes per column name."""
    columns: dict[str, tuple[np.ndarray, np.ndarray]]
    capacity: int


@dataclass
class _NBound:
    """One bound expression: type + result vocab + numpy evaluator."""
    type: EValueType
    vocab: Optional[np.ndarray]
    emit: Callable[[_Ctx], tuple[np.ndarray, np.ndarray]]


_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")


def _gather_table(table: np.ndarray):
    """Mirror of expr._gather_binding: pad-bucketed table + clip gather,
    so garbage codes under invalid lanes map to the SAME garbage."""
    def gather(codes: np.ndarray) -> np.ndarray:
        return table[np.clip(codes, 0, table.shape[0] - 1)]
    return gather


class NumpyBinder:
    """ExprBinder's numpy twin: binds one plan's expressions against one
    chunk's vocabularies, producing closures that evaluate eagerly.  The
    bind-phase host computations (vocab merges, remap/predicate tables,
    literal codes) are shared with expr.py helper-for-helper, so codes
    and vocabularies can never diverge from the compiled path."""

    def __init__(self, columns: dict):
        # name -> (EValueType, vocab) — same view ColumnBinding carries.
        self.columns = columns

    def bind(self, node: ir.TExpr) -> _NBound:
        method = getattr(self, f"_bind_{type(node).__name__}", None)
        if method is None:
            raise InterpUnsupported(type(node).__name__)
        return method(node)

    # -- leaves ---------------------------------------------------------------

    def _bind_TLiteral(self, node: ir.TLiteral) -> _NBound:
        ty = node.type
        if not isinstance(ty, EValueType):
            raise InterpUnsupported("vector literal")
        if ty is EValueType.null:
            def emit_null(ctx: _Ctx):
                return (np.zeros(ctx.capacity, dtype=np.int8),
                        np.zeros(ctx.capacity, dtype=bool))
            return _NBound(type=ty, vocab=None, emit=emit_null)
        if ty is EValueType.string:
            vocab = np.array([node.value], dtype=object)

            def emit_str(ctx: _Ctx):
                return (np.zeros(ctx.capacity, dtype=np.int32),
                        np.ones(ctx.capacity, dtype=bool))
            return _NBound(type=ty, vocab=vocab, emit=emit_str)
        value = node.value
        dt = device_dtype(ty)
        if ty is EValueType.boolean:
            def emit_bool(ctx: _Ctx):
                return (np.full(ctx.capacity, bool(value), dtype=dt),
                        np.ones(ctx.capacity, dtype=bool))
            return _NBound(type=ty, vocab=None, emit=emit_bool)
        # analyze: allow(host-sync): `value` is a host python literal, not a device plane
        const = np.asarray(value, dtype=dt)

        def emit(ctx: _Ctx):
            return (np.broadcast_to(const, (ctx.capacity,)),
                    np.ones(ctx.capacity, dtype=bool))
        return _NBound(type=ty, vocab=None, emit=emit)

    def _bind_TReference(self, node: ir.TReference) -> _NBound:
        binding = self.columns.get(node.name)
        if binding is None:
            raise InterpUnsupported(f"unbound column {node.name}")
        if not isinstance(node.type, EValueType):
            raise InterpUnsupported("vector column")
        name = node.name

        def emit(ctx: _Ctx):
            return ctx.columns[name]
        return _NBound(type=node.type, vocab=binding[1], emit=emit)

    # -- operators ------------------------------------------------------------

    def _bind_TUnary(self, node: ir.TUnary) -> _NBound:
        operand = self.bind(node.operand)
        op = node.op

        def emit(ctx: _Ctx):
            data, valid = operand.emit(ctx)
            if op == "not":
                return ~data.astype(bool), valid
            if op == "-":
                return -data, valid
            if op == "~":
                return ~data, valid
            raise InterpUnsupported(op)
        return _NBound(type=node.type, vocab=None, emit=emit)

    def _bind_TBinary(self, node: ir.TBinary) -> _NBound:
        op = node.op
        lhs_b = self.bind(node.lhs)
        rhs_b = self.bind(node.rhs)

        if op in ("and", "or"):
            def emit_logical(ctx: _Ctx):
                ld, lv = lhs_b.emit(ctx)
                rd, rv = rhs_b.emit(ctx)
                ld, rd = ld.astype(bool), rd.astype(bool)
                if op == "and":
                    known_false = (lv & ~ld) | (rv & ~rd)
                    valid = (lv & rv) | known_false
                    data = np.where(lv, ld, True) & np.where(rv, rd, True)
                else:
                    known_true = (lv & ld) | (rv & rd)
                    valid = (lv & rv) | known_true
                    data = np.where(lv, ld, False) | np.where(rv, rd,
                                                              False)
                return data & valid if op == "and" else data, valid
            return _NBound(type=EValueType.boolean, vocab=None,
                           emit=emit_logical)

        if EValueType.string in (lhs_b.type, rhs_b.type) and \
                lhs_b.type is not EValueType.null and \
                rhs_b.type is not EValueType.null:
            encoded = self._bind_string_literal_cmp(node, op, lhs_b, rhs_b)
            if encoded is not None:
                return encoded
            merged = _merge_vocabs(lhs_b.vocab, rhs_b.vocab)
            l_vocab = lhs_b.vocab if lhs_b.vocab is not None \
                else _EMPTY_VOCAB
            r_vocab = rhs_b.vocab if rhs_b.vocab is not None \
                else _EMPTY_VOCAB
            l_gather = _gather_table(_pad_np(
                _remap_table(l_vocab, merged),
                _vocab_bucket(max(len(l_vocab), 1)), 0))
            r_gather = _gather_table(_pad_np(
                _remap_table(r_vocab, merged),
                _vocab_bucket(max(len(r_vocab), 1)), 0))

            def emit_strcmp(ctx: _Ctx):
                ld, lv = lhs_b.emit(ctx)
                rd, rv = rhs_b.emit(ctx)
                data = _np_compare(op, l_gather(ld), r_gather(rd))
                return data, lv & rv
            return _NBound(type=EValueType.boolean, vocab=None,
                           emit=emit_strcmp)

        target = node.type if op not in _CMP_OPS else None

        def emit(ctx: _Ctx):
            ld, lv = lhs_b.emit(ctx)
            rd, rv = rhs_b.emit(ctx)
            valid = lv & rv
            if op in _CMP_OPS:
                ld, rd = _np_promote_pair(ld, rd)
                return _np_compare(op, ld, rd), valid
            dt = device_dtype(target)
            ld = ld.astype(dt)
            rd = rd.astype(dt)
            if op == "+":
                data = ld + rd
            elif op == "-":
                data = ld - rd
            elif op == "*":
                data = ld * rd
            elif op == "/":
                if np.issubdtype(dt, np.integer):
                    safe = np.where(rd == 0, np.ones_like(rd), rd)
                    data = _np_trunc_div(ld, safe)
                    valid = valid & (rd != 0)
                else:
                    data = ld / rd
            elif op == "%":
                if np.issubdtype(dt, np.integer):
                    safe = np.where(rd == 0, np.ones_like(rd), rd)
                    data = np.fmod(ld, safe)
                    valid = valid & (rd != 0)
                else:
                    data = np.fmod(ld, rd)
            elif op == "|":
                data = ld | rd
            elif op == "&":
                data = ld & rd
            elif op == "^":
                data = ld ^ rd
            elif op == "<<":
                data = np.left_shift(ld, rd)
            elif op == ">>":
                data = np.right_shift(ld, rd)
            else:
                raise InterpUnsupported(op)
            return data, valid
        return _NBound(type=node.type, vocab=None, emit=emit)

    def _bind_string_literal_cmp(self, node: ir.TBinary, op: str,
                                 lhs_b: _NBound,
                                 rhs_b: _NBound) -> Optional[_NBound]:
        """Numpy twin of ExprBinder._bind_string_literal_cmp — the SAME
        decision (config gate, literal side, vocab presence) and the SAME
        code formulas (_vocab_code for =/!=, doubled-space _range_code
        for range ops), or tier bit-identity breaks."""
        from ytsaurus_tpu.config import compile_config
        if op not in _CMP_OPS or not compile_config().encoded_predicates:
            return None
        if not (lhs_b.type is EValueType.string
                and rhs_b.type is EValueType.string):
            return None
        if isinstance(node.rhs, ir.TLiteral) and lhs_b.vocab is not None:
            col_b, lit, lit_on_right = lhs_b, node.rhs.value, True
        elif isinstance(node.lhs, ir.TLiteral) and rhs_b.vocab is not None:
            col_b, lit, lit_on_right = rhs_b, node.lhs.value, False
        else:
            return None
        if lit is None:
            return None
        vocab = col_b.vocab
        if op in ("=", "!="):
            code = np.int32(_vocab_code(vocab, lit))

            def emit_eq(ctx: _Ctx):
                data, valid = col_b.emit(ctx)
                out = (data == code) if op == "=" else (data != code)
                return out, valid
            return _NBound(type=EValueType.boolean, vocab=None,
                           emit=emit_eq)
        code = np.int32(_range_code(vocab, lit))

        def emit_rng(ctx: _Ctx):
            data, valid = col_b.emit(ctx)
            doubled = data.astype(np.int32) * 2 + 1
            out = _np_compare(op, doubled, code) if lit_on_right \
                else _np_compare(op, code, doubled)
            return out, valid
        return _NBound(type=EValueType.boolean, vocab=None, emit=emit_rng)

    # -- functions ------------------------------------------------------------

    def _bind_TFunction(self, node: ir.TFunction) -> _NBound:
        name = node.name
        if name not in COVERED_FUNCTIONS:
            raise InterpUnsupported(f"function {name}")
        args = [self.bind(a) for a in node.args]

        if name == "if":
            return self._bind_if(node, args)
        if name == "is_null":
            a = args[0]

            def emit_is_null(ctx):
                _, valid = a.emit(ctx)
                return ~valid, np.ones_like(valid)
            return _NBound(type=EValueType.boolean, vocab=None,
                           emit=emit_is_null)
        if name == "if_null":
            return self._bind_merge_select(
                node, [args[0], args[1]],
                lambda ctx, planes: (
                    np.where(planes[0][1], planes[0][0], planes[1][0]),
                    planes[0][1] | planes[1][1]))
        if name in ("int64", "uint64", "double", "boolean"):
            a = args[0]
            dt = device_dtype(node.type)

            def emit_cast(ctx):
                data, valid = a.emit(ctx)
                if data.dtype == np.bool_ or \
                        node.type is EValueType.boolean:
                    return (data.astype(dt)
                            if node.type is not EValueType.boolean
                            else (data != 0)), valid
                return data.astype(dt), valid
            return _NBound(type=node.type, vocab=None, emit=emit_cast)
        if name == "abs":
            a = args[0]

            def emit_abs(ctx):
                data, valid = a.emit(ctx)
                if np.issubdtype(data.dtype, np.unsignedinteger):
                    return data, valid
                return np.abs(data), valid
            return _NBound(type=node.type, vocab=None, emit=emit_abs)
        if name in ("floor", "ceil", "sqrt"):
            a = args[0]
            fn = {"floor": np.floor, "ceil": np.ceil,
                  "sqrt": np.sqrt}[name]

            def emit_math(ctx):
                data, valid = a.emit(ctx)
                return fn(data.astype(np.float64)), valid
            return _NBound(type=node.type, vocab=None, emit=emit_math)
        if name in ("lower", "upper"):
            return self._bind_string_map(
                args[0], (lambda v: v.lower()) if name == "lower" else
                (lambda v: v.upper()))
        if name == "concat":
            return self._bind_concat(args[0], args[1])
        if name.startswith("timestamp_floor_"):
            unit = name[len("timestamp_floor_"):]
            a = args[0]

            def emit_ts_floor(ctx):
                data, valid = a.emit(ctx)
                return _np_timestamp_floor(data.astype(np.int64),
                                           unit), valid
            return _NBound(type=EValueType.int64, vocab=None,
                           emit=emit_ts_floor)
        if name in ("is_finite", "is_nan"):
            a = args[0]
            fn = np.isfinite if name == "is_finite" else np.isnan

            def emit_fpred(ctx):
                data, valid = a.emit(ctx)
                return fn(data.astype(np.float64)), valid
            return _NBound(type=EValueType.boolean, vocab=None,
                           emit=emit_fpred)
        if name == "length":
            return self._bind_vocab_table(args[0], EValueType.int64,
                                          np.int64, len)
        if name in ("min_of", "max_of"):
            pick_min = name == "min_of"

            def emit_minmax(ctx):
                planes = [a.emit(ctx) for a in args]
                data, valid = planes[0]
                for d, v in planes[1:]:
                    d, data2 = _np_promote_pair(d, data)
                    better = (d < data2) if pick_min else (d > data2)
                    take = v & (~valid | better)
                    data = np.where(take, d, data2)
                    valid = valid | v
                return data, valid
            return _NBound(type=node.type, vocab=None, emit=emit_minmax)
        raise InterpUnsupported(f"function {name}")

    def _bind_if(self, node, args):
        cond, then_b, else_b = args

        def select(ctx, planes):
            cd, cv = planes[0]
            td, tv = planes[1]
            ed, ev = planes[2]
            take_then = cv & cd.astype(bool)
            take_else = cv & ~cd.astype(bool)
            td2, ed2 = _np_promote_pair(td, ed)
            data = np.where(take_then, td2, ed2)
            valid = np.where(take_then, tv, take_else & ev)
            return data, valid
        return self._bind_merge_select(node, [cond, then_b, else_b],
                                       select, string_operands=(1, 2))

    def _bind_merge_select(self, node, args, select,
                           string_operands=(0, 1)):
        if node.type is EValueType.string:
            value_args = [args[i] for i in string_operands]
            merged = _merge_vocabs(*[a.vocab for a in value_args])
            remap_gathers = {}
            for i in string_operands:
                a = args[i]
                vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
                remap_gathers[i] = _gather_table(_pad_np(
                    _remap_table(vocab, merged),
                    _vocab_bucket(max(len(vocab), 1)), 0))

            def emit_str(ctx):
                planes = []
                for i, a in enumerate(args):
                    d, v = a.emit(ctx)
                    if i in remap_gathers and a.type is EValueType.string:
                        d = remap_gathers[i](d)
                    planes.append((d, v))
                return select(ctx, planes)
            return _NBound(type=node.type, vocab=merged, emit=emit_str)

        def emit(ctx):
            planes = [a.emit(ctx) for a in args]
            return select(ctx, planes)
        return _NBound(type=node.type, vocab=None, emit=emit)

    def _bind_concat(self, a: _NBound, b: _NBound) -> _NBound:
        va = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        vb = b.vocab if b.vocab is not None else _EMPTY_VOCAB
        na, nb = max(len(va), 1), max(len(vb), 1)
        if na * nb > 1 << 16:
            raise YtError(
                f"concat() vocabulary cross product too large "
                f"({len(va)}x{len(vb)}); reduce distinct values",
                code=EErrorCode.QueryUnsupported)
        pairs = [bytes(x) + bytes(y)
                 for x in (va if len(va) else [b""])
                 for y in (vb if len(vb) else [b""])]
        merged = np.array(sorted(set(pairs)), dtype=object)
        lookup = {v: i for i, v in enumerate(merged)}
        table = np.array([lookup[p] for p in pairs], dtype=np.int32)
        gather = _gather_table(_pad_np(table,
                                       _vocab_bucket(len(table)), 0))
        nb_const = nb

        def emit(ctx):
            da, valid_a = a.emit(ctx)
            db, valid_b = b.emit(ctx)
            pair = da.astype(np.int32) * nb_const + db.astype(np.int32)
            return gather(pair), valid_a & valid_b
        return _NBound(type=EValueType.string, vocab=merged, emit=emit)

    def _bind_vocab_table(self, a: _NBound, result_type, np_dtype,
                          fn) -> _NBound:
        vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        table = np.array([fn(v) for v in vocab] or [np_dtype()],
                         dtype=np_dtype)
        gather = _gather_table(_pad_np(table,
                                       _vocab_bucket(len(table)), 0))

        def emit(ctx):
            data, valid = a.emit(ctx)
            return gather(data), valid
        return _NBound(type=result_type, vocab=None, emit=emit)

    def _bind_string_map(self, a: _NBound, fn) -> _NBound:
        vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        new_values = [fn(v) for v in vocab]
        new_vocab = np.array(sorted(set(new_values)), dtype=object)
        lookup = {v: i for i, v in enumerate(new_vocab)}
        table = np.array([lookup[v] for v in new_values], dtype=np.int32)
        if len(table) == 0:
            table = np.zeros(1, dtype=np.int32)
        gather = _gather_table(_pad_np(table,
                                       _vocab_bucket(len(table)), 0))

        def emit(ctx):
            data, valid = a.emit(ctx)
            return gather(data), valid
        return _NBound(type=EValueType.string, vocab=new_vocab,
                       emit=emit)

    # -- membership / ranges / predicates --------------------------------------

    def _value_tuples(self, operands, values, range_encode=False,
                      pad_to=None):
        """Mirror of expr._bind_value_tuples returning host arrays."""
        cols = []
        oks = []
        for oi, operand in enumerate(operands):
            col = [tup[oi] if oi < len(tup) else None for tup in values]
            if operand.type is EValueType.string:
                vocab = operand.vocab if operand.vocab is not None \
                    else _EMPTY_VOCAB
                if range_encode:
                    arr = np.array(
                        [_range_code(vocab, v) if v is not None else 0
                         for v in col], dtype=np.int32)
                else:
                    arr = np.array(
                        [_vocab_code(vocab, v) if v is not None else -2
                         for v in col], dtype=np.int32)
            else:
                dt = device_dtype(operand.type) \
                    if operand.type is not EValueType.null else np.int64
                arr = np.array([v if v is not None else 0 for v in col],
                               dtype=dt)
            ok = np.array([v is not None for v in col], dtype=bool)
            if len(arr) == 0:
                arr = np.zeros(1, dtype=arr.dtype)
                ok = np.zeros(1, dtype=bool)
            if pad_to is not None and len(arr) < pad_to:
                arr = _pad_np(arr, pad_to, 0)
                ok = _pad_np(ok, pad_to, False)
            cols.append(arr)
            oks.append(ok)
        return cols, oks

    def _bind_TIn(self, node: ir.TIn) -> _NBound:
        from ytsaurus_tpu.chunks.columnar import next_pow2
        operands = [self.bind(o) for o in node.operands]
        n_bucket = next_pow2(len(node.values))
        value_cols, value_oks = self._value_tuples(
            operands, node.values, pad_to=n_bucket)
        present = np.zeros(n_bucket, dtype=bool)
        present[: len(node.values)] = True

        def emit(ctx):
            op_planes = [o.emit(ctx) for o in operands]
            match_any = np.zeros(ctx.capacity, dtype=bool)
            for vi in range(n_bucket):
                row_match = np.ones(ctx.capacity, dtype=bool)
                for oi, (data, valid) in enumerate(op_planes):
                    const = value_cols[oi][vi]
                    cvalid = value_oks[oi][vi]
                    row_match = row_match & np.where(
                        cvalid, valid & (data == const), ~valid)
                match_any = match_any | (row_match & present[vi])
            return match_any, np.ones(ctx.capacity, dtype=bool)
        return _NBound(type=EValueType.boolean, vocab=None, emit=emit)

    def _bind_TBetween(self, node: ir.TBetween) -> _NBound:
        operands = [self.bind(o) for o in node.operands]
        string_ops = [o.type is EValueType.string for o in operands]
        bound_ranges = []
        for lower, upper in node.ranges:
            lo = self._value_tuples(operands[: len(lower)], [lower],
                                    range_encode=True)
            up = self._value_tuples(operands[: len(upper)], [upper],
                                    range_encode=True)
            bound_ranges.append((len(lower), lo, len(upper), up))

        def _lex_compare(cap, op_planes, tables, op):
            value_cols, value_oks = tables
            result = np.full(cap, op in ("<=", ">="), dtype=bool)
            for oi in range(len(op_planes) - 1, -1, -1):
                data, valid = op_planes[oi]
                const = value_cols[oi][0]
                cvalid = value_oks[oi][0]
                eq = np.where(cvalid, valid & (data == const), ~valid)
                if op in ("<=", "<"):
                    lt = np.where(cvalid, (~valid) | (data < const),
                                  np.zeros(cap, dtype=bool))
                    result = lt | (eq & result)
                else:
                    gt = np.where(cvalid, valid & (data > const), valid)
                    result = gt | (eq & result)
            return result

        def emit(ctx):
            op_planes = []
            for operand, is_str in zip(operands, string_ops):
                data, valid = operand.emit(ctx)
                if is_str:
                    data = data.astype(np.int32) * 2 + 1
                op_planes.append((data, valid))
            in_any = np.zeros(ctx.capacity, dtype=bool)
            for lo_len, lo_t, up_len, up_t in bound_ranges:
                ge = _lex_compare(ctx.capacity, op_planes[:lo_len],
                                  lo_t, ">=")
                le = _lex_compare(ctx.capacity, op_planes[:up_len],
                                  up_t, "<=")
                in_any = in_any | (ge & le)
            result = ~in_any if node.negated else in_any
            return result, np.ones(ctx.capacity, dtype=bool)
        return _NBound(type=EValueType.boolean, vocab=None, emit=emit)

    def _bind_TStringPredicate(self, node) -> _NBound:
        operand = self.bind(node.operand)
        vocab = operand.vocab if operand.vocab is not None \
            else _EMPTY_VOCAB
        matcher = _string_matcher(node)
        table = np.array([matcher(v) for v in vocab], dtype=bool)
        if len(table) == 0:
            table = np.zeros(1, dtype=bool)
        if node.negated:
            table = ~table
        gather = _gather_table(_pad_np(
            table, _vocab_bucket(len(table)), False))

        def emit(ctx):
            data, valid = operand.emit(ctx)
            return gather(data), valid
        return _NBound(type=EValueType.boolean, vocab=None, emit=emit)


# --- the plan pipeline --------------------------------------------------------


def materialize_planes(chunk, schema) -> tuple[dict, np.ndarray]:
    """The interpreter tier's ONE sanctioned device→host sync: pull the
    chunk's column planes and row mask to numpy in a single place (the
    `yt analyze` jax pass knows this function by name)."""
    sanitizers.note_host_sync("interp.materialize_planes")
    columns = {}
    for col_schema in schema:
        col = chunk.columns.get(col_schema.name)
        if col is None:
            raise YtError(f"Chunk is missing column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        columns[col_schema.name] = (np.asarray(col.data),
                                    np.asarray(col.valid))
    return columns, np.asarray(chunk.row_valid)


@dataclass
class InterpretedQuery:
    """Host-bound interpreted plan for one chunk shape: `execute(chunk)`
    returns (planes, count) exactly like PreparedQuery.run, with numpy
    planes and a python-int count."""
    run: Callable
    output: list

    def execute(self, chunk):
        return self.run(chunk)


def try_prepare(plan, chunk) -> Optional[InterpretedQuery]:
    """Bind `plan` for interpretation, or None when any part of it falls
    outside the declared coverage (the caller compiles inline instead)."""
    if not covers(plan):
        return None
    try:
        return _prepare(plan, chunk)
    except InterpUnsupported:
        return None


def _prepare(plan, chunk) -> InterpretedQuery:
    from ytsaurus_tpu.query.engine.lowering import (
        OutputColumn,
        _column_min_max,
    )
    from ytsaurus_tpu.chunks.columnar import next_pow2, pad_capacity
    from ytsaurus_tpu.config import compile_config

    capacity = chunk.capacity
    columns_meta = {}
    for col_schema in plan.schema:
        col = chunk.columns.get(col_schema.name)
        if col is None:
            raise YtError(f"Chunk is missing column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        columns_meta[col_schema.name] = (col_schema.type, col.dictionary)
    binder = NumpyBinder(columns_meta)

    where_b = None
    where = getattr(plan, "where", None)
    if where is not None:
        where_b = binder.bind(where)

    group = plan.group
    group_key_b = []
    agg_arg_b = []
    having_b = None
    post_binder = None
    if group is not None:
        for item in group.group_items:
            group_key_b.append((item.name, binder.bind(item.expr)))
        for agg in group.aggregate_items:
            if agg.argument is None:
                raise InterpUnsupported("argument-less aggregate")
            arg = binder.bind(agg.argument)
            by_arg = binder.bind(agg.by_argument) \
                if agg.by_argument is not None else None
            agg_arg_b.append((agg, arg, by_arg))
        post_columns = {}
        for (name, bound), item in zip(group_key_b, group.group_items):
            post_columns[name] = (bound.type, bound.vocab)
        for agg, arg, _ in agg_arg_b:
            vocab = arg.vocab if (arg is not None and
                                  agg.type is EValueType.string) else None
            post_columns[agg.name] = (agg.type, vocab)
        post_binder = NumpyBinder(post_columns)
        if plan.having is not None:
            having_b = post_binder.bind(plan.having)
    final_binder = post_binder if post_binder is not None else binder

    order_b = []
    if plan.order is not None:
        for item in plan.order.items:
            order_b.append((final_binder.bind(item.expr),
                            item.descending))

    project_b = []
    if plan.project is not None:
        for item in plan.project.items:
            project_b.append((item.name, final_binder.bind(item.expr)))
    else:
        if group is not None:
            for (name, bound) in group_key_b:
                project_b.append((name, _post_ref(name, bound.type,
                                                  bound.vocab)))
            for agg, arg, _ in agg_arg_b:
                vocab = arg.vocab if (arg is not None and
                                      agg.type is EValueType.string) \
                    else None
                project_b.append((agg.name, _post_ref(agg.name, agg.type,
                                                      vocab)))
        else:
            for col_schema in plan.schema:
                project_b.append(
                    (col_schema.name,
                     final_binder.bind(ir.TReference(
                         type=col_schema.type, name=col_schema.name))))

    output = [OutputColumn(name=name, type=b.type, vocab=b.vocab)
              for name, b in project_b]
    offset = plan.offset
    limit = plan.limit
    parameterized = compile_config().parameterize

    # Fast-group decision: IDENTICAL probe to lowering's (same memoized
    # _column_min_max, same domain caps) — a divergent decision would
    # change the group output ORDER (dense slots put nulls last; the
    # sorted path puts them first).
    fast_group = None
    if group is not None:
        sizes_offsets = []
        for item, (_, bound) in zip(group.group_items, group_key_b):
            if bound.type is EValueType.string and \
                    bound.vocab is not None:
                sizes_offsets.append((len(bound.vocab), 0))
            elif bound.type is EValueType.boolean:
                sizes_offsets.append((2, 0))
            elif bound.type in (EValueType.int64, EValueType.uint64) and \
                    isinstance(item.expr, ir.TReference):
                col = chunk.columns.get(item.expr.name) \
                    if hasattr(chunk, "columns") else None
                data = getattr(col, "data", None)
                if data is None:
                    sizes_offsets = None
                    break
                lo, hi = _column_min_max(col, bound.type)
                if hi - lo + 1 > 65536:
                    sizes_offsets = None
                    break
                sizes_offsets.append((hi - lo + 1, lo))
            else:
                sizes_offsets = None
                break
        if sizes_offsets is not None:
            dims = 1
            for s, _ in sizes_offsets:
                dims *= s + 1
            if 0 < dims <= 65536:
                strides = []
                acc = 1
                for s, _ in reversed(sizes_offsets):
                    strides.append(acc)
                    acc *= s + 1
                strides.reverse()
                fast_group = (tuple(sizes_offsets), tuple(strides), dims,
                              pad_capacity(dims + 1))

    def run(chunk):
        with np.errstate(over="ignore", invalid="ignore",
                         divide="ignore"):
            return _execute(chunk)

    def _execute(chunk):
        columns, row_valid = materialize_planes(chunk, plan.schema)
        ctx = _Ctx(columns=columns, capacity=capacity)
        stage_cap = capacity
        mask = row_valid
        if where_b is not None:
            d, v = where_b.emit(ctx)
            mask = mask & v & d.astype(bool)

        if group is not None and fast_group is not None:
            sizes_offsets, strides, dims, seg_cap = fast_group
            nseg = dims + 1

            def _pad(plane):
                out = np.zeros(seg_cap, dtype=plane.dtype)
                out[:nseg] = plane
                return out

            key_planes = [b.emit(ctx) for _, b in group_key_b]
            seg = np.zeros(capacity, dtype=np.int32)
            for (data, valid), (size, key_offset), stride in zip(
                    key_planes, sizes_offsets, strides):
                if np.issubdtype(data.dtype, np.integer):
                    off = np.uint64(key_offset % (1 << 64))
                    shifted = (data.astype(np.uint64)
                               - off).astype(np.int32)
                else:
                    shifted = (data.astype(np.int64)
                               - key_offset).astype(np.int32)
                code = np.where(valid, shifted, size)
                seg = seg + code * stride
            seg = np.where(mask, seg, dims).astype(np.int64)
            present_counts, _ = _np_segment_aggregate(
                "count", mask, mask, seg, nseg, EValueType.int64)
            present = _pad((np.arange(nseg) < dims) &
                           (present_counts > 0))
            new_columns = {}
            slot = np.arange(seg_cap)
            for (name, bound), (size, key_offset), stride in zip(
                    group_key_b, sizes_offsets, strides):
                code = (slot // stride) % (size + 1)
                key_valid = code < size
                data = np.clip(code, 0, max(size - 1, 0))
                if bound.type is EValueType.boolean:
                    data = data.astype(np.bool_)
                elif bound.type in (EValueType.int64, EValueType.uint64):
                    dt = device_dtype(bound.type)
                    data = data.astype(dt) + np.array(key_offset,
                                                      dtype=dt)
                else:
                    data = data.astype(np.int32)
                new_columns[name] = (data, key_valid)
            _aggregate_into(new_columns, agg_arg_b, ctx, mask, seg, nseg,
                            pad=_pad)
            mask = present
            stage_cap = seg_cap
            ctx = _Ctx(columns=new_columns, capacity=seg_cap)
            if having_b is not None:
                d, v = having_b.emit(ctx)
                mask = mask & v & d.astype(bool)
        elif group is not None:
            key_planes = [b.emit(ctx) for _, b in group_key_b]
            order_idx = _hash_group_order(key_planes, mask)
            sorted_mask = mask[order_idx]
            sorted_keys = [(d[order_idx], v[order_idx])
                           for d, v in key_planes]
            seg_ids, num_groups = _segment_boundaries(sorted_keys,
                                                      sorted_mask)
            new_columns = {}
            for (name, _), (data, valid) in zip(group_key_b,
                                                sorted_keys):
                out_d, _ = _np_segment_aggregate(
                    "first", data, sorted_mask, seg_ids, capacity,
                    EValueType.null)
                out_v, _ = _np_segment_aggregate(
                    "first", valid.astype(np.int8), sorted_mask,
                    seg_ids, capacity, EValueType.null)
                new_columns[name] = (out_d, out_v.astype(bool))
            _aggregate_into(new_columns, agg_arg_b, ctx, sorted_mask,
                            seg_ids, capacity, reorder=order_idx)
            mask = np.arange(capacity) < num_groups
            ctx = _Ctx(columns=new_columns, capacity=capacity)
            if having_b is not None:
                d, v = having_b.emit(ctx)
                mask = mask & v & d.astype(bool)

        if order_b:
            # Full stable sort (no top-k candidate stage): identical over
            # the visible window, see the module docstring.
            keys = [(~mask).astype(np.uint8)]
            for bound, descending in order_b:
                data, valid = bound.emit(ctx)
                null_plane = ((~valid) if descending
                              else valid).astype(np.uint8)
                enc = _np_monotone_u64(data)
                if descending:
                    enc = ~enc
                enc = np.where(valid, enc, np.uint64(0))
                keys.append(null_plane)
                keys.append(enc)
            order_idx = np.lexsort(tuple(reversed(keys)))
            ctx = _Ctx(columns={name: (d[order_idx], v[order_idx])
                                for name, (d, v) in ctx.columns.items()},
                       capacity=stage_cap)
            mask = mask[order_idx]

        planes = [b.emit(ctx) for _, b in project_b]

        comp_idx = np.argsort((~mask).astype(np.uint8), kind="stable")
        total = int(mask.sum())
        off = min(offset, stage_cap) if parameterized else offset
        count = total - off
        if limit is not None:
            lim = min(limit, stage_cap) if parameterized else limit
            count = min(count, lim)
        count = max(count, 0)
        out_planes = []
        shift = np.clip(np.arange(stage_cap) + off, 0, stage_cap - 1)
        in_count = np.arange(stage_cap) < count
        for d, v in planes:
            d = d[comp_idx][shift]
            v = v[comp_idx][shift] & in_count
            out_planes.append((d, v))
        return out_planes, count

    return InterpretedQuery(run=run, output=output)


def _post_ref(name: str, ty, vocab) -> _NBound:
    def emit(ctx: _Ctx):
        return ctx.columns[name]
    return _NBound(type=ty, vocab=vocab, emit=emit)


def _aggregate_into(new_columns, agg_arg_b, ctx, gmask, seg, nseg,
                    pad=None, reorder=None):
    """Shared aggregate loop for both group paths, mirroring lowering's
    per-function dispatch.  `reorder` re-sorts argument planes into the
    grouped row order (the sorted path); `pad` widens fast-group outputs
    to the padded slot capacity."""
    def _r(plane):
        return plane if reorder is None else plane[reorder]

    def _out(plane):
        return plane if pad is None else pad(plane)

    for agg, arg, by_arg in agg_arg_b:
        if agg.function == "avg":
            data, valid = arg.emit(ctx)
            data = _r(data).astype(np.float64)
            valid = _r(valid) & gmask
            s, sv = _np_segment_aggregate("sum", data, valid, seg, nseg,
                                          EValueType.double)
            c, _ = _np_segment_aggregate("count", data, valid, seg,
                                         nseg, EValueType.int64)
            new_columns[agg.name] = (_out(s / np.maximum(c, 1)),
                                     _out(sv))
        elif agg.function == "cardinality":
            data, valid = arg.emit(ctx)
            d, dv = _np_segment_distinct_count(
                _r(data), _r(valid) & gmask, seg, nseg)
            new_columns[agg.name] = (_out(d), _out(dv))
        elif agg.function in ("argmin", "argmax"):
            vd, vv = arg.emit(ctx)
            bd, bv = by_arg.emit(ctx)
            out_d, out_v = _np_segment_arg_by(
                _r(vd), _r(vv), _r(bd), _r(bv) & gmask, seg, nseg,
                take_max=(agg.function == "argmax"))
            new_columns[agg.name] = (_out(out_d), _out(out_v))
        else:
            data, valid = arg.emit(ctx)
            valid = _r(valid) & gmask
            out, out_v = _np_segment_aggregate(
                agg.function, _r(data), valid, seg, nseg, agg.type)
            new_columns[agg.name] = (_out(out), _out(out_v))


def _hash_group_order(key_planes, mask) -> np.ndarray:
    """Mirror of segments.hash_group_order: stable ascending sort by
    [flags word (masked bit | per-key validity bits), then each key's
    monotone encoding with invalid values zeroed]."""
    flags = (~mask).astype(np.uint64)
    for data, valid in key_planes:
        flags = (flags << np.uint64(1)) | valid.astype(np.uint64)
    keys = [flags]
    for data, valid in key_planes:
        keys.append(np.where(valid, _np_monotone_u64(data),
                             np.uint64(0)))
    return np.lexsort(tuple(reversed(keys)))


def _segment_boundaries(sorted_keys, in_mask):
    """Mirror of segments.segment_boundaries — including the raw-plane
    compare (garbage under invalid splits exactly like the device)."""
    cap = in_mask.shape[0]
    change = np.zeros(cap, dtype=bool)
    for data, valid in sorted_keys:
        differs = (data != np.roll(data, 1)) | \
            (valid != np.roll(valid, 1))
        change = change | differs
    if cap:
        change[0] = False
    boundary = change & in_mask
    seg = np.cumsum(boundary.astype(np.int64))
    num_segments = int(seg[-1] + 1) if in_mask.any() else 0
    seg = np.where(in_mask, seg, num_segments)
    return seg, num_segments
