"""Master transactions: locks + undo-based rollback over the Cypress tree.

Ref shape: server/master/transaction_server (nested master transactions)
and cypress_server/node_detail.h lock semantics (snapshot/shared/exclusive
locks, implicit exclusive locks on writes).

Redesign: the reference branches versioned node states per transaction and
merges on commit; here mutations under a transaction apply WRITE-THROUGH to
the live tree while an UNDO entry is recorded, and abort replays the undo
in reverse.  Undo entries are recomputed deterministically during WAL
replay (each mutation recomputes its undo against the same tree state), so
only the mutation stream needs to be durable — undo logs never hit disk.
Lock conflicts use path containment: an exclusive lock on `//a/b` blocks
any other writer under `//a/b` and any writer on its ancestor chain.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ytsaurus_tpu.cypress.tree import CypressNode, CypressTree, parse_ypath
from ytsaurus_tpu.errors import EErrorCode, YtError

LOCK_MODES = ("snapshot", "shared", "exclusive")


def _node_path(path: str) -> str:
    """Strip an attribute suffix: locks are per node."""
    tokens, _attr = parse_ypath(path)
    return "//" + "/".join(tokens) if tokens else "/"


def _covers(a: str, b: str) -> bool:
    """True if lock path `a` and access path `b` overlap (ancestor either
    way): a writer under a locked subtree conflicts, and so does removing
    an ancestor of a locked node."""
    if a == "/" or b == "/":
        return True
    return a == b or b.startswith(a + "/") or a.startswith(b + "/")


def _undo_paths(entry: tuple) -> "set[str]":
    """Node paths an undo entry edits when replayed (attribute entries
    collapse to their node's path)."""
    kind = entry[0]
    if kind == "seq":
        out: set = set()
        for sub in entry[1:]:
            out.update(_undo_paths(sub))
        return out
    if kind in ("remove_if_created", "restore"):
        return {entry[1]}
    if kind in ("set_attr", "remove_attr"):
        return {_node_path(entry[1])}
    return set()


@dataclass
class MasterTransaction:
    id: str
    parent_id: Optional[str] = None
    # path -> mode for shared/exclusive; snapshot copies are separate.
    locks: dict[str, str] = field(default_factory=dict)
    snapshots: dict[str, CypressNode] = field(default_factory=dict)
    undo: list[tuple] = field(default_factory=list)
    children: list[str] = field(default_factory=list)

    def serialize(self) -> dict:
        # Undo entries MUST be durable: write-through means a transaction's
        # mutations are inside the snapshot, so abort-after-restart depends
        # on the persisted undo log.
        return {"id": self.id, "parent_id": self.parent_id,
                "locks": dict(self.locks),
                "children": list(self.children),
                "undo": [list(_listify(e)) for e in self.undo],
                "snapshots": {p: n.serialize()
                              for p, n in self.snapshots.items()}}

    @classmethod
    def deserialize(cls, data: dict) -> "MasterTransaction":
        return cls(id=data["id"], parent_id=data.get("parent_id"),
                   locks={k: v for k, v in (data.get("locks") or {}).items()},
                   children=list(data.get("children") or []),
                   undo=[_tuplify(e) for e in (data.get("undo") or [])],
                   snapshots={p: CypressNode.deserialize(n)
                              for p, n in
                              (data.get("snapshots") or {}).items()})


class MasterTransactionManager:
    """Lock table + undo logs for transactions on the metadata tree.

    Owned by the Master; all entry points run under the master's mutation
    lock and are invoked both for live mutations and during WAL replay.
    """

    def __init__(self, tree: CypressTree):
        self.tree = tree
        self.transactions: dict[str, MasterTransaction] = {}

    def set_tree(self, tree: CypressTree) -> None:
        self.tree = tree

    # -- lifecycle -------------------------------------------------------------

    def start(self, tx_id: Optional[str] = None,
              parent_id: Optional[str] = None) -> str:
        tx_id = tx_id or uuid.uuid4().hex
        if tx_id in self.transactions:
            raise YtError(f"Transaction {tx_id} already exists",
                          code=EErrorCode.AlreadyExists)
        if parent_id is not None:
            parent = self._get(parent_id)
            parent.children.append(tx_id)
        self.transactions[tx_id] = MasterTransaction(tx_id,
                                                     parent_id=parent_id)
        return tx_id

    def commit(self, tx_id: str) -> "list[str]":
        """Changes are already live (write-through); commit hands locks and
        undo to the parent (nested tx) or discards them (top-level).
        Returns the node paths ROLLED BACK by aborting uncommitted
        children — rollback edits the tree outside the mutation stream,
        so post-commit observers (Sequoia) resync exactly those."""
        tx = self._get(tx_id)
        touched: set = set()
        for child in list(tx.children):
            if child in self.transactions:
                touched.update(self.abort(child))   # children roll back
        parent = self.transactions.get(tx.parent_id) \
            if tx.parent_id else None
        if parent is not None:
            # Parent inherits: its abort must also roll back this child.
            parent.undo.extend(tx.undo)
            for path, mode in tx.locks.items():
                if _rank(mode) > _rank(parent.locks.get(path, "")):
                    parent.locks[path] = mode
            parent.children.remove(tx_id)
        del self.transactions[tx_id]
        return sorted(touched)

    def abort(self, tx_id: str) -> "list[str]":
        """Roll the transaction back; returns every node path the undo
        replay touched (the abort-scoped resync set for observers — the
        Sequoia alternative to a full table resync)."""
        tx = self._get(tx_id)
        touched: set = set()
        for child in list(tx.children):
            if child in self.transactions:
                touched.update(self.abort(child))
        for entry in reversed(tx.undo):
            touched.update(_undo_paths(entry))
            self._apply_undo(entry)
        if tx.parent_id and tx.parent_id in self.transactions:
            parent = self.transactions[tx.parent_id]
            if tx_id in parent.children:
                parent.children.remove(tx_id)
        del self.transactions[tx_id]
        return sorted(touched)

    def _get(self, tx_id: str) -> MasterTransaction:
        tx = self.transactions.get(tx_id)
        if tx is None:
            raise YtError(f"No such transaction {tx_id}",
                          code=EErrorCode.NoSuchTransaction)
        return tx

    # -- locks -----------------------------------------------------------------

    def lock(self, tx_id: str, path: str, mode: str = "exclusive") -> None:
        if mode not in LOCK_MODES:
            raise YtError(f"Unknown lock mode {mode!r}")
        tx = self._get(tx_id)
        path = _node_path(path)
        node = self.tree.resolve(path)
        if mode == "snapshot":
            # Pin a deep copy for the transaction's reads; never conflicts.
            import copy
            tx.snapshots[path] = copy.deepcopy(node)
            return
        self._check_conflicts(tx_id, path, want=mode)
        current = tx.locks.get(path, "")
        if _rank(mode) > _rank(current):
            tx.locks[path] = mode

    def _check_conflicts(self, tx_id: Optional[str], path: str,
                         want: str) -> None:
        """Exclusive conflicts with everything else on overlapping paths;
        shared conflicts with exclusive only."""
        for other in self.transactions.values():
            if other.id == tx_id:
                continue
            # Ancestors of `other` do not conflict with it (nested txs).
            if tx_id is not None and self._is_ancestor(other.id, tx_id):
                continue
            for lock_path, lock_mode in other.locks.items():
                if not _covers(lock_path, path):
                    continue
                if lock_mode == "exclusive" or want == "exclusive":
                    raise YtError(
                        f"Cannot take {want!r} lock on {path!r}: "
                        f"transaction {other.id} holds {lock_mode!r} lock "
                        f"on {lock_path!r}",
                        code=EErrorCode.ConcurrentTransactionLockConflict)

    def _is_ancestor(self, maybe_ancestor: str, tx_id: str) -> bool:
        current = self.transactions.get(tx_id)
        while current is not None and current.parent_id is not None:
            if current.parent_id == maybe_ancestor:
                return True
            current = self.transactions.get(current.parent_id)
        return False

    # -- mutation interception -------------------------------------------------

    def before_mutation(self, tx_id: Optional[str], op: str,
                        args: dict) -> Optional[tuple]:
        """Conflict check + implicit exclusive lock + undo capture.  Called
        BEFORE the mutation applies (the undo must see the old state).
        Returns the undo entry; the caller records it via `after_mutation`
        only once the tree op SUCCEEDS (an undo for a failed mutation would
        roll back state the mutation never changed)."""
        paths = _written_paths(op, args)
        for path in paths:
            self._check_conflicts(tx_id, path, want="exclusive")
        if tx_id is None:
            return None
        tx = self._get(tx_id)
        for path in paths:
            if _rank("exclusive") > _rank(tx.locks.get(path, "")):
                tx.locks[path] = "exclusive"
        return self._capture_undo(op, args)

    def after_mutation(self, tx_id: Optional[str],
                       undo: Optional[tuple]) -> None:
        if tx_id is not None and undo is not None:
            self._get(tx_id).undo.append(undo)

    def _capture_undo(self, op: str, args: dict) -> tuple:
        tree = self.tree
        if op == "create":
            # ignore_existing on a pre-existing node creates nothing —
            # undoing it must NOT delete the pre-existing subtree.
            path = _node_path(args["path"])
            if tree.try_resolve(path) is not None:
                return ("noop",)
            # A recursive create materializes intermediate map nodes too;
            # the undo must remove the TOPMOST node the create builds or
            # rollback leaves orphan ancestors behind.
            tokens, _ = parse_ypath(args["path"])
            for i in range(1, len(tokens) + 1):
                candidate = "//" + "/".join(tokens[:i])
                if tree.try_resolve(candidate) is None:
                    return ("remove_if_created", candidate)
            return ("remove_if_created", path)
        if op == "set":
            path = args["path"]
            tokens, attr = parse_ypath(path)
            node = tree.try_resolve(_node_path(path))
            if node is None:
                return ("remove_if_created", _node_path(path))
            if attr is not None:
                try:
                    old = tree.get(path)
                    return ("set_attr", path, old)
                except YtError:
                    return ("remove_attr", path)
            return ("restore", _node_path(path), node.serialize())
        if op in ("remove", "move"):
            src = args.get("path") or args.get("src")
            tokens, attr = parse_ypath(src)
            if attr is not None:
                try:
                    return ("set_attr", src, tree.get(src))
                except YtError:
                    return ("remove_attr", src)
            node = tree.try_resolve(src)
            if node is None:
                return ("noop",)
            entry = ("restore", _node_path(src), node.serialize())
            if op == "move":
                return ("seq", entry, ("remove_if_created", args["dst"]))
            return entry
        if op == "copy":
            return ("remove_if_created", args["dst"])
        if op == "link":
            return ("remove_if_created", args["link"])
        return ("noop",)

    # Batch atomicity support: the master captures/replays undo entries
    # around multi-op WAL records (Master._apply "batch") so a mid-batch
    # resolution failure rolls earlier sub-ops back.
    def capture_undo(self, op: str, args: dict) -> tuple:
        return self._capture_undo(op, args)

    def apply_undo(self, entry: tuple) -> None:
        self._apply_undo(entry)

    def _apply_undo(self, entry: tuple) -> None:
        kind = entry[0]
        if kind == "noop":
            return
        if kind == "seq":
            for sub in reversed(entry[1:]):
                self._apply_undo(sub)
            return
        if kind == "remove_if_created":
            self.tree.remove(entry[1], recursive=True, force=True)
            return
        if kind == "set_attr":
            self.tree.set(entry[1], entry[2])
            return
        if kind == "remove_attr":
            self.tree.remove(entry[1], force=True)
            return
        if kind == "restore":
            path, payload = entry[1], entry[2]
            self.tree.remove(path, recursive=True, force=True)
            restored = CypressNode.deserialize(payload)
            parent_path = path.rsplit("/", 1)[0] or "/"
            tokens, _ = parse_ypath(path)
            parent = self.tree.resolve(parent_path) \
                if parent_path != "//" else self.tree.root
            parent.children[tokens[-1]] = restored
            return
        raise AssertionError(entry)

    # -- transactional reads ---------------------------------------------------

    def read_snapshot(self, tx_id: str, path: str):
        """Value pinned by a snapshot lock, or None when not pinned."""
        tx = self._get(tx_id)
        node_path = _node_path(path)
        for pinned_path, node in tx.snapshots.items():
            if pinned_path == node_path or \
                    node_path.startswith(pinned_path + "/"):
                shadow = CypressTree()
                tokens, _ = parse_ypath(pinned_path)
                parent = shadow.root
                for token in tokens[:-1]:
                    child = CypressNode(id="x", type="map_node")
                    parent.children[token] = child
                    parent = child
                parent.children[tokens[-1]] = node
                return shadow.get(path)
        return None

    # -- persistence -----------------------------------------------------------

    def serialize(self) -> dict:
        return {tx_id: tx.serialize()
                for tx_id, tx in self.transactions.items()}

    @classmethod
    def deserialize(cls, tree: CypressTree,
                    data: dict) -> "MasterTransactionManager":
        mgr = cls(tree)
        for tx_id, tx_data in (data or {}).items():
            mgr.transactions[tx_id] = MasterTransaction.deserialize(tx_data)
        return mgr


def _listify(entry: tuple) -> list:
    """Undo entry → YSON-able list; recurse only into 'seq' sub-entries
    (payloads like node serializations must pass through untouched)."""
    if entry and entry[0] == "seq":
        return ["seq", *[_listify(e) for e in entry[1:]]]
    return list(entry)


def _tuplify(entry: list) -> tuple:
    if entry and entry[0] == "seq":
        return ("seq", *[_tuplify(e) for e in entry[1:]])
    return tuple(entry)


def _rank(mode: str) -> int:
    return {"": 0, "snapshot": 1, "shared": 2, "exclusive": 3}.get(mode, 0)


def _written_paths(op: str, args: dict) -> list[str]:
    if op in ("create", "remove", "set"):
        return [_node_path(args["path"])]
    if op in ("copy", "move"):
        out = [_node_path(args["dst"])]
        if op == "move":
            out.append(_node_path(args["src"]))
        return out
    if op == "link":
        return [_node_path(args["link"])]
    return []
