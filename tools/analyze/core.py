"""Shared substrate of the `yt analyze` static-analysis suite (ISSUE 9).

The pattern PR 6's sensor-catalog lint proved — an AST walk over the
tree, run from the test suite, keeping a cross-cutting invariant green
forever — generalized into one framework every pass shares:

  SourceFile        one parsed module: repo-relative path, source lines,
                    AST, and the waiver table parsed from comments.
  Finding           one violation with `path:line`, a stable RULE id,
                    severity, and a message.
  waivers           `# analyze: allow(<rule>): <reason>` on (or directly
                    above) the offending line suppresses that rule there;
                    the reason string is MANDATORY — a bare waiver is
                    itself a finding (`waiver-reason`).
  baseline ratchet  findings aggregate per (pass, rule, path) into
                    counts checked against tools/analyze/baseline.json:
                    counts may only DECREASE; a new (pass, rule, path)
                    key or a count increase fails the build.  Fixing
                    debt then running `yt analyze --update-baseline`
                    tightens the ratchet.

Passes register in `tools/analyze/__init__.py::PASSES`; each exposes
`run(files: list[SourceFile]) -> list[Finding]` and is pure AST — no
module under analysis is ever imported, so heavy-dep modules cannot
break the lint.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Iterable, Optional

SEVERITIES = ("error", "warning")

# `# analyze: allow(rule-a, rule-b): why this is fine`
_WAIVER_RE = re.compile(
    r"#\s*analyze:\s*allow\(\s*([a-z0-9_\-\s,]+?)\s*\)\s*(?::\s*(.*?))?\s*$")


class Finding:
    """One violation.  `key()` is the baseline-aggregation unit — rule +
    file, NOT the line number, so unrelated edits shifting lines don't
    churn the committed baseline."""

    __slots__ = ("pass_name", "rule", "path", "line", "message",
                 "severity")

    def __init__(self, pass_name: str, rule: str, path: str, line: int,
                 message: str, severity: str = "error"):
        assert severity in SEVERITIES, severity
        self.pass_name = pass_name
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message
        self.severity = severity

    def key(self) -> str:
        return f"{self.pass_name}:{self.rule}:{self.path}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}]"
                f" {self.message}")

    def to_dict(self) -> dict:
        return {"pass": self.pass_name, "rule": self.rule,
                "path": self.path, "line": self.line,
                "message": self.message, "severity": self.severity}


class Waiver:
    __slots__ = ("rules", "reason", "line")

    def __init__(self, rules: "tuple[str, ...]", reason: str, line: int):
        self.rules = rules
        self.reason = reason
        self.line = line


class SourceFile:
    """One module under analysis, parsed once and shared by every pass."""

    def __init__(self, path: str, source: str):
        self.path = path                 # repo-relative, '/'-separated
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> [Waiver]: a waiver governs its own line; a waiver on a
        # comment-only line also governs the next non-blank line (the
        # statement it sits above).
        self.waivers: dict[int, list[Waiver]] = {}
        self._parse_waivers()

    def _parse_waivers(self) -> None:
        for lineno, text in enumerate(self.lines, start=1):
            match = _WAIVER_RE.search(text)
            if match is None:
                continue
            rules = tuple(r.strip() for r in match.group(1).split(",")
                          if r.strip())
            reason = (match.group(2) or "").strip()
            waiver = Waiver(rules, reason, lineno)
            self.waivers.setdefault(lineno, []).append(waiver)
            if text.lstrip().startswith("#"):
                # Standalone comment: governs the statement below it.
                nxt = lineno + 1
                while nxt <= len(self.lines) and not self.lines[nxt - 1].strip():
                    nxt += 1
                self.waivers.setdefault(nxt, []).append(waiver)

    def waived(self, rule: str, line: int) -> bool:
        # Only THIS line: standalone comment-above waivers were already
        # mapped forward by _parse_waivers, so a fallback to line-1 here
        # would let an inline waiver on one line silently suppress the
        # next line's findings too.
        for waiver in self.waivers.get(line, ()):
            if rule in waiver.rules and waiver.reason:
                return True
        return False

    def function_waived(self, rule: str, node: ast.AST) -> bool:
        """A waiver on any line of a def's signature (decorators
        included, or the comment line directly above them) governs the
        whole function for function-granular rules (failpoint
        coverage)."""
        start = getattr(node, "lineno", 0)
        for deco in getattr(node, "decorator_list", []) or []:
            start = min(start, getattr(deco, "lineno", start) - 1)
        end = getattr(node.body[0], "lineno", start) \
            if getattr(node, "body", None) else start
        return any(self.waived(rule, line) for line in range(start, end + 1))


def waiver_findings(pass_name: str, files: "list[SourceFile]"
                    ) -> "list[Finding]":
    """Bare waivers (no reason string) are findings: a suppression with
    no recorded justification is unreviewable debt."""
    out = []
    for f in files:
        seen = set()
        for waivers in f.waivers.values():
            for w in waivers:
                if not w.reason and id(w) not in seen:
                    seen.add(id(w))
                    out.append(Finding(
                        pass_name, "waiver-reason", f.path, w.line,
                        f"waiver for {', '.join(w.rules)} has no reason "
                        f"string — use `# analyze: allow(rule): why`"))
    return out


def load_files(root: str, package: str = "ytsaurus_tpu",
               rel_paths: Optional[Iterable[str]] = None
               ) -> "list[SourceFile]":
    """Parse every .py module under <root>/<package> (or just
    `rel_paths`, repo-relative).  Unparseable files surface as a
    framework finding downstream, not an exception."""
    files: list[SourceFile] = []
    if rel_paths is not None:
        paths = [os.path.join(root, p) for p in rel_paths]
    else:
        paths = []
        pkg_root = os.path.join(root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for filename in sorted(filenames):
                if filename.endswith(".py"):
                    paths.append(os.path.join(dirpath, filename))
    for path in sorted(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        files.append(SourceFile(rel, source))
    return files


# -- baseline ratchet ----------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def aggregate(findings: "list[Finding]") -> "dict[str, int]":
    counts: dict[str, int] = {}
    for finding in findings:
        counts[finding.key()] = counts.get(finding.key(), 0) + 1
    return counts


def load_baseline(path: Optional[str] = None) -> "dict[str, int]":
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(findings: "list[Finding]",
                   path: Optional[str] = None) -> "dict[str, int]":
    counts = aggregate(findings)
    payload = {
        "comment": "Ratcheted debt: counts may only decrease. "
                   "Regenerate with `yt analyze --update-baseline` "
                   "AFTER fixing findings, never to admit new ones.",
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    with open(path or BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return counts


def check_ratchet(findings: "list[Finding]",
                  baseline: "dict[str, int]") -> "list[str]":
    """Ratchet semantics: per (pass, rule, path) the live count must not
    exceed the baseline; unknown keys are NEW findings and always fail.
    Counts below baseline pass (and `--update-baseline` tightens)."""
    errors = []
    counts = aggregate(findings)
    by_key: dict[str, list[Finding]] = {}
    for finding in findings:
        by_key.setdefault(finding.key(), []).append(finding)
    for key in sorted(counts):
        allowed = baseline.get(key)
        if allowed is None:
            for finding in by_key[key]:
                errors.append(f"NEW {finding.format()}")
        elif counts[key] > allowed:
            lines = ", ".join(str(f.line) for f in by_key[key])
            errors.append(
                f"RATCHET {key}: {counts[key]} findings > baseline "
                f"{allowed} (lines {lines}) — fix the regression, do "
                f"not grow the baseline")
    return errors


# -- shared AST helpers --------------------------------------------------------


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('jax.jit', 'self._lock.acquire',
    'open'); '' when the callee is not a plain name/attribute chain."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif not parts:
        return ""
    else:
        parts.append("<expr>")
    return ".".join(reversed(parts))


def walk_functions(tree: ast.AST):
    """Yield (class_name_or_None, function_node) for every def in a
    module, including methods (one level of class nesting, the repo
    idiom)."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


def iter_calls(node: ast.AST):
    for child in ast.walk(node):
        if isinstance(child, ast.Call):
            yield child


def expr_contains_name(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))
