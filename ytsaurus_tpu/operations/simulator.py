"""Scheduler simulator: replay a synthetic workload through the
fair-share strategy in virtual time.

Ref: yt/yt/tools/scheduler_simulator (+ the scheduler_simulator
integration suite, yt/yt/tests/integration/scheduler_simulator): feed a
trace of operations (arrival time, job count, per-job duration, pool)
into the scheduling strategy with N virtual slots and measure per-pool
usage integrals, completion times, wait times, and preemptions —
without spawning a single real job.  Pool-tree changes and strategy
regressions are evaluated here before touching a cluster.

The simulated strategy IS the production one: PoolState +
compute_fair_shares + pick_pool + find_preemptable from
operations/fair_share.py drive both the live scheduler and this
event loop, so the simulator cannot drift from the shipped math.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Optional

from ytsaurus_tpu.operations.fair_share import (
    PoolState,
    compute_fair_shares,
    find_preemptable,
    pick_pool,
)


@dataclass(frozen=True)
class SimPool:
    name: str
    weight: float = 1.0
    min_share_ratio: float = 0.0
    max_running_jobs: "Optional[int]" = None


@dataclass(frozen=True)
class SimOperation:
    id: str
    pool: str
    arrival: float              # virtual seconds
    n_jobs: int
    job_duration: float         # virtual seconds per job


@dataclass
class SimResult:
    makespan: float
    completions: dict           # op id → completion time
    wait_times: dict            # op id → first-job start − arrival
    pool_usage_integral: dict   # pool → slot·seconds actually used
    preemptions: int
    samples: list = field(default_factory=list)   # (t, {pool: running})

    def usage_ratio(self, a: str, b: str) -> float:
        return self.pool_usage_integral[a] / \
            max(self.pool_usage_integral[b], 1e-12)


def simulate(pools: "list[SimPool]", operations: "list[SimOperation]",
             total_slots: int, preemption: bool = True,
             max_virtual_time: float = 1e9) -> SimResult:
    """Event-driven loop: virtual time advances to the next arrival or
    job completion; every event triggers a scheduling pass that fills
    free slots via pick_pool and (optionally) preempts one over-share
    job per pass when a pool starves below fair share — the strategy's
    own preemption rule."""
    states = {p.name: PoolState(
        name=p.name, weight=p.weight, min_share_ratio=p.min_share_ratio,
        max_running_jobs=p.max_running_jobs) for p in pools}
    for op in operations:
        if op.pool not in states:
            raise ValueError(f"operation {op.id} names unknown pool "
                             f"{op.pool!r}")
    arrivals = sorted(operations, key=lambda o: (o.arrival, o.id))
    queued: dict[str, int] = {}          # op id → jobs waiting
    unfinished: dict[str, int] = {}      # op id → jobs not yet completed
    op_index = {op.id: op for op in operations}
    first_start: dict[str, float] = {}
    completions: dict[str, float] = {}
    usage_integral = {p.name: 0.0 for p in pools}
    samples: list = []
    # Running jobs: (finish_time, seq, op_id, start_time).  seq breaks
    # ties deterministically; the NEWEST job of a pool is its preemption
    # victim (speculative work lost is minimized), matching the live
    # scheduler's victim choice.
    running: list = []
    seq = 0
    slots_free = total_slots
    preemptions_total = 0
    t = 0.0
    i = 0

    def pool_running(name: str) -> int:
        return sum(1 for _, _, oid, _ in running
                   if op_index[oid].pool == name)

    def refresh_states() -> None:
        for name, state in states.items():
            state.running = pool_running(name)
            state.pending = sum(
                n for oid, n in queued.items()
                if n > 0 and op_index[oid].pool == name)
        compute_fair_shares(list(states.values()), total_slots)

    def start_one(pool_name: str) -> None:
        nonlocal seq, slots_free
        # FIFO among the pool's arrived operations.
        candidates = [oid for oid, n in queued.items()
                      if n > 0 and op_index[oid].pool == pool_name]
        oid = min(candidates,
                  key=lambda o: (op_index[o].arrival, o))
        queued[oid] -= 1
        first_start.setdefault(oid, t)
        seq += 1
        heapq.heappush(running,
                       (t + op_index[oid].job_duration, seq, oid, t))
        slots_free -= 1

    while t <= max_virtual_time:
        next_arrival = arrivals[i].arrival if i < len(arrivals) \
            else float("inf")
        next_finish = running[0][0] if running else float("inf")
        t_next = min(next_arrival, next_finish)
        if t_next == float("inf"):
            break
        for name in usage_integral:
            usage_integral[name] += pool_running(name) * (t_next - t)
        t = t_next
        while i < len(arrivals) and arrivals[i].arrival <= t:
            op = arrivals[i]
            queued[op.id] = queued.get(op.id, 0) + op.n_jobs
            unfinished[op.id] = unfinished.get(op.id, 0) + op.n_jobs
            i += 1
        while running and running[0][0] <= t:
            _, _, oid, _ = heapq.heappop(running)
            slots_free += 1
            unfinished[oid] -= 1
            if unfinished[oid] == 0 and queued.get(oid, 0) == 0:
                completions[oid] = t
        # Scheduling pass.
        preempted_this_pass = 0
        while True:
            refresh_states()
            if slots_free > 0:
                chosen = pick_pool(list(states.values()))
                if chosen is None:
                    break
                start_one(chosen.name)
                continue
            if not preemption or preempted_this_pass >= total_slots:
                break
            victim_pool = find_preemptable(list(states.values()))
            if victim_pool is None:
                break
            # Evict the victim pool's newest job; its work is requeued
            # whole (the live scheduler reschedules preempted jobs at
            # attempt+1 — lost progress is the cost of fairness).
            victims = [entry for entry in running
                       if op_index[entry[2]].pool == victim_pool.name]
            entry = max(victims, key=lambda e: (e[3], e[1]))
            running.remove(entry)
            heapq.heapify(running)
            queued[entry[2]] += 1
            slots_free += 1
            preempted_this_pass += 1
        samples.append((t, {name: pool_running(name)
                            for name in states}))
        preemptions_total += preempted_this_pass
    wait_times = {oid: first_start.get(oid, float("inf")) -
                  op_index[oid].arrival for oid in op_index}
    return SimResult(
        makespan=t, completions=completions, wait_times=wait_times,
        pool_usage_integral=usage_integral,
        preemptions=preemptions_total, samples=samples)
