"""Continuous queries (ISSUE 13): incremental materialized views over
ordered tablets.

Covers: incremental delta-merge correctness vs a full-recompute oracle
(aggregates incl. avg/argmin state decomposition, DISTINCT, plain
selects keyed by $row_index), the exactly-once 2PC protocol under seeded
crash-once schedules + daemon restarts, ordered-cursor edge cases the
tail loop surfaced (empty micro-batches, cursor at/below the trim
boundary, concurrent trim-vs-read), daemon lifecycle + dynamic-config
pause/resume, compile-once steady state, per-view accounting + the
view-lag burn-rate SLO, and the driver/CLI/monitoring surfaces.
"""

import json
import threading
import urllib.request

import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query.views import (
    ViewRefresher,
    load_view,
    prepare_incremental,
    build_view_plan,
)
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.server.view_daemon import ViewDaemon, views_snapshot
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.failpoints import InjectedCrash

SRC_SCHEMA = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "double")])

AGG_QUERY = ("g, sum(v) AS s, count(*) AS c, avg(v) AS a "
             "FROM [{src}] GROUP BY g")


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


@pytest.fixture(autouse=True)
def _fresh_views_config():
    yield
    yt_config.set_views_config(None)


def make_source(client, path="//src", n_rows=0):
    client.create("table", path, recursive=True,
                  attributes={"schema": SRC_SCHEMA, "dynamic": True})
    client.mount_table(path)
    if n_rows:
        push(client, path, range(n_rows))
    return path


def push(client, path, ks):
    client.push_queue(path, [
        {"k": k, "g": k % 5, "v": float((k * 7) % 23)} for k in ks])


def canon(rows):
    def norm(v):
        return round(v, 9) if isinstance(v, float) else v
    return sorted(tuple((name, norm(value)) for name, value in
                        sorted(r.items())) for r in rows)


def view_rows(client, spec, columns):
    return client.select_rows(f"{columns} FROM [{spec['target']}]")


# --- incremental correctness --------------------------------------------------


def test_agg_view_delta_merge_matches_oracle(client):
    src = make_source(client, n_rows=23)
    spec = client.create_materialized_view(
        "agg", AGG_QUERY.format(src=src), batch_rows=7)
    report = client.refresh_view("agg")
    assert report["rows_in"] == 23 and report["lag_rows"] == 0
    oracle_q = AGG_QUERY.format(src=src)
    assert canon(view_rows(client, spec, "g, s, c, a")) == \
        canon(client.select_rows(oracle_q))
    # Incremental: a second ingest delta-merges into the stored states
    # (avg via its (sum, count) decomposition), not a recompute.
    push(client, src, range(100, 137))
    report = client.refresh_view("agg")
    assert report["rows_in"] == 37
    assert canon(view_rows(client, spec, "g, s, c, a")) == \
        canon(client.select_rows(oracle_q))


def test_argminmax_view_keeps_by_state(client):
    src = make_source(client, n_rows=19)
    q = ("g, argmin(k, v) AS ak, argmax(k, v) AS xk, min(v) AS mv "
         f"FROM [{src}] GROUP BY g")
    spec = client.create_materialized_view("am", q, batch_rows=6)
    client.refresh_view("am")
    push(client, src, range(200, 231))
    client.refresh_view("am")
    assert canon(view_rows(client, spec, "g, ak, xk, mv")) == \
        canon(client.select_rows(q))
    # The `__b` comparison state is persisted alongside the value, so
    # later merges could still compare.
    stored = client.select_rows(f"ak__b, xk__b FROM [{spec['target']}]")
    assert all(r["ak__b"] is not None for r in stored)


def test_distinct_view(client):
    src = make_source(client, n_rows=17)
    q = f"g FROM [{src}] GROUP BY g"
    spec = client.create_materialized_view("dst", q, batch_rows=4)
    client.refresh_view("dst")
    push(client, src, range(40, 53))
    client.refresh_view("dst")
    assert canon(view_rows(client, spec, "g")) == \
        canon(client.select_rows(q))


def test_plain_view_filters_and_projects(client):
    src = make_source(client, n_rows=29)
    q = f"k, v, v * 2.0 AS v2 FROM [{src}] WHERE v > 5.0"
    spec = client.create_materialized_view("plain", q, batch_rows=8)
    client.refresh_view("plain")
    push(client, src, range(300, 321))
    client.refresh_view("plain")
    assert canon(view_rows(client, spec, "k, v, v2")) == \
        canon(client.select_rows(q))
    # Upserts key on the source $row_index: replaying the same batch
    # (simulated by a manual re-insert) cannot duplicate rows.
    assert spec["target"].startswith("//sys/views/plain/")


def test_all_filtered_batch_still_advances_cursor(client):
    src = make_source(client, n_rows=9)
    # v is in [0, 23); nothing matches.
    spec = client.create_materialized_view(
        "nil", f"k, v FROM [{src}] WHERE v > 1000.0", batch_rows=4)
    report = client.refresh_view("nil")
    assert report["rows_in"] == 9 and report["rows_out"] == 0
    assert client.get_view("nil")["offset"] == 9
    assert view_rows(client, spec, "k, v") == []


def test_view_query_validation(client):
    src = make_source(client)
    other = make_source(client, "//dim")
    cases = [
        f"k FROM [{src}] ORDER BY k LIMIT 5",
        f"k FROM [{src}] LIMIT 5",
        f"g, cardinality(k) AS d FROM [{src}] GROUP BY g",
        f"g, sum(v) AS s FROM [{src}] GROUP BY g HAVING sum(v) > 1.0",
        f"k, sum(v) OVER (PARTITION BY g) AS w FROM [{src}]",
        f"g, sum(v) / count(*) AS r FROM [{src}] GROUP BY g",
        f"k, g FROM [{src}] JOIN [{other}] USING k",
    ]
    for query in cases:
        with pytest.raises(YtError) as err:
            client.create_materialized_view("bad", query)
        # Joins of two ordered tables may already die in the builder
        # (both sides carry $row_index) — any rejection is fine.
        assert err.value.code in (EErrorCode.QueryUnsupported,
                                  EErrorCode.QueryParseError,
                                  EErrorCode.QueryTypeError), query
    # Sorted (non-queue) source is rejected.
    client.create("table", "//sorted", recursive=True, attributes={
        "schema": TableSchema.make([("k", "int64", "ascending"),
                                    ("v", "int64")], unique_keys=True),
        "dynamic": True})
    client.mount_table("//sorted")
    with pytest.raises(YtError):
        client.create_materialized_view("bad", "k, v FROM [//sorted]")
    # Duplicate names are rejected.
    client.create_materialized_view("dup", f"k, v FROM [{src}]")
    with pytest.raises(YtError):
        client.create_materialized_view("dup", f"k, v FROM [{src}]")


# --- exactly-once under injected crashes --------------------------------------


def _drive_until_drained(client, name, max_crashes=64):
    """Run the refresh loop like a crashy daemon would: every
    InjectedCrash kills the 'process' (the refresher) and a fresh one
    resumes from the committed offsets."""
    crashes = 0
    refresher = ViewRefresher(client, load_view(client, name))
    while True:
        try:
            result = refresher.refresh_once()
            if result.empty:
                return crashes
        except InjectedCrash:
            crashes += 1
            assert crashes <= max_crashes, "crash loop did not converge"
            refresher = ViewRefresher(client, load_view(client, name))


@pytest.mark.parametrize("site", ["views.batch_execute", "views.commit"])
@pytest.mark.parametrize("seed", [11, 22])
def test_exactly_once_across_crashes(client, site, seed):
    """The chaos soak: crash-once schedules at both failpoint sites —
    including BETWEEN the staged target write and the offset commit —
    must leave both an aggregate and a plain view bit-identical to the
    full-recompute oracle after restarts."""
    src = make_source(client, n_rows=31)
    agg = client.create_materialized_view(
        "agg", AGG_QUERY.format(src=src), batch_rows=6)
    plain_q = f"k, v FROM [{src}] WHERE v > 4.0"
    plain = client.create_materialized_view("plain", plain_q,
                                            batch_rows=6)
    crashes = 0
    with failpoints.active(f"{site}=crash-once:times=2", seed=seed):
        crashes += _drive_until_drained(client, "agg")
        crashes += _drive_until_drained(client, "plain")
    push(client, src, range(500, 541))
    with failpoints.active(f"{site}=crash-once:times=2", seed=seed + 1):
        crashes += _drive_until_drained(client, "agg")
        crashes += _drive_until_drained(client, "plain")
    assert crashes >= 2, "the schedule never fired — proves nothing"
    assert canon(view_rows(client, agg, "g, s, c, a")) == \
        canon(client.select_rows(AGG_QUERY.format(src=src)))
    assert canon(view_rows(client, plain, "k, v")) == \
        canon(client.select_rows(plain_q))


def test_view_failpoint_sites_fired():
    """Coverage gate (mirrors test_chaos_soak's): both view sites must
    actually TRIGGER in the chaos runs above — dead sites prove
    nothing."""
    counters = failpoints.counters()
    triggered = {site: counters.get(site, {}).get("triggers", 0)
                 for site in ("views.batch_execute", "views.commit")}
    if not any(triggered.values()):
        pytest.skip("chaos tests did not run in this session")
    assert all(triggered.values()), \
        f"view failpoint sites never fired: {triggered}"


def test_refresher_restart_resumes_from_committed_offset(client):
    src = make_source(client, n_rows=12)
    client.create_materialized_view(
        "r", AGG_QUERY.format(src=src), batch_rows=5)
    first = ViewRefresher(client, load_view(client, "r"))
    first.refresh_once()            # one batch of 5, then "die"
    assert client.get_view("r")["offset"] == 5
    second = ViewRefresher(client, load_view(client, "r"))
    report = second.refresh()
    assert report["rows_in"] == 7   # resumed at 5, not 0
    assert client.get_view("r")["lag_rows"] == 0


def test_create_rejects_zero_batch_rows_and_recovers_wedged_names(client):
    src = make_source(client, n_rows=2)
    with pytest.raises(YtError) as err:
        client.create_materialized_view("z", f"k, v FROM [{src}]",
                                        batch_rows=0)
    assert err.value.code == EErrorCode.InvalidConfig
    # A half-created registry node (failure before @view_spec landed)
    # must not wedge the name.
    client.create("map_node", "//sys/views/z", recursive=True)
    spec = client.create_materialized_view("z", f"k, v FROM [{src}]")
    assert spec["state"] == "running"
    # A failed create rolls its target back (name AND target reusable).
    with pytest.raises(YtError):
        client.create_materialized_view(
            "z2", f"k FROM [{src}] LIMIT 1", target="//z2target")
    assert not client.exists("//z2target")
    assert not client.exists("//sys/views/z2/@view_spec")


def test_daemon_and_cli_survive_one_broken_view(client, capsys):
    from ytsaurus_tpu.cli import run
    src = make_source(client, n_rows=6)
    client.create_materialized_view("ok", f"k, v FROM [{src}]",
                                    batch_rows=4)
    client.create_materialized_view("broken", f"k, v FROM [{src}]")
    # Corrupt the broken view's spec (hand-edited Cypress) so loading
    # it raises a NON-YtError (KeyError): the daemon pass must record
    # it and still refresh the healthy view; the CLI listing must still
    # render the registry.
    client.set("//sys/views/broken/@view_spec", {"name": "broken"})
    daemon = ViewDaemon(client)
    report = daemon.step()
    assert "error" in report["broken"]
    assert report["ok"]["lag_rows"] == 0 and report["ok"]["rows_in"] == 6
    assert run(["view", "list"], client=client) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "broken" in out


def test_stale_concurrent_writer_cannot_rewind_cursor(client):
    """Review finding: a second writer holding an already-superseded
    batch must NOT commit it — the optimistic cursor check inside the
    commit window rejects the stale delta, so the view never
    double-applies rows."""
    src = make_source(client, n_rows=20)
    spec = client.create_materialized_view(
        "race", f"g, sum(v) AS s, count(*) AS c FROM [{src}] GROUP BY g",
        batch_rows=4)
    stale = ViewRefresher(client, load_view(client, "race"))
    # The stale writer computes its first batch's delta... then stalls.
    rows = client.pull_queue(src, offset=0, limit=4)
    upserts = stale._compute_upserts(rows)
    # Meanwhile the live refresher drains the whole queue.
    ViewRefresher(client, load_view(client, "race")).refresh()
    assert client.get_view("race")["offset"] == 20
    # The stale commit must be rejected (and counted as a conflict),
    # leaving the view on the oracle.
    with pytest.raises(YtError) as err:
        stale._commit(upserts, 4, base_offset=0)
    assert err.value.code == EErrorCode.TransactionLockConflict
    assert client.get_view("race")["offset"] == 20
    assert canon(view_rows(client, spec, "g, s, c")) == \
        canon(client.select_rows(
            f"g, sum(v) AS s, count(*) AS c FROM [{src}] GROUP BY g"))


def test_remove_view_keeps_external_target_and_survives_dead_source(client):
    """Review findings: an EXTERNAL target must outlive the view unless
    drop_target; removing a view whose source table was already dropped
    must succeed (best-effort unregister)."""
    src = make_source(client, n_rows=6)
    client.create_materialized_view(
        "ext", f"k, v FROM [{src}]", target="//kept/target")
    client.refresh_view("ext")
    client.remove_view("ext")                 # drop_target=False
    assert client.exists("//kept/target")
    assert client.select_rows("k, v FROM [//kept/target]")
    # Source dropped out from under the second view: removal still works.
    client.create_materialized_view("orphan", f"k, v FROM [{src}]")
    client.unmount_table(src)
    client.remove(src, recursive=True)
    client.remove_view("orphan")
    assert client.list_views() == []


# --- ordered-cursor edge cases (ISSUE 13 satellite) ---------------------------


def test_empty_micro_batches_are_cheap_noops(client):
    src = make_source(client, n_rows=4)
    client.create_materialized_view(
        "e", AGG_QUERY.format(src=src), batch_rows=8)
    refresher = ViewRefresher(client, load_view(client, "e"))
    assert refresher.refresh_once().rows_in == 4
    for _ in range(3):
        result = refresher.refresh_once()
        assert result.empty and result.offset == 4
    assert client.get_view("e")["offset"] == 4


def test_cursor_at_trim_boundary(client):
    src = make_source(client, n_rows=20)
    client.create_materialized_view(
        "t", AGG_QUERY.format(src=src), batch_rows=5)
    refresher = ViewRefresher(client, load_view(client, "t"))
    refresher.refresh_once()                 # cursor at 5
    client.trim_rows(src, 5)                 # trim EXACTLY to the cursor
    result = refresher.refresh_once()
    assert result.rows_in == 5 and result.trim_skipped == 0
    refresher.refresh()
    assert client.get_view("t")["lag_rows"] == 0


def test_cursor_below_trim_boundary_skips_forward(client):
    src = make_source(client, n_rows=20)
    client.create_materialized_view(
        "skip", f"k, v FROM [{src}]", batch_rows=5)
    refresher = ViewRefresher(client, load_view(client, "skip"))
    refresher.refresh_once()                 # cursor at 5
    client.trim_rows(src, 12)                # operator trim past cursor
    result = refresher.refresh_once()
    assert result.trim_skipped == 7
    assert result.rows_in == 5 and result.offset == 17
    refresher.refresh()
    status = client.get_view("skip")
    assert status["offset"] == 20 and status["lag_rows"] == 0


def test_pull_consumer_trim_gap_regression(client):
    """pull_consumer used to return the STALE offset when the trim
    boundary passed it and nothing was live — parking the consumer
    below trimmed_count forever (surfaced by the view tail loop)."""
    src = make_source(client, n_rows=6)
    client.register_queue_consumer(src, "//c")
    rows, next_off = client.pull_consumer("//c", src)
    assert next_off == 6
    client.advance_consumer("//c", src, 2)
    client.trim_rows(src, 6)                 # everything trimmed
    rows, next_off = client.pull_consumer("//c", src)
    assert rows == []
    assert next_off == 6, "cursor must land on the trim boundary"
    client.advance_consumer("//c", src, next_off)   # and be committable


def test_concurrent_trim_vs_tail(client):
    """Agent-style trimming (gated on the view's VITAL consumer) racing
    the tail loop: no errors, no lost rows, view == python oracle."""
    from ytsaurus_tpu.server.queue_agent import QueueAgent
    src = make_source(client)
    client.create_materialized_view(
        "ct", f"g, sum(v) AS s, count(*) AS c FROM [{src}] GROUP BY g",
        batch_rows=16)
    refresher = ViewRefresher(client, load_view(client, "ct"))
    agent = QueueAgent(client)
    stop = threading.Event()
    errors = []

    def trimmer():
        while not stop.is_set():
            try:
                agent.trim_queue(src)
            except YtError as err:           # pragma: no cover
                errors.append(err)

    thread = threading.Thread(target=trimmer)
    thread.start()
    try:
        expected_s: dict = {}
        expected_c: dict = {}
        for wave in range(6):
            ks = range(wave * 50, wave * 50 + 50)
            push(client, src, ks)
            for k in ks:
                g = k % 5
                expected_s[g] = expected_s.get(g, 0.0) + \
                    float((k * 7) % 23)
                expected_c[g] = expected_c.get(g, 0) + 1
            refresher.refresh()
    finally:
        stop.set()
        thread.join(timeout=10)
    assert not errors
    got = {r["g"]: r for r in view_rows(client, {"target":
           load_view(client, "ct").target}, "g, s, c")}
    assert {g: (round(r["s"], 6), r["c"]) for g, r in got.items()} == \
        {g: (round(expected_s[g], 6), expected_c[g]) for g in expected_s}
    # The vital consumer gates trimming: nothing was trimmed past the
    # committed cursor, so nothing was lost.
    (tablet,) = client._mounted_tablets(src)
    assert tablet.trimmed_count <= client.get_view("ct")["offset"]


# --- 8-device mesh dual-check -------------------------------------------------


def test_view_dual_checked_against_mesh_oracle(client, mesh8):
    """The recompute oracle for an aggregate view, executed BOTH as the
    local single-chunk plan and as the 8-device SPMD distributed plan
    (the whole-plan/shuffle ladder), must match the incrementally
    maintained target."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        coordinate_distributed,
    )
    src = make_source(client, n_rows=64)
    q = AGG_QUERY.format(src=src)
    spec = client.create_materialized_view("m", q, batch_rows=16)
    client.refresh_view("m")
    push(client, src, range(1000, 1041))
    client.refresh_view("m")

    plan = build_view_plan(client, q)
    rows = client.pull_queue(src, 0)
    shards = [rows[i::8] for i in range(8)]
    chunks = [ColumnarChunk.from_rows(plan.schema, part)
              for part in shards if part]
    mesh_oracle = coordinate_distributed(
        plan, mesh8, chunks,
        evaluator=DistributedEvaluator(mesh8)).to_rows()
    local_oracle = client.select_rows(q)
    got = canon(view_rows(client, spec, "g, s, c, a"))
    assert got == canon(local_oracle)
    assert got == canon(mesh_oracle)


# --- compile-once steady state ------------------------------------------------


def test_steady_state_refresh_is_compile_free(client):
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    src = make_source(client, n_rows=96)
    client.create_materialized_view(
        "cc", AGG_QUERY.format(src=src), batch_rows=32)
    refresher = ViewRefresher(client, load_view(client, "cc"))
    refresher.refresh()                      # warmup: compiles happen here
    obs = get_compile_observatory()
    before = obs.totals()
    for wave in range(3):
        push(client, src, range(2000 + wave * 32, 2000 + wave * 32 + 32))
        refresher.refresh()
    after = obs.totals()
    assert after["misses"] == before["misses"], \
        "steady-state refresh must replay cached programs only"
    assert after["hits"] > before["hits"]


# --- daemon lifecycle + dynamic config ----------------------------------------


def _wait(predicate, timeout=30.0):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_view_daemon_tails_pauses_and_resumes(client):
    src = make_source(client, n_rows=10)
    client.create_materialized_view(
        "d", AGG_QUERY.format(src=src), batch_rows=8)
    daemon = ViewDaemon(client).start()
    try:
        assert _wait(lambda: client.get_view("d")["lag_rows"] == 0)
        # Registry pause (yt view pause): the daemon skips the view.
        client.pause_view("d")
        # Two FULL passes after the pause: a pass in flight when the
        # attribute landed may still run with the pre-pause spec.
        settled = daemon.passes + 2
        assert _wait(lambda: daemon.passes >= settled)
        assert daemon.snapshot()["views"]["d"]["paused"]
        push(client, src, range(50, 60))
        assert client.get_view("d")["lag_rows"] == 10
        client.resume_view("d")
        assert _wait(lambda: client.get_view("d")["lag_rows"] == 0)

        # Dynamic-config pause: a config document patch flips `paused`
        # through the DynamicConfigManager subscriber path.
        patches = [{"paused": ["d"]}]
        manager = yt_config.DynamicConfigManager(
            fetch=lambda: patches[0],
            base_config=yt_config.ViewsConfig())
        manager.subscribe(daemon.apply_config)
        assert manager.poll_once()
        settled = daemon.passes + 2
        assert _wait(lambda: daemon.passes >= settled)
        assert daemon.snapshot()["views"]["d"]["paused"]
        push(client, src, range(70, 76))
        assert client.get_view("d")["lag_rows"] == 6
        patches[0] = {"paused": []}
        assert manager.poll_once()
        assert _wait(lambda: client.get_view("d")["lag_rows"] == 0)
    finally:
        daemon.stop()
    assert canon(client.select_rows(
        f"g, s, c, a FROM [{load_view(client, 'd').target}]")) == \
        canon(client.select_rows(AGG_QUERY.format(src=src)))


def test_daemon_restart_resumes_from_committed_offsets(client):
    src = make_source(client, n_rows=40)
    client.create_materialized_view(
        "dr", AGG_QUERY.format(src=src), batch_rows=16)
    first = ViewDaemon(client)
    first.step()
    assert client.get_view("dr")["lag_rows"] == 0
    push(client, src, range(600, 625))
    # A brand-new daemon (fresh process analog) sees only the delta.
    second = ViewDaemon(client)
    report = second.step()
    assert report["dr"]["rows_in"] == 25
    assert canon(client.select_rows(
        f"g, s, c, a FROM [{load_view(client, 'dr').target}]")) == \
        canon(client.select_rows(AGG_QUERY.format(src=src)))


# --- accounting + SLO ---------------------------------------------------------


def test_refresh_folds_into_pool_accounting(client):
    from ytsaurus_tpu.query.accounting import ResourceAccountant
    src = make_source(client, n_rows=12)
    spec = client.create_materialized_view(
        "acct", AGG_QUERY.format(src=src), pool="analytics",
        batch_rows=6)
    accountant = ResourceAccountant()
    refresher = ViewRefresher(client, load_view(client, "acct"),
                              accountant=accountant)
    refresher.refresh()
    snapshot = accountant.snapshot()
    usage = snapshot["by_pool"]["analytics"]
    assert usage["view_batches"] == 2
    assert usage["view_rows"] == 12 and usage["rows_read"] == 12
    assert usage["wall_seconds"] > 0
    (record,) = snapshot["records"]
    assert (record["pool"], record["user"]) == ("analytics",
                                                "view-daemon")
    assert spec["pool"] == "analytics"


def test_view_lag_slo_burn_rate_alert(client):
    """The view-lag SLO spec over the telemetry rings: sustained
    freshness-lag breaches fire the burn-rate alert; draining the
    backlog resolves it."""
    from ytsaurus_tpu.utils.profiling import MetricsHistory, get_registry
    from ytsaurus_tpu.utils.slo import SloTracker
    yt_config.set_views_config(yt_config.ViewsConfig(lag_slo_rows=4))
    src = make_source(client, n_rows=0)
    client.create_materialized_view(
        "slo", f"k, v FROM [{src}]", batch_rows=2)
    refresher = ViewRefresher(client, load_view(client, "slo"))
    hist = MetricsHistory(registry=get_registry(), fine_capacity=720,
                          sample_period=10.0)
    tracker = SloTracker(
        yt_config.TelemetryConfig(slos={
            "view_lag": yt_config.view_lag_slo(
                view="slo", objective=0.9, burn_threshold=2.0)}),
        history=hist)
    t = 0.0
    for _ in range(40):                      # healthy: drained each tick
        push(client, src, range(2))
        refresher.refresh()
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    assert tracker.evaluate(now=t)["active_alerts"] == []
    push(client, src, range(400))            # backlog storm
    for _ in range(30):                      # one 2-row batch per tick:
        refresher.refresh_once()             # lag stays >> objective
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    snap = tracker.evaluate(now=t)
    (alert,) = snap["active_alerts"]
    assert alert["slo"] == "view_lag" and alert["state"] == "firing"
    refresher.refresh()                      # drain fully
    for _ in range(40):
        push(client, src, range(2))
        refresher.refresh()
        t = hist.sample_once(t + 10.0)
        tracker.evaluate(now=t)
    assert tracker.evaluate(now=t)["active_alerts"] == []


# --- driver / CLI / monitoring ------------------------------------------------


def test_driver_and_cli_verbs(client, capsys):
    from ytsaurus_tpu.cli import run
    from ytsaurus_tpu.driver import Driver
    src = make_source(client, n_rows=8)
    driver = Driver(client)
    spec = driver.execute("create_materialized_view", {
        "name": "cli", "query": AGG_QUERY.format(src=src),
        "batch_rows": 4})
    assert spec["state"] == "running"
    assert driver.execute("list_views", {}) == ["cli"]
    report = driver.execute("refresh_view", {"name": "cli"})
    assert report["rows_in"] == 8
    status = driver.execute("get_view", {"name": "cli"})
    assert status["lag_rows"] == 0
    assert driver.execute("pause_view",
                          {"name": "cli"})["state"] == "paused"
    assert driver.execute("resume_view",
                          {"name": "cli"})["state"] == "running"

    assert run(["view", "list"], client=client) == 0
    out = capsys.readouterr().out
    assert "cli" in out and "running" in out
    assert run(["view", "show", "cli"], client=client) == 0
    shown = json.loads(capsys.readouterr().out)
    assert shown["offset"] == 8
    assert run(["view", "pause", "cli"], client=client) == 0
    capsys.readouterr()
    assert load_view(client, "cli").state == "paused"
    assert run(["view", "resume", "cli"], client=client) == 0
    capsys.readouterr()
    assert run(["view", "refresh", "cli"], client=client) == 0
    capsys.readouterr()
    # remove drops the registry node and unregisters the consumer.
    driver.execute("remove_view", {"name": "cli", "drop_target": True})
    assert driver.execute("list_views", {}) == []
    regs = client.get(src + "/@registrations")
    assert regs == {}


def test_views_monitoring_endpoint_and_orchid(client):
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import default_orchid
    src = make_source(client, n_rows=6)
    client.create_materialized_view(
        "mon", AGG_QUERY.format(src=src), batch_rows=4)
    daemon = ViewDaemon(client)
    daemon.step()
    snapshots = [s for s in views_snapshot() if "mon" in s["views"]]
    assert snapshots and snapshots[0]["views"]["mon"]["lag_rows"] == 0
    assert snapshots[0]["views"]["mon"]["daemon"]["rows_in"] == 6

    server = MonitoringServer(orchid=default_orchid())
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://{server.address}/views", timeout=10) as resp:
            payload = json.loads(resp.read())
        ours = [d for d in payload["daemons"] if "mon" in d["views"]]
        assert ours and ours[0]["views"]["mon"]["offset"] == 6
        orchid_view = server.orchid.get("/views")
        assert any("mon" in d["views"] for d in orchid_view["daemons"])
    finally:
        server.stop()
    # Freshness rides the target node for plain readers.
    freshness = client.get(
        load_view(client, "mon").target + "/@view_freshness")
    assert freshness["offset"] == 6


def test_incremental_plan_shapes(client):
    """White-box: the decomposition persists exactly the states the
    merge needs."""
    src = make_source(client)
    plan = build_view_plan(
        client, f"g, avg(v) AS a FROM [{src}] GROUP BY g")
    inc = prepare_incremental(plan)
    assert inc.aggregating
    assert [c.name for c in inc.target_schema] == ["g", "a", "a__s",
                                                   "a__c"]
    assert inc.target_schema.key_column_names == ["g"]
    state_names = [c.name for c in inc.state_schema]
    assert state_names == ["g", "a__s", "a__c"]
    plain = prepare_incremental(
        build_view_plan(client, f"k, v FROM [{src}] WHERE v > 1.0"))
    assert not plain.aggregating
    assert plain.target_schema.key_column_names == ["$row_index"]
