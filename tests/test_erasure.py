"""Erasure coding tests (ref model: library/cpp/erasure unittests)."""

import os

import numpy as np
import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.chunks.erasure import get_erasure_codec
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.schema import TableSchema


def test_rs63_roundtrip_no_erasures():
    codec = get_erasure_codec("rs_6_3")
    blob = bytes(range(256)) * 41 + b"tail"
    parts = codec.encode(blob)
    assert len(parts) == 9
    assert codec.decode(parts, len(blob)) == blob


@pytest.mark.parametrize("lost", [
    (0,), (5,), (6,), (8,), (0, 1), (0, 6), (7, 8), (0, 3, 8), (1, 2, 4),
    (6, 7, 8), (0, 1, 2),
])
def test_rs63_repairs_any_three_erasures(lost):
    codec = get_erasure_codec("rs_6_3")
    rng = np.random.default_rng(7)
    blob = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    parts = list(codec.encode(blob))
    for i in lost:
        parts[i] = None
    assert codec.decode(parts, len(blob)) == blob


def test_rs63_four_erasures_fail():
    codec = get_erasure_codec("rs_6_3")
    parts = list(codec.encode(b"x" * 600))
    for i in (0, 2, 6, 8):
        parts[i] = None
    with pytest.raises(YtError):
        codec.decode(parts, 600)


def test_store_erasure_chunk_survives_part_loss(tmp_path):
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("k", "int64"), ("s", "string")])
    chunk = ColumnarChunk.from_rows(
        schema, [(i, f"row-{i}") for i in range(500)])
    cid = store.write_chunk(chunk, erasure="rs_6_3")
    assert store.exists(cid)
    assert store.list_chunks() == [cid]
    # Destroy three arbitrary parts (two data + one parity).
    for i in (1, 4, 7):
        os.unlink(store._part_path(cid, i))
    back = store.read_chunk(cid)
    assert back.to_rows() == chunk.to_rows()
    # A fourth loss is fatal.
    os.unlink(store._part_path(cid, 0))
    with pytest.raises(YtError):
        store.read_chunk(cid)
    store.remove_chunk(cid)
    assert not store.exists(cid)


def test_small_blob_erasure():
    codec = get_erasure_codec("rs_3_2")
    blob = b"abc"
    parts = list(codec.encode(blob))
    parts[0] = None
    parts[2] = None
    assert codec.decode(parts, 3) == blob
