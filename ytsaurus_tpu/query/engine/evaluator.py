"""The query evaluator: plan + chunks → result chunk, with a compile cache.

Analog of TEvaluator::Run (library/query/engine/evaluator.cpp:40-120): looks
up / populates a compiled-program cache keyed by (plan fingerprint, capacity
bucket, binding shapes) — the XLA counterpart of the reference's LLVM image
cache keyed by llvm::FoldingSet fingerprint (engine_api/cg_cache.h) — then
runs the program over the chunk's planes.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Mapping, Optional, Sequence

import jax
import numpy as np

from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, concat_chunks
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.parameterize import plan_fingerprint
from ytsaurus_tpu.query.engine.joins import execute_join
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import EValueType, TableSchema
from ytsaurus_tpu.utils.profiling import PoolSensorCache, Profiler
from ytsaurus_tpu.utils import sanitizers

# Process-wide compile-cache counters, tagged by the admitted query's
# pool (identity rides the CancellationToken): the steady-state
# compile-cache hit-rate SLO (ROADMAP item 1's acceptance gate, a
# TIME-SERIES claim) reads these from the telemetry history rings.
# The compilation observatory's per-fingerprint totals reconcile
# EXACTLY with these (same dispatch event increments both; the
# reconciliation is test-enforced).
_cache_counters = PoolSensorCache("/query/compile_cache",
                                  ("hits", "misses"))
_evictions_counter = Profiler("/query/compile_cache").counter("evictions")

# Execution-tier telemetry (ISSUE 18): which tier served each dispatch
# (interpreted vs compiled), background promotions, the promotion
# queue's depth, and prewarm compiles.  Deliberately a SEPARATE sensor
# family from /query/compile_cache — tier traffic must never perturb
# the hit/miss counters the compile-storm SLO and the observatory
# reconciliation are built on.
_tier_counters = PoolSensorCache("/query/tiers",
                                 ("interpreted", "compiled"))
_tiers_profiler = Profiler("/query/tiers")
_promotions_counter = _tiers_profiler.counter("promotions")
_prewarm_counter = _tiers_profiler.counter("prewarm_compiles")
_tier_queue_gauge = _tiers_profiler.gauge("queue_depth")

# Kernel-execution telemetry (ISSUE 19): dispatches whose string
# predicates ran on encoded dictionary planes vs the decoded fallback,
# and dispatches that armed buffer donation.
_kernel_profiler = Profiler("/query/kernels")
_encoded_scans_counter = _kernel_profiler.counter("encoded_scans")
_decoded_fallbacks_counter = _kernel_profiler.counter("decoded_fallbacks")
_donated_buffers_counter = _kernel_profiler.counter("donated_buffers")


def _flat_notes(structure_key) -> "set[str]":
    """Leading tags of every bind-notebook note tuple nested anywhere in
    a structure key (("strlit", op, digest) -> "strlit")."""
    out: set[str] = set()

    def walk(node):
        if isinstance(node, tuple):
            if node and isinstance(node[0], str):
                out.add(node[0])
            for item in node:
                walk(item)

    walk(structure_key)
    return out

# Buffer donation (ISSUE 19): XLA reuses donated input buffers for
# outputs of matching shape, halving peak residency for chunk-sized
# temporaries.  CPU backends ignore donation (it is inert there) but
# warn per call — suppress exactly that message so the armed path stays
# quiet on the CPU bench/test floor.
import warnings as _warnings

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def _jit_run(run, donate_columns: bool = False):
    """jit a prepared `run` with ISSUE 19 buffer donation.

    `row_valid` (argnum 1) is always donatable: `chunk.row_valid` is a
    property that builds a fresh iota-compare plane per access, so every
    dispatch owns its copy and nothing reads it after the call.  The
    column planes (argnum 0) are donated only when the caller owns the
    chunk — a join-cascade intermediate built by this very dispatch —
    never for persistent table chunks (the compile-cache key carries the
    donation mode so the two executables cannot alias)."""
    from ytsaurus_tpu.config import compile_config
    if not compile_config().donate_buffers:
        return jax.jit(run)
    return jax.jit(run, donate_argnums=(0, 1) if donate_columns else (1,))


class CompileObservatory:
    """Per-fingerprint compile telemetry (ISSUE 8 tentpole, piece b).

    Every evaluator dispatch folds here: compile count + cumulative
    compile seconds per plan fingerprint (the "compile burn" `/compile`
    and `yt compile-cache top` rank by — Flare's adaptive-compilation
    feedback signal, arxiv 1703.08219), the shape-spectrum cardinality
    (distinct (capacity, binding-shape) programs one fingerprint
    compiled — an unbounded spectrum IS the recompilation pathology),
    evictions, and the LAST MISS CAUSE:

      new_fingerprint   this plan shape never compiled before
      new_shape         known shape, but a capacity bucket / binding
                        shape it never met (shape-spectrum growth)
      eviction          the exact program existed and was LRU-evicted
                        (the cache is too small for the working set)

    Optionally captures each compiled executable's XLA artifacts (HLO
    text + cost_analysis() FLOPs/bytes) behind
    `WorkloadConfig.capture_artifacts` — bounded, for debugging a hot
    fingerprint, not steady-state telemetry."""

    SHAPE_SET_CAP = 512

    def __init__(self):
        # guards: _fps, _artifacts, _evicted, hits_n, misses_n, evictions_n
        self._lock = sanitizers.register_lock(
            "evaluator.CompileObservatory._lock")
        self._fps: dict[str, dict] = {}
        self._artifacts: deque = deque(maxlen=64)
        # Bounded memory of evicted program keys: a re-miss on one is
        # cause=eviction, not cause=new_shape.
        self._evicted: "OrderedDict[tuple, None]" = OrderedDict()
        self.hits_n = 0
        self.misses_n = 0
        self.evictions_n = 0
        self.disk_hits_n = 0
        self.background_n = 0

    def _entry_locked(self, fp: str) -> dict:
        entry = self._fps.get(fp)
        if entry is None:
            entry = self._fps[fp] = {
                "compiles": 0, "hits": 0, "disk_hits": 0,
                "compile_seconds": 0.0,
                "shapes": set(), "shape_count": 0, "evictions": 0,
                "last_miss_cause": None, "last_compile_at": 0.0,
                "background_compiles": 0, "background_seconds": 0.0,
            }
        return entry

    def classify_miss(self, fp: str, key: tuple) -> str:
        with self._lock:
            if key in self._evicted:
                return "eviction"
            if fp in self._fps:
                return "new_shape"
            return "new_fingerprint"

    def observe_hit(self, fp: str) -> None:
        with self._lock:
            self.hits_n += 1
            self._entry_locked(fp)["hits"] += 1

    def observe_miss(self, fp: str, key: tuple, cause: str,
                     seconds: float) -> None:
        shape_sig = key[1:]
        with self._lock:
            self.misses_n += 1
            entry = self._entry_locked(fp)
            if cause == "disk_hit":
                # A memory miss served by the persistent tier: no fresh
                # compile burn — count it apart so `compiles` stays the
                # honest "programs actually built here" number.
                self.disk_hits_n += 1
                entry["disk_hits"] += 1
            else:
                entry["compiles"] += 1
                entry["compile_seconds"] += seconds
            entry["last_miss_cause"] = cause
            entry["last_compile_at"] = time.time()
            shapes = entry["shapes"]
            if shape_sig not in shapes:
                entry["shape_count"] += 1
                if len(shapes) < self.SHAPE_SET_CAP:
                    shapes.add(shape_sig)
            self._evicted.pop(key, None)

    def observe_background(self, fp: str, key: tuple,
                           seconds: float) -> None:
        """A DELIBERATE off-the-query-path compile (background
        promotion or capture-driven prewarm, ISSUE 18).  Kept in
        SEPARATE books from observe_miss: these are warm-up, not
        misses — they must not move the `/query/compile_cache`
        hit/miss counters the compile-storm SLO burns against, and the
        sensor<->observatory reconciliation (test-enforced) only holds
        if both keep counting the same dispatch events."""
        shape_sig = key[1:]
        with self._lock:
            self.background_n += 1
            entry = self._entry_locked(fp)
            entry["background_compiles"] += 1
            entry["background_seconds"] += seconds
            entry["last_miss_cause"] = "background_promotion"
            entry["last_compile_at"] = time.time()
            shapes = entry["shapes"]
            if shape_sig not in shapes:
                entry["shape_count"] += 1
                if len(shapes) < self.SHAPE_SET_CAP:
                    shapes.add(shape_sig)
            self._evicted.pop(key, None)

    def observe_eviction(self, key: tuple) -> None:
        with self._lock:
            self.evictions_n += 1
            if key[0] in self._fps:
                self._fps[key[0]]["evictions"] += 1
            self._evicted[key] = None
            while len(self._evicted) > 4096:
                self._evicted.popitem(last=False)

    def capture_artifact(self, fp: str, key: tuple, hlo: str,
                         cost: Optional[dict],
                         seconds: float) -> None:
        from ytsaurus_tpu.config import workload_config
        cfg = workload_config()
        cost = cost or {}
        artifact = {
            "fingerprint": fp,
            "capacity": key[1],
            "binding_shapes": repr(key[2]),
            "compile_seconds": round(seconds, 6),
            "flops": cost.get("flops"),
            "bytes_accessed": cost.get("bytes accessed",
                                       cost.get("bytes_accessed")),
            "hlo": hlo[:cfg.hlo_max_chars] if cfg.hlo_max_chars else "",
            "captured_at": time.time(),
        }
        with self._lock:
            if self._artifacts.maxlen != cfg.artifact_capacity:
                self._artifacts = deque(self._artifacts,
                                        maxlen=cfg.artifact_capacity)
            self._artifacts.append(artifact)

    # -- views -----------------------------------------------------------------

    def totals(self) -> dict:
        with self._lock:
            return {"hits": self.hits_n, "misses": self.misses_n,
                    "evictions": self.evictions_n,
                    "disk_hits": self.disk_hits_n,
                    "background_compiles": self.background_n,
                    "fingerprints": len(self._fps)}

    def top(self, n: int = 20,
            by: str = "compile_seconds") -> list[dict]:
        """Fingerprints ranked by compile burn (or any numeric field)."""
        with self._lock:
            rows = [{"fingerprint": fp,
                     **{k: v for k, v in entry.items() if k != "shapes"}}
                    for fp, entry in self._fps.items()]
        for row in rows:
            row["compile_seconds"] = round(row["compile_seconds"], 6)
        rows.sort(key=lambda r: (-float(r.get(by) or 0.0),
                                 r["fingerprint"]))
        return rows[:n] if n else rows

    def artifacts(self) -> list[dict]:
        with self._lock:
            return list(self._artifacts)

    def snapshot(self, top: int = 50) -> dict:
        from ytsaurus_tpu.query.engine.aot_cache import get_disk_cache
        disk = get_disk_cache()
        return {"totals": self.totals(),
                "fingerprints": self.top(top),
                # The persistent artifact tier's view (ISSUE 10): None
                # when the disk cache is disabled.
                "disk": disk.snapshot() if disk is not None else None,
                "artifacts": [{k: v for k, v in a.items() if k != "hlo"}
                              for a in self.artifacts()]}

    def reset(self) -> None:
        with self._lock:
            self._fps.clear()
            self._artifacts.clear()
            self._evicted.clear()
            self.hits_n = self.misses_n = self.evictions_n = 0
            self.disk_hits_n = 0
            self.background_n = 0


_observatory = CompileObservatory()


def get_compile_observatory() -> CompileObservatory:
    return _observatory


def _cost_analysis(compiled) -> Optional[dict]:
    """Normalized XLA cost analysis of a compiled executable: jax
    returns a dict on recent versions, a one-element list of dicts on
    older ones, and some backends return None."""
    try:
        cost = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 — backend-dependent, optional
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    return dict(cost) if isinstance(cost, dict) else None


class _PendingResult:
    """A DISPATCHED (not yet synchronized) plan execution: the output
    planes and the device-resident row count.  `finish()` performs the
    one device→host sync (`int(count)`) and wraps the chunk — callers
    fanning out over many shards dispatch every program first and
    synchronize once (`finish_all`), instead of stalling the dispatch
    queue on a host read per shard."""

    __slots__ = ("planes", "count", "output", "stats", "_t0", "_chunk",
                 "compile_seconds", "execution_tier")

    def __init__(self, planes, count, output, stats=None, t0=None):
        self.planes = planes
        self.count = count
        self.output = output
        self.stats = stats
        self._t0 = t0
        self.compile_seconds = 0.0
        self.execution_tier = "compiled"
        self._chunk: Optional[ColumnarChunk] = None

    def finish(self, host_count: Optional[int] = None) -> ColumnarChunk:
        import time as _time
        if self._chunk is None:
            if host_count is None:
                # The sanctioned host-sync point (jax pass): int(count)
                # below blocks on a device→host read — the sanitizer
                # flags it when it runs under a registered hot lock.
                # With host_count supplied, finish_all already did ONE
                # stacked transfer for the batch (noted there).
                sanitizers.note_host_sync("evaluator.finish")
            n = int(self.count if host_count is None else host_count)
            out_columns: dict[str, Column] = {}
            out_schema_cols = []
            for out_col, (data, valid) in zip(self.output, self.planes):
                out_schema_cols.append((out_col.name, out_col.type.value))
                out_columns[out_col.name] = Column(
                    type=out_col.type, data=data, valid=valid,
                    dictionary=out_col.vocab)
            out_schema = TableSchema.make(out_schema_cols)
            self._chunk = ColumnarChunk(schema=out_schema, row_count=n,
                                        columns=out_columns)
            if self.stats is not None and self._t0 is not None:
                self.stats.execute_time += _time.perf_counter() - self._t0
        return self._chunk


class _ReadyResult:
    """Already-materialized result (totals plans sync internally)."""

    __slots__ = ("_chunk",)
    count = None
    execution_tier = "compiled"

    def __init__(self, chunk: ColumnarChunk):
        self._chunk = chunk

    def finish(self, host_count: Optional[int] = None) -> ColumnarChunk:
        return self._chunk


def finish_all(pendings: Sequence) -> list[ColumnarChunk]:
    """Synchronize a batch of dispatched plans with ONE host transfer:
    the per-shard row counts cross device→host as a single stacked
    array instead of one blocking read per shard."""
    import jax.numpy as jnp
    open_ = [p for p in pendings
             if isinstance(p, _PendingResult) and p._chunk is None]
    host: dict[int, int] = {}
    if len(open_) > 1:
        # The one stacked transfer happens HERE; a single open pending
        # falls through to finish(), which notes its own sync.
        sanitizers.note_host_sync("evaluator.finish_all")
        counts = np.asarray(jnp.stack([p.count for p in open_]))
        host = {id(p): int(c) for p, c in zip(open_, counts)}
    return [p.finish(host_count=host.get(id(p))) for p in pendings]


class TierGovernor:
    """Per-fingerprint interpreter-tier roll-up (ISSUE 18 tentpole,
    piece b): interpreted run count and cumulative interpreted seconds
    per fingerprint — the promotion signal.  `note_interpreted` returns
    True exactly once per fingerprint, when the run count crosses the
    configured hot threshold, so the caller enqueues ONE background
    promotion; a dropped enqueue re-arms via `rearm` (promotion is an
    optimization, a full queue must not silently orphan a hot shape)."""

    CAP = 4096

    def __init__(self):
        # guards: _fps
        self._lock = sanitizers.register_lock(
            "evaluator.TierGovernor._lock")
        self._fps: "OrderedDict[str, dict]" = OrderedDict()

    def note_interpreted(self, fp: str, seconds: float,
                         threshold: int) -> bool:
        with self._lock:
            entry = self._fps.get(fp)
            if entry is None:
                entry = self._fps[fp] = {"runs": 0, "seconds": 0.0,
                                         "armed": True}
                while len(self._fps) > self.CAP:
                    self._fps.popitem(last=False)
            entry["runs"] += 1
            entry["seconds"] += seconds
            if entry["armed"] and entry["runs"] >= threshold:
                entry["armed"] = False
                return True
            return False

    def rearm(self, fp: str) -> None:
        with self._lock:
            entry = self._fps.get(fp)
            if entry is not None:
                entry["armed"] = True

    def runs(self, fp: str) -> int:
        with self._lock:
            entry = self._fps.get(fp)
            return entry["runs"] if entry else 0

    def snapshot(self) -> list[dict]:
        with self._lock:
            rows = [{"fingerprint": fp, "runs": e["runs"],
                     "interpreted_seconds": round(e["seconds"], 6)}
                    for fp, e in self._fps.items()]
        rows.sort(key=lambda r: (-r["runs"], r["fingerprint"]))
        return rows

    def reset(self) -> None:
        with self._lock:
            self._fps.clear()


class BackgroundCompiler:
    """Bounded off-thread promotion pipeline (ISSUE 18 tentpole, piece
    b): hot interpreted fingerprints compile HERE — single-flight per
    cache key, bounded queue (overflow drops, never blocks a serving
    thread), cache insert under the evaluator's cache lock — and the
    compiled program atomically replaces the interpreter mid-traffic:
    the very next dispatch of that key takes the memory-LRU hit path.

    `_lock` guards ONLY queue/bookkeeping state and is NEVER held
    across a compile or while taking the evaluator's cache lock, so the
    lock-order graph gains no edges from this thread."""

    IDLE_EXIT_SECONDS = 1.0

    def __init__(self, evaluator: "Evaluator"):
        self._evaluator = evaluator
        # guards: _queue, _queued, _promoted, _thread, compiled_n, dropped_n
        self._lock = sanitizers.register_lock(
            "evaluator.BackgroundCompiler._lock")
        self._queue: deque = deque()
        self._queued: set = set()
        # Fingerprints promoted but not yet observed by a serving
        # thread: the first compiled hit after promotion reports
        # execution_tier="promoted-midstream" (consume-once).
        self._promoted: set = set()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compiled_n = 0
        self.dropped_n = 0

    def enqueue(self, key: tuple, prepared, args,
                depth: int) -> str:
        """Returns "queued", "duplicate", or "full"."""
        with self._lock:
            if key in self._queued:
                return "duplicate"
            if len(self._queue) >= depth:
                self.dropped_n += 1
                return "full"
            self._queued.add(key)
            self._queue.append((key, prepared, args))
            _tier_queue_gauge.set(len(self._queue))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="background-compiler")
                self._thread.start()
        self._wake.set()
        return "queued"

    def consume_promoted(self, fp: str) -> bool:
        if not self._promoted:     # lock-free fast path: usually empty
            return False
        with self._lock:
            if fp in self._promoted:
                self._promoted.discard(fp)
                return True
        return False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def drain(self, timeout: float = 30.0) -> None:
        """Block until the queue is empty and no compile is in flight
        (tests + graceful shutdown; the serving path never calls it)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._queued:
                    return
            time.sleep(0.005)

    def snapshot(self) -> dict:
        with self._lock:
            return {"queue_depth": len(self._queue),
                    "compiled": self.compiled_n,
                    "dropped": self.dropped_n,
                    "pending_promoted_tags": len(self._promoted)}

    # -- worker ----------------------------------------------------------------

    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=self.IDLE_EXIT_SECONDS)
            self._wake.clear()
            while True:
                with self._lock:
                    item = self._queue.popleft() if self._queue else None
                    _tier_queue_gauge.set(len(self._queue))
                if item is None:
                    break
                try:
                    self._work(item)
                except Exception:   # noqa: BLE001 — promotion is an
                    # optimization; a failed compile must never kill
                    # the worker (the interpreter keeps serving, and
                    # _work's finally already released the key).
                    pass
            with self._lock:
                if not self._queue and not self._wake.is_set():
                    # Park: exit the thread; a later enqueue restarts
                    # one (bounded threads across idle evaluators).
                    self._thread = None
                    return

    def _work(self, item) -> None:
        key, prepared, args = item
        evaluator = self._evaluator
        try:
            with evaluator._cache_lock:
                done = key in evaluator._cache
            if not done:
                self._promote(key, prepared, args)
        finally:
            with self._lock:
                self._queued.discard(key)

    def _promote(self, key: tuple, prepared, args) -> None:
        import time as _time

        from ytsaurus_tpu.config import workload_config
        from ytsaurus_tpu.query.engine.aot_cache import (
            get_cluster_store, get_disk_cache)
        cfg = workload_config()
        t0 = _time.perf_counter()
        lowered = None
        jitted = _jit_run(prepared.run)
        try:
            lowered = jitted.lower(*args)
            fn = lowered.compile()
        except Exception:   # noqa: BLE001 — AOT is an optimization;
            # anything it cannot lower promotes through the jit
            # wrapper (the call below compiles it fused, off-thread).
            lowered = None
            fn = jitted
            fn(*args)
        seconds = _time.perf_counter() - t0
        if lowered is not None:
            disk = get_disk_cache()
            cluster = get_cluster_store()
            if disk is not None:
                disk.store(key, fn, key[0], seconds)
            if cluster is not None:
                cluster.publish(key, fn, key[0], seconds)
        with self._evaluator._cache_lock:
            self._evaluator._cache[key] = fn
            evicted_keys = []
            if cfg.compile_cache_capacity:
                while len(self._evaluator._cache) > \
                        cfg.compile_cache_capacity:
                    evicted_keys.append(
                        self._evaluator._cache.popitem(last=False)[0])
        for evicted_key in evicted_keys:
            _observatory.observe_eviction(evicted_key)
            _evictions_counter.increment()
        _observatory.observe_background(key[0], key, seconds)
        _promotions_counter.increment()
        with self._lock:
            self._promoted.add(key[0])
            self.compiled_n += 1
        # The flight recorder's slow-query surface records the
        # promotion event (ISSUE 18 satellite): which fingerprint, how
        # long the background compile ran, how many interpreted runs
        # preceded it.
        from ytsaurus_tpu.query.profile import get_flight_recorder
        get_flight_recorder().note_promotion(
            key[0], seconds,
            runs_interpreted=self._evaluator._governor.runs(key[0]),
            capacity=int(key[1]))


class Evaluator:
    """Caches compiled query programs and executes plans over chunks."""

    def __init__(self):
        # LRU order (promote on hit); bounded when
        # WorkloadConfig.compile_cache_capacity > 0, with evictions fed
        # to the compilation observatory.  The lock covers every cache
        # mutation — concurrent gateway threads share one evaluator, and
        # an unlocked move_to_end could KeyError against a concurrent
        # eviction (compiles themselves run outside the lock).
        self._cache: OrderedDict = OrderedDict()
        # guards: _cache, _inflight
        self._cache_lock = sanitizers.register_lock(
            "evaluator.Evaluator._cache_lock")
        # Single-flight compilation (ISSUE 10): concurrent dispatches
        # missing on the SAME key elect one compiler; the rest wait on
        # its event and take the cached program — a cold shape under an
        # 8-thread replay burst used to compile 4-8 identical programs
        # (thundering herd), each counted as a miss against the
        # steady-state hit-rate SLO.
        self._inflight: dict = {}
        self._join_cache: dict = {}
        # Adaptive tiering (ISSUE 18): interpreted-run roll-up (the
        # promotion signal) + the background promotion pipeline.  Both
        # are inert — no threads, a few allocations — until
        # TieringConfig.enabled turns the tier decision on.
        self._governor = TierGovernor()
        self._background = BackgroundCompiler(self)

    def cache_size(self) -> int:
        return len(self._cache)

    def tier_snapshot(self, top: int = 50) -> dict:
        """Monitoring/orchid view of the tiering plane (ISSUE 18)."""
        from ytsaurus_tpu.config import tiering_config
        cfg = tiering_config()
        return {"enabled": cfg.enabled,
                "hot_threshold": cfg.hot_threshold,
                "background": self._background.snapshot(),
                "fingerprints": self._governor.snapshot()[:top]}

    def _acquire_inflight(self, key: tuple):
        """Single-flight gate for one cache key: returns the compiled
        program if a concurrent leader finished it, or None with THIS
        caller elected leader (it must call _release_inflight)."""
        while True:
            with self._cache_lock:
                fn = self._cache.get(key)
                if fn is not None:
                    self._cache.move_to_end(key)
                    return fn
                event = self._inflight.get(key)
                if event is None:
                    self._inflight[key] = threading.Event()
                    return None
            # A leader is compiling this key: wait, then re-check (the
            # loop re-elects if the leader failed or the entry was
            # evicted before we woke).
            event.wait(timeout=600)

    def _release_inflight(self, key: tuple) -> None:
        with self._cache_lock:
            event = self._inflight.pop(key, None)
        if event is not None:
            event.set()

    # -- plan execution --------------------------------------------------------

    def run_plan(self, plan: "ir.Query | ir.FrontQuery",
                 chunk: ColumnarChunk,
                 foreign_chunks: Optional[Mapping[str, ColumnarChunk]] = None,
                 stats: Optional[QueryStatistics] = None,
                 token=None) -> ColumnarChunk:
        """Execute a plan over one input chunk (plus join tables).

        `token` (query/serving.CancellationToken) is checked BEFORE any
        device program launches: a query past its deadline stops here
        instead of consuming device time on a result nobody will read."""
        return self.run_plan_async(plan, chunk, foreign_chunks, stats,
                                   token).finish()

    def run_plan_async(self, plan: "ir.Query | ir.FrontQuery",
                       chunk: ColumnarChunk,
                       foreign_chunks: Optional[Mapping[str, ColumnarChunk]] = None,
                       stats: Optional[QueryStatistics] = None,
                       token=None):
        """Dispatch a plan's device program WITHOUT synchronizing;
        returns a pending handle whose `.finish()` yields the chunk.
        The coordinator's shard fan-out uses this to enqueue every
        shard's program before the first host sync."""
        import time as _time

        from ytsaurus_tpu.utils.tracing import child_span
        if token is not None:
            token.check()
        t0 = _time.perf_counter()
        jplan = None
        if isinstance(plan, ir.Query) and len(plan.joins) > 1:
            # Cost-based join order (ISSUE 14, query/planner.py): the
            # cascade below runs most-selective-first off the foreign
            # chunks' stats (memoized per chunk).  MUST happen before
            # the fingerprint: the reordered plan's fingerprint is how
            # the order reaches the compile cache — stable stats hit the
            # same program, a stats-driven flip compiles a fresh one.
            from ytsaurus_tpu.query import planner
            plan, jplan = planner.reorder_for_chunks(
                plan, chunk.row_count, foreign_chunks)
        # Span per plan execution, tagged with the plan fingerprint (ref:
        # evaluator.cpp:67-75 annotates spans with query fingerprints);
        # computed once and reused as the compile-cache key.  With
        # CompileConfig.parameterize this is the SHAPE fingerprint —
        # literal values hoisted, limits bucketed (ISSUE 10) — so one
        # cache entry serves every constant of a query shape.  INTERIOR
        # site: records only under a live trace (gateway/scheduler root),
        # so untraced evaluator use stays on the null fast path.
        fp = plan_fingerprint(plan)
        span = child_span("evaluator.run_plan", fingerprint=fp,
                          rows=chunk.row_count)
        with span:
            pending = self._dispatch_traced(plan, chunk, foreign_chunks,
                                            stats, t0, fp,
                                            pool=getattr(token, "pool",
                                                         None),
                                            jplan=jplan)
            span.add_tag("compile_seconds",
                         round(getattr(pending, "compile_seconds", 0.0),
                               6))
            span.add_tag("execution_tier",
                         getattr(pending, "execution_tier", "compiled"))
            return pending

    def _dispatch_traced(self, plan, chunk, foreign_chunks, stats, t0,
                         fp=None, pool=None, jplan=None):
        import time as _time
        owned_chunk = False
        if isinstance(plan, ir.Query) and plan.joins:
            foreign_chunks = foreign_chunks or {}
            # Materialize joins in (planner) execution order, widening
            # the namespace; each stage's actual cardinality folds into
            # the EXPLAIN ANALYZE join plan next to the estimate.
            namespace = list(_initial_namespace(plan))
            current = _project_chunk(chunk, TableSchema.make(namespace))
            decisions = jplan.decisions if jplan is not None else None
            for pos, join in enumerate(plan.joins):
                if join.foreign_table not in foreign_chunks:
                    raise YtError(
                        f"No data provided for join table {join.foreign_table!r}",
                        code=EErrorCode.QueryExecutionError)
                namespace = _extend_namespace(namespace, join)
                current = execute_join(
                    current, TableSchema.make(namespace), join,
                    foreign_chunks[join.foreign_table], self._join_cache)
                if stats is not None:
                    stats.joins_executed += 1
                    stats.note_join_stage(
                        pos, join.foreign_table, "local",
                        est_rows=decisions[pos].est_out
                        if decisions is not None else 0,
                        actual_rows=current.row_count)
            chunk = current
            # The cascade built `chunk`; this dispatch is its only
            # consumer, so its column planes are donatable (a totals
            # plan dispatches the same chunk twice — excluded below).
            owned_chunk = True
        elif isinstance(plan, ir.Query):
            chunk = _project_chunk(chunk, plan.schema)

        # GROUP BY ... WITH TOTALS: one extra grand-total row (null keys)
        # aggregated over the same filtered input, appended after the groups
        # (ref: totals handling in GroupOpHelper/GroupTotalsOpHelper,
        # cg_routines/registry.cpp:1920; totals_mode=before_having).
        # The concat needs both row counts, so totals plans materialize
        # eagerly.
        if plan.group is not None and plan.group.totals:
            main = self._dispatch(plan, chunk, stats, fp=fp, pool=pool)
            result = main.finish()
            totals_plan = _make_totals_plan(plan)
            totals_pending = self._dispatch(totals_plan, chunk, stats,
                                            pool=pool)
            totals = totals_pending.finish()
            result = concat_chunks([result, totals])
            if stats is not None:
                # Compile time is tallied separately inside _dispatch;
                # keep it out of the execute bucket.
                stats.execute_time += _time.perf_counter() - t0 - \
                    main.compile_seconds - totals_pending.compile_seconds
            return _ReadyResult(result)

        pending = self._dispatch(plan, chunk, stats, fp=fp, pool=pool,
                                 donate_columns=owned_chunk)
        pending.stats = stats
        # The execute clock starts after compilation: wall = compile +
        # execute, reported separately (EXPLAIN ANALYZE's first split).
        pending._t0 = t0 + pending.compile_seconds
        return pending

    def _dispatch(self, plan, chunk: ColumnarChunk,
                  stats: Optional[QueryStatistics] = None,
                  fp: Optional[str] = None,
                  pool: Optional[str] = None,
                  donate_columns: bool = False) -> _PendingResult:
        prepared = prepare(plan, chunk)
        key = (fp or plan_fingerprint(plan), chunk.capacity,
               prepared.binding_shapes())
        if donate_columns:
            # A donating executable consumes its column planes; it must
            # never be served to a dispatch over a persistent chunk.
            key = key + ("donate-cols",)
        columns = {c.name: (chunk.columns[c.name].data,
                            chunk.columns[c.name].valid)
                   for c in plan.schema}
        args = (columns, chunk.row_valid, tuple(prepared.bindings))
        with self._cache_lock:
            fn = self._cache.get(key)
            if fn is not None:
                self._cache.move_to_end(key)
        compile_seconds = 0.0
        result = None
        if stats is not None:
            # The pow2 capacity bucket this program runs against:
            # bucket churn (a shape-spectrum leak) becomes visible PER
            # QUERY in EXPLAIN ANALYZE, not just in aggregate.
            stats.capacity_buckets.add(int(chunk.capacity))
        if fn is None:
            # Single-flight: either a concurrent leader hands us the
            # finished program (counted as a hit below), or WE are
            # elected leader (None back) and must release the gate.
            fn = self._acquire_inflight(key)
        if fn is None:
            # Tier decision (ISSUE 18): with tiering on and the plan
            # inside the interpreter's DECLARED coverage, _compile_miss
            # probes only the persistent AOT rungs — when all of them
            # miss it returns fn=None with ZERO miss bookkeeping and
            # the interpreter serves this dispatch (off the compile
            # ladder entirely) while the background compiler owns the
            # fingerprint's promotion.  Coverage fallthrough
            # (try_prepare -> None) and the kill switch both take the
            # pre-tiering inline-compile path below, unchanged.
            interp_query = None
            tier_cfg = None
            from ytsaurus_tpu.config import tiering_config
            tier_cfg = tiering_config()
            if tier_cfg.enabled:
                from ytsaurus_tpu.query.engine import interp
                interp_query = interp.try_prepare(plan, chunk)
            try:
                fn, compile_seconds, result = self._compile_miss(
                    key, prepared, chunk, args, stats, pool,
                    interp_query=interp_query,
                    donate_columns=donate_columns)
            finally:
                self._release_inflight(key)
            if fn is None and result is None:
                return self._interpreted(interp_query, key, chunk,
                                         prepared, args, stats, pool,
                                         tier_cfg)
        else:
            _cache_counters.counters(pool)["hits"].increment()
            _observatory.observe_hit(key[0])
            if stats is not None:
                stats.cache_hits += 1
        execution_tier = "compiled"
        # Encoded-plane accounting (ISSUE 19): the bind notebook says
        # which mode the string predicates compiled in — code-space
        # compares ("strlit" notes) vs the merged-vocab remap fallback
        # ("str-decoded" notes).  A query with both counts as decoded:
        # one remap gather re-materializes the cost the encoded path
        # exists to avoid.
        notes = _flat_notes(prepared.structure_key)
        if "str-decoded" in notes:
            _decoded_fallbacks_counter.increment()
            if stats is not None:
                stats.execution_encoding = "decoded"
        elif "strlit" in notes:
            _encoded_scans_counter.increment()
        from ytsaurus_tpu.config import compile_config as _cc
        if _cc().donate_buffers:
            # Donation armed for this compiled dispatch: row_valid
            # always, the column planes too for owned (join-cascade)
            # chunks.  Inert on CPU, but the counter tracks arming, not
            # the backend's ability to honor it.
            _donated_buffers_counter.increment(
                1 + (len(args[0]) if donate_columns else 0))
        if self._background.consume_promoted(key[0]):
            # First compiled serve after a mid-traffic background
            # promotion: the atomic swap, made visible.
            execution_tier = "promoted-midstream"
        _tier_counters.counters(pool)["compiled"].increment()
        if stats is not None:
            stats.execution_tier = execution_tier
        if result is None:
            try:
                planes, count = fn(*args)
            except Exception:
                if hasattr(fn, "lower"):
                    raise             # plain jitted fn: a genuine error
                # AOT-compiled rejects an aval drift the cache key did
                # not capture: rebuild through the tolerant jit wrapper
                # (a genuine execution error re-raises identically).
                fn = _jit_run(prepared.run, donate_columns)
                with self._cache_lock:
                    self._cache[key] = fn
                planes, count = fn(*args)
        else:
            planes, count = result
        pending = _PendingResult(planes, count, prepared.output)
        pending.compile_seconds = compile_seconds
        pending.execution_tier = execution_tier
        return pending

    def _interpreted(self, interp_query, key, chunk, prepared, args,
                     stats, pool, tier_cfg) -> _PendingResult:
        """Serve one dispatch from the interpreter tier (ISSUE 18):
        executes the no-compile numpy program, rolls the fingerprint up
        in the governor, and enqueues a background promotion once the
        hot threshold is crossed.  Runs with the single-flight gate
        ALREADY RELEASED — concurrent dispatches of the same cold key
        each interpret in parallel (interpretation is cheap; the gate
        exists to prevent compile herds, not numpy herds)."""
        import time as _time
        t0 = _time.perf_counter()
        planes, count = interp_query.execute(chunk)
        seconds = _time.perf_counter() - t0
        _tier_counters.counters(pool)["interpreted"].increment()
        if stats is not None:
            stats.execution_tier = "interpreted"
        if self._governor.note_interpreted(key[0], seconds,
                                           tier_cfg.hot_threshold):
            status = self._background.enqueue(key, prepared, args,
                                              tier_cfg.queue_depth)
            if status == "full":
                self._governor.rearm(key[0])
        pending = _PendingResult(planes, count, interp_query.output)
        pending.execution_tier = "interpreted"
        return pending

    def _compile_miss(self, key, prepared, chunk, args, stats, pool,
                      interp_query=None, donate_columns=False):
        """The memory-miss slow path (single-flight leader only):
        disk-tier load or fresh AOT compile, cache insert + eviction,
        counters/observatory/artifact bookkeeping.  Returns
        (fn, compile_seconds, eager_result_or_None).

        With `interp_query` set (tier decision, ISSUE 18) the persistent
        rungs are still probed — a ready executable beats interpreting —
        but when ALL of them miss this returns (None, 0.0, None) with no
        side effects at all: no miss counters, no span, no storm signal.
        The caller serves the interpreter and the background compiler
        owns the compile."""
        import time as _time

        from ytsaurus_tpu.config import workload_config
        from ytsaurus_tpu.query.engine.aot_cache import (
            get_cluster_store, get_disk_cache)
        from ytsaurus_tpu.utils.tracing import child_span
        cfg = workload_config()
        result = None
        # Cache miss, classified for the observatory BEFORE the
        # entry mutates: never-seen plan shape vs a known shape
        # meeting a new capacity/binding-shape vs an LRU re-miss —
        # or a DISK HIT, when the persistent artifact tier serves a
        # ready executable (the warm-restart arm, ISSUE 10).
        cause = _observatory.classify_miss(key[0], key)
        lowered = None
        fn = None
        disk = get_disk_cache()
        cluster = get_cluster_store()
        if interp_query is not None:
            t0p = _time.perf_counter()
            if disk is not None and (fn := disk.load(key)) is not None:
                cause = "disk_hit"
            elif cluster is not None and \
                    (fn := cluster.fetch(key)) is not None:
                cause = "cluster_hit"
            else:
                return None, 0.0, None
            probe_seconds = _time.perf_counter() - t0p
        # Memory miss: try the disk tier, then the CLUSTER artifact
        # store (fetch-on-miss, ISSUE 17 — a replica joining mid-storm
        # pulls hot executables its peers already published), else
        # build the device program NOW (AOT lower + compile, the XLA
        # analog of the reference's LLVM codegen pass) so compile time
        # is measured apart from execution.  Shapes/dtypes are pinned
        # by the cache key (capacity + binding shapes), which is
        # exactly what AOT requires — and exactly what makes the
        # executables serializable across processes.
        span = child_span("evaluator.compile", fingerprint=key[0],
                          capacity=chunk.capacity)
        with span:
            t0c = _time.perf_counter()
            if fn is not None:
                pass     # the tier probe above hit a persistent rung
            elif disk is not None and \
                    (fn := disk.load(key)) is not None:
                cause = "disk_hit"
            elif cluster is not None and \
                    (fn := cluster.fetch(key)) is not None:
                cause = "cluster_hit"
            else:
                jitted = _jit_run(prepared.run, donate_columns)
                try:
                    lowered = jitted.lower(*args)
                    fn = lowered.compile()
                except Exception:   # noqa: BLE001 — AOT is an
                    # optimization; anything it cannot lower falls back
                    # to the jit wrapper (first call compiles fused).
                    fn = jitted
                    lowered = None
                    result = fn(*args)
            compile_seconds = _time.perf_counter() - t0c
            if interp_query is not None:
                compile_seconds += probe_seconds
            span.add_tag("cause", cause)
        if lowered is not None:
            # Persist the fresh AOT product so the NEXT process
            # (rolling restart) warm-starts this shape from disk, and
            # publish-on-compile to the cluster store so a replica
            # added mid-storm fetches it instead of compiling inline.
            if disk is not None:
                disk.store(key, fn, key[0], compile_seconds)
            if cluster is not None:
                cluster.publish(key, fn, key[0], compile_seconds)
        with self._cache_lock:
            self._cache[key] = fn
            evicted_keys = []
            if cfg.compile_cache_capacity:
                while len(self._cache) > cfg.compile_cache_capacity:
                    evicted_keys.append(
                        self._cache.popitem(last=False)[0])
        for evicted_key in evicted_keys:
            _observatory.observe_eviction(evicted_key)
            _evictions_counter.increment()
        _cache_counters.counters(pool)["misses"].increment()
        _observatory.observe_miss(key[0], key, cause, compile_seconds)
        if cfg.capture_artifacts and lowered is not None:
            try:
                _observatory.capture_artifact(
                    key[0], key, lowered.as_text(),
                    _cost_analysis(fn), compile_seconds)
            except Exception:   # noqa: BLE001 — artifact capture is a
                # debugging aid, never an execution hazard.
                pass
        if stats is not None:
            stats.compile_count += 1
            stats.compile_time += compile_seconds
            if cause == "disk_hit":
                stats.compile_disk_hit += 1
            elif cause == "cluster_hit":
                stats.compile_cluster_hit += 1
            elif cause == "eviction":
                stats.compile_evicted += 1
            elif cause == "new_shape":
                stats.compile_new_shape += 1
            else:
                stats.compile_new_fingerprint += 1
        return fn, compile_seconds, result

    def _execute(self, plan, chunk: ColumnarChunk,
                 stats: Optional[QueryStatistics] = None,
                 fp: Optional[str] = None) -> ColumnarChunk:
        return self._dispatch(plan, chunk, stats, fp=fp).finish()


def _initial_namespace(plan: ir.Query) -> list[tuple[str, str]]:
    """Self-table columns = plan.schema minus columns contributed by joins."""
    joined = set()
    for join in plan.joins:
        for fname in join.foreign_columns:
            joined.add(f"{join.alias}.{fname}" if join.alias else fname)
    return [(c.name, c.type.value) for c in plan.schema if c.name not in joined]


def _extend_namespace(namespace: list[tuple[str, str]],
                      join: ir.JoinClause) -> list[tuple[str, str]]:
    out = list(namespace)
    for fname in join.foreign_columns:
        flat = f"{join.alias}.{fname}" if join.alias else fname
        out.append((flat, join.foreign_schema.get(fname).type.value))
    return out


def _project_chunk(chunk: ColumnarChunk, schema: TableSchema) -> ColumnarChunk:
    """View of `chunk` under `schema` (subset/reorder of columns)."""
    columns = {}
    for col_schema in schema:
        col = chunk.columns.get(col_schema.name)
        if col is None:
            raise YtError(f"Chunk is missing column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        columns[col_schema.name] = col
    # Column projection keeps row order; the sealed sort order survives
    # for the longest key prefix whose columns are still present (rows
    # sorted by (a, b) are NOT sorted by b alone once a is dropped).
    sorted_by = []
    for name in chunk.sorted_by:
        if name not in columns:
            break
        sorted_by.append(name)
    return ColumnarChunk(schema=schema, row_count=chunk.row_count,
                         columns=columns, sorted_by=tuple(sorted_by))


def _typed_null(ty):
    """A null-valued expression carrying type `ty`: if(false, zero, null)."""
    return ir.TFunction(
        type=ty, name="if",
        args=(ir.TLiteral(type=EValueType.boolean, value=False),
              ir.TLiteral(type=ty, value=_zero_value(ty)),
              ir.TLiteral(type=EValueType.null, value=None)))


def _make_totals_plan(plan):
    """Derive the grand-total plan: single constant group key, same
    aggregates, project with group-key references nulled out, no having
    (before_having semantics), no order/limit."""
    from dataclasses import replace as dc_replace

    key_types = {item.name: item.expr.type for item in plan.group.group_items}

    def subst(e):
        return ir.map_expr(
            e, lambda node: _typed_null(node.type)
            if isinstance(node, ir.TReference) and node.name in key_types
            else node)

    const_key = ir.NamedExpr(
        name="__totals", expr=ir.TLiteral(type=EValueType.int64, value=0))
    group = ir.GroupClause(group_items=(const_key,),
                           aggregate_items=plan.group.aggregate_items,
                           totals=False)
    if plan.project is not None:
        project = ir.ProjectClause(items=tuple(
            ir.NamedExpr(name=i.name, expr=subst(i.expr))
            for i in plan.project.items))
    else:
        # Default projection: null keys + aggregate values, matching the
        # main query's output schema.
        items = []
        for item in plan.group.group_items:
            items.append(ir.NamedExpr(name=item.name,
                                      expr=_typed_null(item.expr.type)))
        for agg in plan.group.aggregate_items:
            items.append(ir.NamedExpr(
                name=agg.name,
                expr=ir.TReference(type=agg.type, name=agg.name)))
        project = ir.ProjectClause(items=tuple(items))
    return dc_replace(plan, group=group, having=None, order=None,
                      project=project, offset=0, limit=None)


def _zero_value(ty):
    if ty is EValueType.string:
        return b""
    if ty is EValueType.boolean:
        return False
    if ty is EValueType.double:
        return 0.0
    return 0


# -- convenience API -----------------------------------------------------------


_global_evaluator = Evaluator()


def select_rows(query: str,
                tables: Mapping[str, "ColumnarChunk | Sequence"],
                schemas: Optional[Mapping[str, TableSchema]] = None,
                evaluator: Optional[Evaluator] = None,
                params: Optional[Sequence] = None) -> ColumnarChunk:
    """One-shot: parse, plan, and execute a query over in-memory tables.

    `tables` maps table path → ColumnarChunk (or row list, requiring `schemas`
    to carry that table's schema).  `params` binds `?` placeholders (a list
    of floats binds as a vector — the NEAREST query vector).
    """
    evaluator = evaluator or _global_evaluator
    chunks: dict[str, ColumnarChunk] = {}
    schemas = dict(schemas or {})
    for path, data in tables.items():
        if isinstance(data, ColumnarChunk):
            chunks[path] = data
            schemas.setdefault(path, data.schema)
        else:
            if path not in schemas:
                raise YtError(f"Row-list table {path!r} requires a schema")
            chunks[path] = ColumnarChunk.from_rows(schemas[path], data)
    plan = build_query(query, schemas, params=params)
    source_chunk = chunks[plan.source]
    foreign = {p: c for p, c in chunks.items() if p != plan.source}
    return evaluator.run_plan(plan, source_chunk, foreign)
