"""Whole-plan fused SPMD execution (ISSUE 12): the entire distributed
query as ONE jit(shard_map) program on the virtual 8-device mesh.

Quick tier-1 coverage: dual-check over one representative per fused
SHAPE (CORPUS_QUICK), the single-host-sync contract, the fusion gate +
unfusable-plan ladder fallback, exchange-quota overflow escalation +
memoization, the partition-rule registry, and the in-process SPMD AOT
disk tier.  The full post-stage/alias/key corpus (over 3 random
tables), the failpoint-injected collective-fault ladder, mesh resize,
and the cross-process restart leg live behind `slow` in this module
(test_dual_check_randomized_sweep et al.) so the quick pass fits the
tier-1 870s budget.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.chunks.columnar import concat_chunks
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.query.statistics import QueryStatistics
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.utils import failpoints

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"), ("s", "string"),
    ("v", "int64"), ("d", "double")])
T = "//t"

# The dual-check plan corpus: every fused shape (exchange-states,
# exchange-rows, gather) across the q1/groupby/window/topk classes.
CORPUS = [
    # q1 class: multi-aggregate GROUP BY over few groups.
    "g, sum(v) AS sv, count(*) AS c, avg(d) AS a, min(v) AS mn, "
    "max(v) AS mx FROM [//t] GROUP BY g",
    # groupby class: WHERE + HAVING + ORDER + LIMIT on top.
    "g, sum(v) AS sv FROM [//t] WHERE v > 100 GROUP BY g "
    "HAVING count(*) > 2 ORDER BY g LIMIT 500",
    # string group keys ride the unified vocabulary.
    "s, sum(v) AS sv, count(*) AS c FROM [//t] GROUP BY s "
    "ORDER BY s LIMIT 100",
    # argmin/argmax decompose into mergeable states.
    "g, argmax(k, d) AS am, argmin(k, d) AS an FROM [//t] GROUP BY g "
    "ORDER BY g LIMIT 500",
    # ORDER BY avg(): the front substitutes the avg alias into its
    # sum/count state columns — the merge must agree with local.
    "g, avg(d) AS a FROM [//t] GROUP BY g ORDER BY avg(d) DESC LIMIT 5",
    # Expression group keys route by the EVALUATED key slot.
    "g + 1 AS gg, sum(v * 2) AS sv FROM [//t] WHERE d < 8.0 "
    "GROUP BY g + 1 ORDER BY g + 1 LIMIT 100",
    # cardinality cannot merge from states → exchange-rows shape.
    "g, cardinality(s) AS cd, count(*) AS c FROM [//t] GROUP BY g "
    "ORDER BY g LIMIT 500",
    # window class: co-partitioned exact windows → exchange-rows shape.
    "k, v, sum(v) OVER (PARTITION BY g ORDER BY k) AS rs, "
    "rank() OVER (PARTITION BY g ORDER BY k) AS rk FROM [//t] "
    "ORDER BY k LIMIT 200",
    # topk class: gather shape with the per-shard top-k bottom.
    "k, d FROM [//t] ORDER BY d DESC LIMIT 9",
    # plain filter scan: gather shape.
    "k, v FROM [//t] WHERE v > 900",
]

# Quick-tier subset: one representative per fused SHAPE (exchange-states
# multi-agg, cardinality exchange-rows, window exchange-rows, top-k
# gather, filter gather).  Each corpus query costs a full 8-device
# shard_map compile (~6s on CPU); the remaining post-stage/alias/key
# variants of the exchange-states shape run under `slow` in
# test_dual_check_randomized_sweep, which sweeps the FULL corpus over
# 3 random tables.
CORPUS_QUICK = [CORPUS[0], CORPUS[6], CORPUS[7], CORPUS[8], CORPUS[9]]


@pytest.fixture(autouse=True)
def _fresh_compile_config():
    yield
    yt_config.set_compile_config(None)


@pytest.fixture(scope="module")
def table8(request):
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    rng = np.random.default_rng(21)
    words = [f"w{i:02d}" for i in range(13)]
    chunks = []
    for sh in range(8):
        n = 150 + sh * 11
        rows = [(sh * 10_000 + i, int(rng.integers(0, 40)),
                 words[int(rng.integers(0, 13))],
                 int(rng.integers(0, 1000)), float(rng.uniform(0, 10)))
                for i in range(n)]
        chunks.append(ColumnarChunk.from_rows(SCHEMA, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    return mesh, chunks, table, concat_chunks(chunks)


def _canon(rows):
    """Order-insensitive row canon: ints/strings bit-exact, floats to
    1e-9 (partial-state merges sum in a different order than the local
    single pass — same discipline as test_distributed).  NULLs encode
    as a sortable rank so null-keyed rows canonicalize too."""
    def norm(v):
        if v is None:
            return (0, 0)
        return (1, round(v, 9) if isinstance(v, float) else v)

    out = []
    for r in rows:
        out.append(tuple((k, norm(v)) for k, v in sorted(r.items())))
    return sorted(out)


def _canon_ordered(rows):
    """Position-sensitive canon for totally-ordered outputs."""
    def norm(v):
        if v is None:
            return (0, 0)
        return (1, round(v, 9) if isinstance(v, float) else v)

    return [tuple((k, norm(v)) for k, v in sorted(r.items()))
            for r in rows]


def test_dual_check_corpus(table8):
    """Fused whole-plan vs the local evaluator over the full corpus,
    with exactly ONE host sync per fused query."""
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        host_sync_count,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, _chunks, table, merged = table8
    de = DistributedEvaluator(mesh)
    local = Evaluator()
    for query in CORPUS_QUICK:
        plan = build_query(query, {T: SCHEMA})
        stats = QueryStatistics()
        s0 = host_sync_count()
        got = run_whole_plan(de, plan, table, stats=stats)
        assert host_sync_count() - s0 == 1, query
        assert stats.whole_plan == 1
        want = local.run_plan(plan, merged)
        if plan.order is not None:
            # Every ordered corpus query sorts by a key that is UNIQUE
            # in its output (group keys post-group, unique k, random
            # doubles), so positions must match exactly — compare the
            # canon WITHOUT the order-insensitive final sort.
            assert _canon_ordered(got.to_rows()) == \
                _canon_ordered(want.to_rows()), query
        assert _canon(got.to_rows()) == _canon(want.to_rows()), query


def test_repeat_query_compiles_nothing(table8):
    """Steady state: a repeated fused query is a pure cache hit — zero
    fresh compiles, zero overflow retries (the quota memo settled)."""
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, _chunks, table, merged = table8
    de = DistributedEvaluator(mesh)
    plan = build_query(CORPUS[0], {T: SCHEMA})
    run_whole_plan(de, plan, table)
    fc = de.fresh_compiles
    stats = QueryStatistics()
    got = run_whole_plan(de, plan, table, stats=stats)
    assert de.fresh_compiles == fc
    assert stats.whole_plan_retries == 0
    assert _canon(got.to_rows()) == \
        _canon(Evaluator().run_plan(plan, merged).to_rows())


def test_unfusable_plans_fall_to_stitched_ladder(table8):
    """WITH TOTALS stays on the stitched rungs; join plans fuse since
    ISSUE 14 — but one with NO foreign data still degrades cleanly, and
    the fused join result matches the local evaluator."""
    from dataclasses import replace as dc_replace

    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        coordinate_distributed,
    )
    from ytsaurus_tpu.parallel.whole_plan import can_fuse, run_whole_plan
    from ytsaurus_tpu.errors import YtError
    mesh, chunks, table, merged = table8
    dim_schema = TableSchema.make([("dk", "int64", "ascending"),
                                   ("name", "int64")])
    dim = ColumnarChunk.from_arrays(dim_schema, {
        "dk": np.arange(0, 80, 2), "name": np.arange(40) * 10})
    plan = build_query("g, name, sum(v) AS sv FROM [//t] "
                       "JOIN [//d] ON g = dk GROUP BY g, name",
                       {T: SCHEMA, "//d": dim_schema})
    # Joins fuse now (ISSUE 14) — missing foreign data raises, and the
    # ladder serves the query off-rung.
    assert can_fuse(plan) is None
    de = DistributedEvaluator(mesh)
    with pytest.raises(YtError):
        run_whole_plan(de, plan, table)         # no foreign chunks
    stats = QueryStatistics()
    got = coordinate_distributed(plan, mesh, chunks, {"//d": dim},
                                 evaluator=de, stats=stats)
    want = Evaluator().run_plan(plan, merged, {"//d": dim})
    assert _canon(got.to_rows()) == _canon(want.to_rows())
    assert stats.whole_plan == 1               # fused join rung served it
    # WITH TOTALS: gated (eager two-rowset concat), reason names it.
    gplan = build_query("g, sum(v) AS sv FROM [//t] GROUP BY g",
                        {T: SCHEMA})
    totals_plan = dc_replace(
        gplan, group=dc_replace(gplan.group, totals=True))
    assert "TOTALS" in can_fuse(totals_plan)


@pytest.mark.slow
def test_failpoint_fault_lands_on_stitched_ladder(table8):
    """A failpoint-injected `parallel.all_to_all` fault knocks the fused
    rung (and the stitched shuffle) out; the ladder still serves the
    query bit-identically — and with every collective dead, the host
    coordinator answers."""
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        coordinate_distributed,
    )
    mesh, chunks, table, merged = table8
    de = DistributedEvaluator(mesh)
    plan = build_query(CORPUS[0], {T: SCHEMA})
    baseline = _canon(coordinate_distributed(
        plan, mesh, chunks, evaluator=de).to_rows())
    assert baseline == _canon(Evaluator().run_plan(plan, merged).to_rows())
    stats = QueryStatistics()
    with failpoints.active("parallel.all_to_all=error:times=1", seed=3):
        got = coordinate_distributed(plan, mesh, chunks, evaluator=de,
                                     stats=stats)
    assert _canon(got.to_rows()) == baseline
    assert stats.whole_plan == 0       # served off-rung
    with failpoints.active("parallel.all_to_all=error:times=4;"
                           "parallel.gather=error:times=4", seed=4):
        got = coordinate_distributed(plan, mesh, chunks, evaluator=de)
    assert _canon(got.to_rows()) == baseline


def test_overflow_escalation_and_quota_memo(request):
    """Skewed routing keys overflow the optimistic static quota: the
    query re-runs at the demanded pow2 rung (correct results), and the
    settled quota memoizes so the NEXT query runs clean."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("g", "int64"), ("v", "int64")])
    rng = np.random.default_rng(5)
    chunks = []
    for sh in range(8):
        n = 256
        # ~90% of rows share one partition key → one (src, dst) cell
        # holds most of a shard.
        g = np.where(rng.uniform(size=n) < 0.9, 7,
                     rng.integers(0, 32, n))
        chunks.append(ColumnarChunk.from_arrays(schema, {
            "k": np.arange(n) + sh * n, "g": g,
            "v": rng.integers(0, 100, n)}))
    from ytsaurus_tpu.parallel.distributed import ShardedTable
    table = ShardedTable.from_chunks(mesh, chunks)
    merged = concat_chunks(chunks)
    de = DistributedEvaluator(mesh)
    plan = build_query(
        "k, sum(v) OVER (PARTITION BY g) AS s FROM [//t] "
        "ORDER BY k LIMIT 100", {T: schema})
    stats = QueryStatistics()
    got = run_whole_plan(de, plan, table, stats=stats)
    want = Evaluator().run_plan(plan, merged)
    assert got.to_rows() == want.to_rows()
    assert stats.whole_plan_retries >= 1
    assert de._quota_memo, "settled quota must memoize"
    stats2 = QueryStatistics()
    got2 = run_whole_plan(de, plan, table, stats=stats2)
    assert stats2.whole_plan_retries == 0
    assert got2.to_rows() == want.to_rows()


def test_partition_rule_registry(table8):
    """The registry is consulted for real: stage names resolve through
    match_partition_rules, a registry that misplaces a stage fails
    loudly, and the rules digest is a cache-key axis."""
    from jax.sharding import PartitionSpec as P

    from ytsaurus_tpu.errors import YtError
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
    from ytsaurus_tpu.parallel.whole_plan import (
        DEFAULT_PARTITION_RULES,
        match_partition_rules,
        rules_fingerprint,
        run_whole_plan,
    )
    mesh, _chunks, table, _merged = table8
    assert match_partition_rules(DEFAULT_PARTITION_RULES, "scan/k") == \
        P(SHARD_AXIS)
    assert match_partition_rules(DEFAULT_PARTITION_RULES,
                                 "shuffle/group") == P(SHARD_AXIS)
    assert match_partition_rules(DEFAULT_PARTITION_RULES, "front") == P()
    with pytest.raises(YtError):
        match_partition_rules(DEFAULT_PARTITION_RULES, "nonsense-stage")
    # A first-hit override ahead of the defaults changes placement —
    # and misplaces the front merge, which must fail loudly (the
    # coordinate_distributed ladder would then degrade to stitched).
    bad = ((r"^front$", P(SHARD_AXIS)),) + DEFAULT_PARTITION_RULES
    plan = build_query(CORPUS[0], {T: SCHEMA})
    de = DistributedEvaluator(mesh)
    with pytest.raises(YtError, match="partition rules place stage"):
        run_whole_plan(de, plan, table, rules=bad)
    assert rules_fingerprint(bad) != \
        rules_fingerprint(DEFAULT_PARTITION_RULES)


@pytest.mark.slow
def test_mesh_resize_is_a_cache_fill(request, tmp_path):
    """Elastic fleet: the mesh shape is a cache-key axis, so resizing
    8 → 4 devices compiles fresh rungs once and a restarted evaluator
    on the SAME disk tier serves the resized mesh with zero fresh
    compiles."""
    request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    yt_config.set_compile_config(
        yt_config.CompileConfig(disk_cache_dir=str(tmp_path)))
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("g", "int64"), ("v", "int64")])
    plan = build_query("g, sum(v) AS sv, count(*) AS c FROM [//t] "
                       "GROUP BY g", {T: schema})

    def shards(n):
        return [ColumnarChunk.from_arrays(schema, {
            "k": np.arange(64) + sh * 64,
            "g": (np.arange(64) + sh) % 7,
            "v": np.arange(64) * 3}) for sh in range(n)]

    want = _canon(Evaluator().run_plan(
        plan, concat_chunks(shards(8))).to_rows())
    for n in (8, 4):
        mesh = make_mesh(n)
        table = ShardedTable.from_chunks(mesh, shards(n))
        de = DistributedEvaluator(mesh)
        got = run_whole_plan(de, plan, table)
        assert de.fresh_compiles >= 1      # a new mesh shape = new rung
        if n == 8:
            assert _canon(got.to_rows()) == want
        # Restarted evaluator, same mesh shape, same disk dir: pure
        # cache fill — 0 fresh compiles.
        de2 = DistributedEvaluator(mesh)
        got2 = run_whole_plan(de2, plan, table)
        assert de2.fresh_compiles == 0 and de2.disk_hits >= 1
        assert _canon(got2.to_rows()) == _canon(got.to_rows())


def test_stitched_spmd_caches_ride_the_disk_tier(table8, tmp_path):
    """ISSUE 12 satellite: the surviving stitched-path program caches
    (finish / shuffled / shuffled-count) serialize too — a fresh
    evaluator over the same artifact dir re-runs both rungs with zero
    fresh SPMD compiles."""
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    mesh, _chunks, table, merged = table8
    yt_config.set_compile_config(
        yt_config.CompileConfig(disk_cache_dir=str(tmp_path)))
    plan = build_query("g, sum(v) AS sv, count(*) AS c FROM [//t] "
                       "GROUP BY g", {T: SCHEMA})
    de = DistributedEvaluator(mesh)
    a = de.run(plan, table, shuffle=True)
    b = de.run(plan, table, shuffle=False)
    assert de.fresh_compiles >= 3          # count + exchange + finish
    de2 = DistributedEvaluator(mesh)
    a2 = de2.run(plan, table, shuffle=True)
    b2 = de2.run(plan, table, shuffle=False)
    assert de2.fresh_compiles == 0, \
        "restart must serve every stitched SPMD program from disk"
    assert de2.disk_hits >= 3
    assert _canon(a2.to_rows()) == _canon(a.to_rows())
    assert _canon(b2.to_rows()) == _canon(b.to_rows())
    want = _canon(Evaluator().run_plan(plan, merged).to_rows())
    assert _canon(a.to_rows()) == want and _canon(b.to_rows()) == want


@pytest.mark.slow
def test_cross_process_spmd_restart(table8, tmp_path):
    """ISSUE 12 acceptance: compile the fused whole-plan program in THIS
    process, then a SECOND process over the same artifact dir serves the
    same plan with 0 fresh SPMD compiles (disk hits only)."""
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, _chunks, table, merged = table8
    yt_config.set_compile_config(
        yt_config.CompileConfig(disk_cache_dir=str(tmp_path)))
    plan = build_query(CORPUS[0], {T: SCHEMA})
    de = DistributedEvaluator(mesh)
    want = run_whole_plan(de, plan, table)
    assert de.fresh_compiles >= 1
    script = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import numpy as np
from ytsaurus_tpu import config as yt_config
yt_config.set_compile_config(yt_config.CompileConfig(
    disk_cache_dir={str(tmp_path)!r}))
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.parallel.distributed import DistributedEvaluator, \
    ShardedTable
from ytsaurus_tpu.parallel.mesh import make_mesh
from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"), ("s", "string"),
    ("v", "int64"), ("d", "double")])
rng = np.random.default_rng(21)
words = [f"w{{i:02d}}" for i in range(13)]
chunks = []
for sh in range(8):
    n = 150 + sh * 11
    rows = [(sh * 10_000 + i, int(rng.integers(0, 40)),
             words[int(rng.integers(0, 13))],
             int(rng.integers(0, 1000)), float(rng.uniform(0, 10)))
            for i in range(n)]
    chunks.append(ColumnarChunk.from_rows(SCHEMA, rows))
mesh = make_mesh(8)
table = ShardedTable.from_chunks(mesh, chunks)
plan = build_query({CORPUS[0]!r}, {{"//t": SCHEMA}})
de = DistributedEvaluator(mesh)
out = run_whole_plan(de, plan, table)
print("CHILD", out.row_count, de.fresh_compiles, de.disk_hits)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    child = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("CHILD")][0].split()
    rows, fresh, disk = int(child[1]), int(child[2]), int(child[3])
    assert rows == want.row_count
    assert fresh == 0, "restart leg must serve the fused plan from disk"
    assert disk >= 1


@pytest.mark.slow
def test_dual_check_randomized_sweep(request):
    """Deeper corpus: 3 random tables (fresh vocabularies, null keys,
    negative values) × the full plan corpus, fused vs local — the
    minutes-long variant of test_dual_check_corpus."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    local = Evaluator()
    for seed in (101, 202, 303):
        rng = np.random.default_rng(seed)
        words = [f"t{i:03d}" for i in range(int(rng.integers(3, 50)))]
        chunks = []
        for sh in range(8):
            n = int(rng.integers(40, 400))
            rows = []
            for i in range(n):
                rows.append((
                    sh * 100_000 + i,
                    int(rng.integers(-50, 50))
                    if rng.uniform() > 0.05 else None,
                    words[int(rng.integers(0, len(words)))],
                    int(rng.integers(-1000, 1000)),
                    float(rng.uniform(-5, 5))))
            chunks.append(ColumnarChunk.from_rows(SCHEMA, rows))
        table = ShardedTable.from_chunks(mesh, chunks)
        merged = concat_chunks(chunks)
        de = DistributedEvaluator(mesh)
        for query in CORPUS:
            plan = build_query(query, {T: SCHEMA})
            got = run_whole_plan(de, plan, table)
            want = local.run_plan(plan, merged)
            assert _canon(got.to_rows()) == _canon(want.to_rows()), \
                (seed, query)


def test_explain_analyze_renders_whole_plan_flag():
    from ytsaurus_tpu.query.profile import format_profile_dict
    stats = QueryStatistics(whole_plan=1, whole_plan_retries=1)
    text = format_profile_dict({"statistics": stats.to_dict()})
    assert "whole-plan fused SPMD" in text
    assert "overflow retries 1" in text
    cold = format_profile_dict(
        {"statistics": QueryStatistics().to_dict()})
    assert "whole-plan" not in cold


# -- mesh telemetry (ISSUE 20) -------------------------------------------------


def _oracle_pids(values, n: int):
    """Destination shard per row via the SAME canonical-hash helpers the
    fused program routes with (`whole_plan._dest_hash`), applied OUTSIDE
    shard_map on the raw numpy column — an independent recomputation in
    the dual-check discipline."""
    import jax.numpy as jnp

    from ytsaurus_tpu.parallel.distributed import _canonical_hash_plane
    from ytsaurus_tpu.query.engine.expr import _combine_u64, _mix_u64
    acc = jnp.full(len(values), np.uint64(0x9E3779B97F4A7C15),
                   dtype=jnp.uint64)
    h = _mix_u64(_canonical_hash_plane(
        jnp.asarray(values, dtype=jnp.int64)))
    acc = _combine_u64(acc, h)
    return np.asarray(acc % np.uint64(n)).astype(int)


def test_mesh_telemetry_block_matches_numpy_oracle(request):
    """ISSUE 20 acceptance: the telemetry block decoded from the ONE
    stacked final transfer is bit-identical to a host-side oracle — live
    input rows per shard, per-shard output rows, and the full all_to_all
    transfer-count matrix recomputed with numpy + the canonical hash
    outside shard_map — and arming telemetry still costs exactly one
    host sync per query on the 8-device mesh."""
    mesh = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
        host_sync_count,
    )
    from ytsaurus_tpu.parallel.whole_plan import (
        MESH_TELEMETRY_VERSION,
        run_whole_plan,
    )
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("g", "int64"), ("v", "int64")])
    rng = np.random.default_rng(11)
    sizes = [40 + 9 * sh for sh in range(8)]
    g_cols, v_cols, chunks = [], [], []
    for sh, rows in enumerate(sizes):
        g = rng.integers(0, 12, rows)
        v = rng.integers(0, 1000, rows)
        g_cols.append(g)
        v_cols.append(v)
        chunks.append(ColumnarChunk.from_arrays(schema, {
            "k": np.arange(rows) + sh * 10_000, "g": g, "v": v}))
    table = ShardedTable.from_chunks(mesh, chunks)
    merged = concat_chunks(chunks)
    de = DistributedEvaluator(mesh)

    # Gather shape: in_rows = live rows, out_rows = per-shard filter
    # survivors, no exchanges.
    plan = build_query("k, v FROM [//t] WHERE v > 500", {T: schema})
    stats = QueryStatistics()
    s0 = host_sync_count()
    got = run_whole_plan(de, plan, table, stats=stats)
    assert host_sync_count() - s0 == 1
    [block] = stats.mesh_blocks
    want_out = [int((v > 500).sum()) for v in v_cols]
    assert block["version"] == MESH_TELEMETRY_VERSION
    assert block["path"] == "fused" and block["shards"] == 8
    assert block["in_rows"] == sizes
    assert block["out_rows"] == want_out
    assert block["skew"] == round(max(want_out) / (sum(want_out) / 8), 4)
    assert block["exchanges"] == [] and block["exchange_bytes"] == 0
    assert got.row_count == sum(want_out)
    assert stats.mesh_skew_max == block["skew"]

    # Exchange-rows shape (window): the routed transfer-count matrix is
    # the canonical key hash of the PARTITION BY column, shard-major.
    planw = build_query(
        "k, v, sum(v) OVER (PARTITION BY g ORDER BY k) AS rs "
        "FROM [//t] ORDER BY k LIMIT 64", {T: schema})
    statsw = QueryStatistics()
    s0 = host_sync_count()
    goww = run_whole_plan(de, planw, table, stats=statsw)
    assert statsw.whole_plan_retries == 0
    assert host_sync_count() - s0 == 1
    [blockw] = statsw.mesh_blocks
    matrix = np.zeros((8, 8), dtype=int)
    for sh in range(8):
        matrix[sh] = np.bincount(_oracle_pids(g_cols[sh], 8),
                                 minlength=8)
    [entry] = blockw["exchanges"]
    assert entry["stage"] == "shuffle/exchange-rows"
    assert entry["matrix"] == matrix.reshape(-1).tolist()
    assert entry["rows"] == int(matrix.sum())
    assert entry["demand"] == int(matrix.max())
    assert entry["quota"] >= entry["demand"]
    assert entry["headroom"] == round(matrix.max() / entry["quota"], 4)
    # Routed rowset = the k/g/v int64 planes: (8 data + 1 validity) × 3.
    assert entry["bytes"] == int(matrix.sum()) * 27
    assert blockw["exchange_bytes"] == entry["bytes"]
    assert blockw["in_rows"] == sizes
    # The window local stage emits one row per received row, so the
    # per-destination output spread IS the matrix column sums.
    assert blockw["out_rows"] == matrix.sum(axis=0).tolist()
    assert _canon_ordered(goww.to_rows()) == _canon_ordered(
        Evaluator().run_plan(planw, merged).to_rows())


def test_mesh_telemetry_disarm_is_free_and_bit_identical(table8):
    """Disarming mesh telemetry compiles a fresh program (the armed bit
    is a cache-key axis), still costs exactly one host sync, publishes
    nothing — and the query result is bit-identical either way."""
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        host_sync_count,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    mesh, _chunks, table, merged = table8
    de = DistributedEvaluator(mesh)
    plan = build_query(CORPUS[0], {T: SCHEMA})
    try:
        stats_on = QueryStatistics()
        s0 = host_sync_count()
        armed_out = run_whole_plan(de, plan, table, stats=stats_on)
        assert host_sync_count() - s0 == 1
        assert len(stats_on.mesh_blocks) == 1
        assert stats_on.mesh_skew_max >= 1.0
        assert stats_on.mesh_exchange_bytes > 0
        yt_config.set_telemetry_config(
            yt_config.TelemetryConfig(mesh_telemetry=False))
        stats_off = QueryStatistics()
        s0 = host_sync_count()
        plain_out = run_whole_plan(de, plan, table, stats=stats_off)
        assert host_sync_count() - s0 == 1
        assert stats_off.mesh_blocks == []
        assert stats_off.mesh_skew_max == 0.0
    finally:
        yt_config.set_telemetry_config(None)
    want = _canon(Evaluator().run_plan(plan, merged).to_rows())
    assert _canon(armed_out.to_rows()) == want
    assert _canon(plain_out.to_rows()) == want


def test_stitched_rungs_report_the_same_block_shape(table8):
    """The stitched shuffle path assembles the SAME versioned block from
    host values it already read (path="stitched"), with the transfer
    matrix agreeing with the canonical-hash oracle — zero additional
    device reads."""
    from ytsaurus_tpu.parallel.distributed import DistributedEvaluator
    from ytsaurus_tpu.parallel.whole_plan import MESH_TELEMETRY_VERSION
    mesh, chunks, table, _merged = table8
    de = DistributedEvaluator(mesh)
    plan = build_query("g, sum(v) AS sv FROM [//t] GROUP BY g",
                       {T: SCHEMA})
    stats = QueryStatistics()
    de.run(plan, table, shuffle=True, stats=stats)
    assert stats.mesh_blocks, "stitched shuffle must publish a block"
    block = stats.mesh_blocks[0]
    assert block["version"] == MESH_TELEMETRY_VERSION
    assert block["path"] == "stitched" and block["shards"] == 8
    assert block["in_rows"] == [c.row_count for c in chunks]
    [entry] = block["exchanges"]
    assert entry["stage"] == "shuffle/stitched"
    assert sum(entry["matrix"]) == entry["rows"] > 0
    assert entry["quota"] >= entry["demand"] == max(entry["matrix"])


def test_explain_analyze_renders_mesh_telemetry():
    from ytsaurus_tpu.query.profile import format_profile_dict
    stats = QueryStatistics(whole_plan=1)
    stats.note_mesh_block({
        "version": 1, "path": "fused", "shards": 4,
        "in_rows": [10, 10, 10, 10], "out_rows": [2, 3, 4, 11],
        "skew": 2.2, "exchange_bytes": 540,
        "exchanges": [{"stage": "shuffle/group", "rows": 20,
                       "bytes": 540, "demand": 11, "quota": 16,
                       "headroom": 0.6875}],
        "memory_watermark_bytes": 4096})
    text = format_profile_dict({"statistics": stats.to_dict()})
    assert "mesh telemetry:" in text
    assert "rows/shard min 2 / median 4 / max 11  skew 2.2" in text
    assert "exchange shuffle/group: 20 rows / 540 bytes" in text
    assert "quota 16 granted / 11 demanded (headroom 0.6875)" in text
    assert "memory watermark 4096 bytes" in text
    cold = format_profile_dict(
        {"statistics": QueryStatistics().to_dict()})
    assert "mesh telemetry" not in cold
