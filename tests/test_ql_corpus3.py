"""QL regression corpus, part 3 — cast matrix, null propagation
through every function family, WITH TOTALS, multi-key grouping, and
composition depth.

With parts 1 and 2 this brings the harness to ~500 cases (reference
scale: library/query/unittests/evaluate/ql_query_ut.cpp ~600).  As
before: behavior-derived, not ported text.
"""

import pytest

from tests.harness import evaluate

T = "//t"
INT_COLS = [("k", "int64", "ascending"), ("v", "int64")]
MULTI = [("k", "int64", "ascending"), ("a", "int64"), ("b", "int64"),
         ("x", "double"), ("s", "string")]


def tbl(rows, cols=INT_COLS, path=T):
    return {path: (cols, rows)}


M = tbl([(1, 0, 0, 1.5, "p"), (2, 0, 1, -2.5, "q"), (3, 1, 0, 0.25, "p"),
         (4, 1, 1, None, None), (5, None, 0, 4.0, "r"),
         (6, 2, None, -0.5, "q")], MULTI)


def run(query, tables, expected, ordered=False):
    evaluate(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# A. cast matrix — every source/target pair at edge values
# ---------------------------------------------------------------------------

CASTS = [
    ("i2d_exact", f"double(v) AS r FROM [{T}]", tbl([(1, 5)]),
     [{"r": 5.0}]),
    ("i2d_large", f"double(v) AS r FROM [{T}]", tbl([(1, 1 << 53)]),
     [{"r": float(1 << 53)}]),
    ("d2i_floor_pos", f"int64(x) AS r FROM [{T}]",
     tbl([(1, 0, 0, 2.99, "z")], MULTI), [{"r": 2}]),
    ("d2i_ceil_neg", f"int64(x) AS r FROM [{T}]",
     tbl([(1, 0, 0, -2.99, "z")], MULTI), [{"r": -2}]),
    ("i2u_neg_wraps", f"uint64(v) AS r FROM [{T}]", tbl([(1, -2)]),
     [{"r": (1 << 64) - 2}]),
    ("u2i_big_wraps", f"int64(uint64(v)) AS r FROM [{T}]",
     tbl([(1, -1)]), [{"r": -1}]),
    ("b2i_true", f"int64(v = 1) AS r FROM [{T}]", tbl([(1, 1)]),
     [{"r": 1}]),
    ("b2i_false", f"int64(v = 2) AS r FROM [{T}]", tbl([(1, 1)]),
     [{"r": 0}]),
    ("i2b_zero", f"boolean(v) AS r FROM [{T}]", tbl([(1, 0)]),
     [{"r": False}]),
    ("i2b_nonzero", f"boolean(v) AS r FROM [{T}]", tbl([(1, -3)]),
     [{"r": True}]),
    ("d2b", f"boolean(x) AS r FROM [{T}]",
     tbl([(1, 0, 0, 0.5, "z")], MULTI), [{"r": True}]),
    ("cast_null_any_target", f"double(a) AS r FROM [{T}] WHERE k = 5",
     M, [{"r": None}]),
    ("chained_casts", f"int64(double(uint64(v))) AS r FROM [{T}]",
     tbl([(1, 7)]), [{"r": 7}]),
    ("cast_in_where", f"k FROM [{T}] WHERE double(v) / 2.0 > 1.4",
     tbl([(1, 2), (2, 3)]), [{"k": 2}]),
    ("cast_in_group_key",
     f"int64(x) AS b, count(*) AS n FROM [{T}] WHERE x > 0 "
     "GROUP BY int64(x)", M,
     [{"b": 1, "n": 1}, {"b": 0, "n": 1}, {"b": 4, "n": 1}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in CASTS],
                         ids=[c[0] for c in CASTS])
def test_cast_matrix(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# B. null propagation through every function family
# ---------------------------------------------------------------------------

NULLP = [
    ("null_upper", f"upper(s) AS r FROM [{T}] WHERE k = 4", M,
     [{"r": None}]),
    ("null_length", f"length(s) AS r FROM [{T}] WHERE k = 4", M,
     [{"r": None}]),
    ("null_concat_left", f"concat(s, 'x') AS r FROM [{T}] WHERE k = 4",
     M, [{"r": None}]),
    ("null_abs", f"abs(a) AS r FROM [{T}] WHERE k = 5", M,
     [{"r": None}]),
    ("null_floor", f"floor(x) AS r FROM [{T}] WHERE k = 4", M,
     [{"r": None}]),
    ("null_min_of_one_side", f"min_of(a, 99) AS r FROM [{T}] WHERE k = 5",
     M, [{"r": 99}]),
    ("null_if_cond_is_false_branch",
     f"if(a > 0, 'yes', 'no') AS r FROM [{T}] WHERE k = 5", M,
     [{"r": None}]),
    ("null_is_null_true", f"k FROM [{T}] WHERE is_null(a)", M,
     [{"k": 5}]),
    ("null_is_null_projected",
     f"is_null(s) AS r FROM [{T}] WHERE k = 4", M, [{"r": True}]),
    ("null_if_null_passthrough",
     f"if_null(a, -1) AS r FROM [{T}] WHERE k IN (3, 5)", M,
     [{"r": 1}, {"r": -1}]),
    ("null_timestamp_floor",
     f"timestamp_floor_hour(a) AS r FROM [{T}] WHERE k = 5", M,
     [{"r": None}]),
    ("null_arith_chain",
     f"(a + b) * 2 - 1 AS r FROM [{T}] WHERE k IN (1, 5)", M,
     [{"r": -1}, {"r": None}]),
    ("null_never_groups_with_zero",
     f"a, count(*) AS n FROM [{T}] GROUP BY a", M,
     [{"a": 0, "n": 2}, {"a": 1, "n": 2}, {"a": None, "n": 1},
      {"a": 2, "n": 1}]),
    ("null_not_counted", f"count(a) AS n FROM [{T}] GROUP BY 1", M,
     [{"n": 5}]),
    ("null_sum_skips", f"sum(a) AS t FROM [{T}] GROUP BY 1", M,
     [{"t": 4}]),
    ("null_avg_skips", f"avg(b) AS r FROM [{T}] GROUP BY 1", M,
     [{"r": 0.4}]),
    ("null_min_skips", f"min(x) AS r FROM [{T}] GROUP BY 1", M,
     [{"r": -2.5}]),
    ("null_argmax_skips_null_weight",
     f"argmax(k, a) AS r FROM [{T}] GROUP BY 1", M, [{"r": 6}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in NULLP],
                         ids=[c[0] for c in NULLP])
def test_null_propagation(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# C. WITH TOTALS + multi-key grouping shapes
# ---------------------------------------------------------------------------

TOTALS = [
    ("totals_basic",
     # WHERE a != 99 drops the null-a row (three-valued comparison);
     # the totals row itself carries a=null.
     f"a, sum(b) AS t FROM [{T}] WHERE a != 99 GROUP BY a WITH TOTALS",
     M, [{"a": 0, "t": 1}, {"a": 1, "t": 1},
         {"a": 2, "t": None}, {"a": None, "t": 2}]),
    ("multi_key_group",
     f"a, b, count(*) AS n FROM [{T}] WHERE k <= 4 GROUP BY a, b", M,
     [{"a": 0, "b": 0, "n": 1}, {"a": 0, "b": 1, "n": 1},
      {"a": 1, "b": 0, "n": 1}, {"a": 1, "b": 1, "n": 1}]),
    ("multi_key_with_expression",
     f"a, b % 2 AS p, count(*) AS n FROM [{T}] WHERE b != 99 "
     "GROUP BY a, b % 2", M,
     [{"a": 0, "p": 0, "n": 1}, {"a": 0, "p": 1, "n": 1},
      {"a": 1, "p": 0, "n": 1}, {"a": 1, "p": 1, "n": 1},
      {"a": None, "p": 0, "n": 1}]),
    ("group_by_string_and_int",
     f"s, a, count(*) AS n FROM [{T}] WHERE s != '' GROUP BY s, a", M,
     [{"s": b"p", "a": 0, "n": 1}, {"s": b"q", "a": 0, "n": 1},
      {"s": b"p", "a": 1, "n": 1}, {"s": b"r", "a": None, "n": 1},
      {"s": b"q", "a": 2, "n": 1}]),
    ("having_on_multi_key",
     f"a, b, count(*) AS n FROM [{T}] GROUP BY a, b "
     "HAVING count(*) >= 1 AND a = 0", M,
     [{"a": 0, "b": 0, "n": 1}, {"a": 0, "b": 1, "n": 1}]),
    ("order_after_group",
     f"a, sum(b) AS t FROM [{T}] WHERE a != 99 GROUP BY a "
     "ORDER BY a ASC LIMIT 10", M,
     [{"a": 0, "t": 1}, {"a": 1, "t": 1}, {"a": 2, "t": None}]),
    ("count_distinct_via_cardinality",
     f"cardinality(s) AS c FROM [{T}] GROUP BY 1", M, [{"c": 3}]),
    ("nested_aggregate_expression",
     f"sum(a * b) AS t FROM [{T}] GROUP BY 1", M, [{"t": 1}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in TOTALS],
                         ids=[c[0] for c in TOTALS])
def test_totals_and_multikey(query, tables, expected):
    ordered = len(tables) and "ORDER BY" in query
    run(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# D. composition depth: nested conditionals / functions / predicates
# ---------------------------------------------------------------------------

DEPTH = [
    ("if_inside_case",
     f"CASE WHEN if(a = 0, b = 0, FALSE) THEN 'both0' ELSE 'other' END "
     f"AS r FROM [{T}] WHERE k IN (1, 2)", M,
     [{"r": b"both0"}, {"r": b"other"}]),
    ("case_inside_arith",
     f"(CASE a WHEN 0 THEN 10 ELSE 20 END) + b AS r FROM [{T}] "
     "WHERE k IN (1, 3)", M, [{"r": 10}, {"r": 20}]),
    ("transform_of_concat",
     f"transform(concat(s, s), ('pp', 'qq'), (1, 2)) AS r FROM [{T}] "
     "WHERE k IN (1, 2)", M, [{"r": 1}, {"r": 2}]),
    ("regex_of_if_null",
     f"k FROM [{T}] WHERE regex_partial_match('p', if_null(s, 'p'))",
     M, [{"k": 1}, {"k": 3}, {"k": 4}]),
    ("substr_of_upper_in_group",
     f"substr(upper(s), 0, 1) AS c, count(*) AS n FROM [{T}] "
     "WHERE s != '' GROUP BY substr(upper(s), 0, 1)", M,
     [{"c": b"P", "n": 2}, {"c": b"Q", "n": 2}, {"c": b"R", "n": 1}]),
    ("between_on_expression",
     f"k FROM [{T}] WHERE a * 2 + b BETWEEN 1 AND 2", M,
     [{"k": 2}, {"k": 3}]),
    ("in_on_function_result",
     f"k FROM [{T}] WHERE length(if_null(s, '??')) IN (1)", M,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}]),
    ("boolean_algebra_chain",
     # k=5 (a null): NOT(null AND true) is null → three-valued AND
     # filters the row even though the left disjunct is true.
     f"k FROM [{T}] WHERE (a = 0 OR b = 0) AND NOT (a = 0 AND b = 0)",
     M, [{"k": 2}, {"k": 3}]),
    ("double_negation", f"k FROM [{T}] WHERE NOT (NOT (a = 1))", M,
     [{"k": 3}, {"k": 4}]),
    ("arith_on_aggregates",
     f"sum(a) * 10 + count(*) AS r FROM [{T}] GROUP BY 1", M,
     [{"r": 46}]),
    ("minmax_of_aggregates",
     f"min_of(min(a), 0 - max(b)) AS r FROM [{T}] GROUP BY 1", M,
     [{"r": -1}]),
    ("deep_if_null_chain",
     f"if_null(if_null(a, b), -9) AS r FROM [{T}] WHERE k IN (5, 6)",
     M, [{"r": 0}, {"r": 2}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in DEPTH],
                         ids=[c[0] for c in DEPTH])
def test_composition_depth(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# E. tuple predicates, LIKE escapes, arithmetic semantics breadth
# ---------------------------------------------------------------------------

PAIRS = tbl([(1, 1), (2, 5), (3, 2), (4, 1), (5, 9)],
            [("a", "int64", "ascending"), ("b", "int64")], T)
ESC = tbl([(1, "100%"), (2, "100x"), (3, "a_b"), (4, "axb"),
           (5, "back\\slash")],
          [("k", "int64", "ascending"), ("s", "string")], T)

EXTRA = [
    ("tuple_in",
     f"a FROM [{T}] WHERE (a, b) IN ((1, 1), (3, 2), (5, 5))", PAIRS,
     [{"a": 1}, {"a": 3}]),
    ("tuple_in_none_match",
     f"a FROM [{T}] WHERE (a, b) IN ((1, 2))", PAIRS, []),
    ("tuple_between_lexicographic",
     # (a,b) in the LEX range [(1,5), (4,0)] — row (2,5),(3,2) inside,
     # (1,1) below, (4,1),(5,9) above.
     f"a FROM [{T}] WHERE (a, b) BETWEEN ((1, 5) AND (4, 0))", PAIRS,
     [{"a": 2}, {"a": 3}]),
    ("tuple_between_multiple_ranges",
     f"a FROM [{T}] WHERE (a, b) BETWEEN ((1, 0) AND (1, 9), "
     "(5, 0) AND (5, 9))", PAIRS, [{"a": 1}, {"a": 5}]),
    ("like_escaped_percent",
     f"k FROM [{T}] WHERE s LIKE '100\\\\%'", ESC, [{"k": 1}]),
    ("like_escaped_underscore",
     f"k FROM [{T}] WHERE s LIKE 'a\\\\_b'", ESC, [{"k": 3}]),
    ("like_unescaped_underscore_wildcards",
     f"k FROM [{T}] WHERE s LIKE 'a_b'", ESC, [{"k": 3}, {"k": 4}]),
    ("like_literal_backslash",
     f"k FROM [{T}] WHERE s LIKE 'back%slash'", ESC, [{"k": 5}]),
    ("div_by_larger", f"b / a AS r FROM [{T}] WHERE a = 2", PAIRS,
     [{"r": 2}]),
    ("mod_sign_follows_dividend", f"(0 - b) % a AS r FROM [{T}] "
     "WHERE a = 2", PAIRS, [{"r": -1}]),
    ("unary_minus_chain", f"0 - (0 - b) AS r FROM [{T}] WHERE a = 1",
     PAIRS, [{"r": 1}]),
    ("bitnot", f"~b AS r FROM [{T}] WHERE a = 1", PAIRS, [{"r": -2}]),
    ("shift_right", f"b >> 1 AS r FROM [{T}] WHERE a = 5", PAIRS,
     [{"r": 4}]),
    ("bit_or_and_xor",
     f"(b | 2) + (b & 2) + (b ^ 2) AS r FROM [{T}] WHERE a = 3",
     PAIRS, [{"r": 4}]),
    ("farm_hash_multiarg_stable",
     f"a FROM [{T}] WHERE farm_hash(a, b) = farm_hash(a, b)", PAIRS,
     [{"a": 1}, {"a": 2}, {"a": 3}, {"a": 4}, {"a": 5}]),
    ("farm_hash_order_sensitive",
     f"a FROM [{T}] WHERE farm_hash(a, b) = farm_hash(b, a) AND a != b",
     PAIRS, []),
    ("min_of_mixed_width",
     f"min_of(a, b, a + b, 100) AS r FROM [{T}] WHERE a = 2", PAIRS,
     [{"r": 2}]),
    ("max_of_negative",
     f"max_of(0 - a, 0 - b) AS r FROM [{T}] WHERE a = 2", PAIRS,
     [{"r": -2}]),
    ("comparison_chain_via_and",
     f"a FROM [{T}] WHERE 1 <= a AND a <= 3 AND b < 3", PAIRS,
     [{"a": 1}, {"a": 3}]),
    ("order_by_two_directions",
     f"a, b FROM [{T}] ORDER BY b ASC, a DESC LIMIT 3", PAIRS,
     [{"a": 4, "b": 1}, {"a": 1, "b": 1}, {"a": 3, "b": 2}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in EXTRA],
                         ids=[c[0] for c in EXTRA])
def test_tuple_predicates_and_arith(query, tables, expected):
    run(query, tables, expected, ordered="ORDER BY" in query)


LIKE_ESCAPE_EDGE = [
    ("like_escaped_backslash_literal",
     f"k FROM [{T}] WHERE s LIKE 'back\\\\\\\\slash'", ESC, [{"k": 5}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in LIKE_ESCAPE_EDGE],
                         ids=[c[0] for c in LIKE_ESCAPE_EDGE])
def test_like_escape_edges(query, tables, expected):
    run(query, tables, expected)


def test_like_invalid_escape_is_a_query_error():
    from ytsaurus_tpu.errors import YtError as _YtError
    with pytest.raises(_YtError):
        evaluate(f"k FROM [{T}] WHERE s LIKE 'a\\\\xb'", ESC)
    with pytest.raises(_YtError):
        evaluate(f"k FROM [{T}] WHERE s LIKE 'trailing\\\\'", ESC)
