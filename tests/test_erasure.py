"""Erasure coding tests (ref model: library/cpp/erasure unittests)."""

import os

import numpy as np
import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.chunks.erasure import get_erasure_codec
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.schema import TableSchema


def test_rs63_roundtrip_no_erasures():
    codec = get_erasure_codec("rs_6_3")
    blob = bytes(range(256)) * 41 + b"tail"
    parts = codec.encode(blob)
    assert len(parts) == 9
    assert codec.decode(parts, len(blob)) == blob


@pytest.mark.parametrize("lost", [
    (0,), (5,), (6,), (8,), (0, 1), (0, 6), (7, 8), (0, 3, 8), (1, 2, 4),
    (6, 7, 8), (0, 1, 2),
])
def test_rs63_repairs_any_three_erasures(lost):
    codec = get_erasure_codec("rs_6_3")
    rng = np.random.default_rng(7)
    blob = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    parts = list(codec.encode(blob))
    for i in lost:
        parts[i] = None
    assert codec.decode(parts, len(blob)) == blob


def test_rs63_four_erasures_fail():
    codec = get_erasure_codec("rs_6_3")
    parts = list(codec.encode(b"x" * 600))
    for i in (0, 2, 6, 8):
        parts[i] = None
    with pytest.raises(YtError):
        codec.decode(parts, 600)


def test_store_erasure_chunk_survives_part_loss(tmp_path):
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("k", "int64"), ("s", "string")])
    chunk = ColumnarChunk.from_rows(
        schema, [(i, f"row-{i}") for i in range(500)])
    cid = store.write_chunk(chunk, erasure="rs_6_3")
    assert store.exists(cid)
    assert store.list_chunks() == [cid]
    # Destroy three arbitrary parts (two data + one parity).
    for i in (1, 4, 7):
        os.unlink(store._part_path(cid, i))
    back = store.read_chunk(cid)
    assert back.to_rows() == chunk.to_rows()
    # Repair-on-read (ISSUE 2): the successful decode rebuilt the lost
    # parts in place, so the chunk is back at full redundancy.
    for i in (1, 4, 7):
        assert os.path.exists(store._part_path(cid, i))
    # Four simultaneous losses exceed rs_6_3's parity: fatal.
    for i in (0, 2, 6, 8):
        os.unlink(store._part_path(cid, i))
    with pytest.raises(YtError):
        store.read_chunk(cid)
    store.remove_chunk(cid)
    assert not store.exists(cid)


def test_small_blob_erasure():
    codec = get_erasure_codec("rs_3_2")
    blob = b"abc"
    parts = list(codec.encode(blob))
    parts[0] = None
    parts[2] = None
    assert codec.decode(parts, 3) == blob


def test_lrc_roundtrip_and_shape():
    codec = get_erasure_codec("lrc_12_2_2")
    assert codec.data_parts == 12 and codec.total_parts == 16
    blob = bytes(range(256)) * 7 + b"tail"
    parts = codec.encode(blob)
    assert len(parts) == 16
    assert codec.decode(parts, len(blob)) == blob
    # Local parity really is the XOR of its group.
    import numpy as np
    group0 = np.frombuffer(parts[0], np.uint8).copy()
    for i in range(1, 6):
        group0 ^= np.frombuffer(parts[i], np.uint8)
    assert group0.tobytes() == parts[12]


def test_lrc_single_erasure_repairs_from_local_group_only():
    """Locality: one lost part rebuilds from its OWN group's 6 surviving
    parts (XOR) — the other group and the global parities may all be
    unavailable.  This is LRC's point: single-failure repair reads 6
    parts, not 12."""
    codec = get_erasure_codec("lrc_12_2_2")
    blob = b"locality-matters" * 37
    encoded = codec.encode(blob)
    parts = list(encoded)
    for i in [2] + list(range(6, 12)) + [13, 14, 15]:
        parts[i] = None
    assert codec.repair_part(parts, 2) == encoded[2]
    # Local parity itself repairs group-locally too.
    parts = list(encoded)
    for i in [12] + list(range(6, 12)) + [13, 14, 15]:
        parts[i] = None
    assert codec.repair_part(parts, 12) == encoded[12]
    # Global parity has no locality: needs a full-rank subset.
    parts = list(encoded)
    parts[14] = None
    assert codec.repair_part(parts, 14) == encoded[14]


def test_lrc_all_three_erasure_patterns_reconstruct():
    from itertools import combinations
    codec = get_erasure_codec("lrc_12_2_2")
    blob = b"every-3-pattern" * 3
    encoded = codec.encode(blob)
    for lost in combinations(range(16), 3):
        parts = [None if i in lost else p for i, p in enumerate(encoded)]
        assert codec.decode(parts, len(blob)) == blob, lost


def test_lrc_four_erasures_mixed_outcomes():
    codec = get_erasure_codec("lrc_12_2_2")
    blob = b"four-erasures" * 11
    encoded = codec.encode(blob)
    # Spread across groups + parities: recoverable.
    parts = [None if i in (0, 7, 12, 14) else p
             for i, p in enumerate(encoded)]
    assert codec.decode(parts, len(blob)) == blob
    # Three data erasures in ONE group plus that group's local parity:
    # only two independent equations (g0, g1) remain for three unknowns.
    parts = [None if i in (0, 1, 2, 12) else p
             for i, p in enumerate(encoded)]
    with pytest.raises(YtError):
        codec.decode(parts, len(blob))


def test_store_lrc_chunk_survives_part_loss(tmp_path):
    import os

    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.schema import TableSchema

    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("a", "int64")])
    chunk = ColumnarChunk.from_rows(schema, [(i,) for i in range(100)])
    cid = store.write_chunk(chunk, erasure="lrc_12_2_2")
    for i in (1, 8, 14):
        os.unlink(store._part_path(cid, i))
    assert store.read_chunk(cid).to_rows() == chunk.to_rows()
